"""Model runner: compiled prefill / decode / embed steps.

The device-side half of the engine (SURVEY §7.1 ``runner.py``). The
reference's equivalent is the remote fleet's decode loop, visible only
through its progress stream (/root/reference/sutro/sdk.py:331-367); here it
is three jitted functions over static shapes:

- ``prefill(ids[1,T])``: full causal attention over one (bucketed) prompt,
  K/V scattered into the paged cache, returns last-position logits.
  Buckets are powers of two, so at most log2(max_ctx) compilations.
- ``decode(ids[B,1])``: one token for every slot in the fixed-size decode
  batch; past gathered from pages, new K/V scattered back, sampling fused
  in (with optional constrained-decoding vocab masks).
- ``embed(ids[B,T])``: trunk + pooled head (last-token for Qwen3-Embedding).

Host-side state (slots, page tables, FSM states) lives in
engine/scheduler.py; this module is stateless apart from params + cache.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models import transformer
from ..models.configs import ModelConfig
from . import faults
from .config import EngineConfig
from .kvcache import KVCache, alloc_cache, write_kv
from ..ops.sampling import NEG_INF, sample, cumulative_logprob


def next_bucket(n: int, lo: int = 16, hi: int = 1 << 20) -> int:
    b = lo
    while b < n and b < hi:
        b *= 2
    return b


class ModelRunner:
    def __init__(
        self,
        mcfg: ModelConfig,
        ecfg: EngineConfig,
        params: Optional[Any] = None,
        *,
        num_pages: Optional[int] = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        shardings: Optional[Any] = None,
    ):
        self.mcfg = mcfg
        self.ecfg = ecfg
        from .config import enable_compile_cache

        enable_compile_cache()
        dtype = jnp.dtype(ecfg.param_dtype)
        if params is None:
            params = transformer.init_params(
                mcfg, jax.random.PRNGKey(ecfg.seed), dtype
            )
        if ecfg.quantize == "int8":
            from ..ops.quant import is_quantized, quantize_params

            if not any(
                is_quantized(x)
                for x in jax.tree_util.tree_leaves(
                    params, is_leaf=is_quantized
                )
            ):
                params = quantize_params(params)
        elif ecfg.quantize:
            raise ValueError(
                f"Unknown quantize mode {ecfg.quantize!r} (only 'int8')"
            )
        # Mesh: explicit > engine-config-resolved > single-device (None).
        if mesh is None:
            from ..parallel.mesh import auto_mesh

            dp, pp, sp, ep, tp = ecfg.resolved_mesh(jax.device_count())
            if dp * pp * sp * ep * tp > 1:
                mesh = auto_mesh(ecfg)
        self.mesh = mesh
        if (
            mesh is not None
            and getattr(ecfg, "kv_quantize", None)
            and int(mesh.shape.get("pipe", 1)) > 1
        ):
            # the pipeline decode path (parallel/pipeline.py) carries
            # bare k/v page pools, no scale pools — quantized KV under
            # pp stays unsupported. Under dp/tp/sp/ep it IS supported:
            # per-token scales are computed over the FULL fused KD axis
            # (a cross-shard reduce under GSPMD), so they are
            # shard-invariant and the scale pools simply replicate.
            import warnings

            warnings.warn(
                "kv_quantize is not supported under pipeline "
                "parallelism; ignoring it for this pp mesh"
            )
            import dataclasses as _dc

            ecfg = self.ecfg = _dc.replace(ecfg, kv_quantize=None)
        # ring-attention sequence parallelism for prefill when the mesh
        # carries a non-trivial "seq" axis (SURVEY §5.7 TPU plan)
        self.sp = int(mesh.shape.get("seq", 1)) if mesh is not None else 1
        # GPipe pipeline stages when the mesh carries a "pipe" axis
        self.pp = int(mesh.shape.get("pipe", 1)) if mesh is not None else 1
        # explicit shard_map EP for MoE MLPs (ops/moe_ep.py). Not under
        # sp/pp: those paths already wrap layers in their own shard_map
        # and nesting is unsupported — they keep GSPMD MoE semantics.
        ep = int(mesh.shape.get("expert", 1)) if mesh is not None else 1
        self.ep_mesh = (
            mesh
            if (ep > 1 and self.sp == 1 and self.pp == 1 and mcfg.moe_experts)
            else None
        )
        if mesh is not None:
            from ..parallel.sharding import param_shardings, cache_shardings

            if shardings is None:
                if self.pp > 1:
                    from ..parallel.pipeline import pp_param_shardings

                    shardings = pp_param_shardings(params, mesh)
                else:
                    shardings = param_shardings(params, mesh)
            params = jax.device_put(params, shardings)
            if self.pp > 1:
                from ..parallel.pipeline import pp_cache_sharding

                self._cache_sharding = pp_cache_sharding(
                    mesh, mcfg.num_kv_heads
                )
            else:
                self._cache_sharding = cache_shardings(
                    mesh, mcfg.num_kv_heads
                )
        else:
            self._cache_sharding = None
            # commit host leaves (checkpoint numpy, host-quantized int8)
            # to the device ONCE — otherwise every jitted dispatch
            # re-uploads them
            params = jax.device_put(params)
        self.params = params
        self.use_pallas = self._resolve_pallas(ecfg)
        # contiguous-KV chunked fetch (PERF.md next-step 1): pages per
        # decode-kernel DMA when a batch's page runs are contiguous
        # (contiguous-first allocators make that the common case).
        # Chip-validated (compiles and beats the per-page walk on v5e:
        # 2521 vs 2430 tok/s on the bench config); default ON, opt out
        # with SUTRO_KV_CHUNK=0.
        from ..ops.pallas_paged import chunk_pages_for

        self.kv_chunk = (
            chunk_pages_for(
                ecfg.kv_page_size,
                ecfg.max_pages_per_seq,
                kv_heads=mcfg.num_kv_heads,
                head_dim=mcfg.head_dim,
                dtype_bytes=(
                    1 if ecfg.kv_quantize == "int8" else dtype.itemsize
                ),
            )
            if self.use_pallas
            and os.environ.get("SUTRO_KV_CHUNK", "1") != "0"
            else 1
        )
        if num_pages is None:
            num_pages = 1 + ecfg.decode_batch_size * ecfg.max_pages_per_seq
            # slack for the final chunk's masked over-read — these pages
            # exist in the pool but are NEVER allocatable (alloc_pages),
            # so a run ending at the allocatable boundary still has
            # kv_chunk-1 valid pages beyond it
            num_pages += self.kv_chunk - 1
        else:
            # Explicit pool size: chunked fetch is only safe with the
            # slack the default sizing adds, so fall back to per-page —
            # SUTRO_KV_CHUNK has no effect for callers that size their
            # own pool (benchmarks/sweep_decode_*.py measure the
            # per-page walk for this reason).
            self.kv_chunk = 1
        self.num_pages = num_pages
        # page count visible to allocators (excludes over-read slack)
        self.alloc_pages = num_pages - (self.kv_chunk - 1)
        self.cache = alloc_cache(mcfg, ecfg, num_pages, dtype=dtype)
        if self._cache_sharding is not None:
            scale_kw = {}
            if self.cache.quantized:
                # per-token scales are shard-invariant (full-KD amax),
                # so the scale pools replicate across the mesh
                from ..parallel.sharding import replicated

                rep = replicated(self.mesh)
                scale_kw = dict(
                    k_scale=jax.device_put(self.cache.k_scale, rep),
                    v_scale=jax.device_put(self.cache.v_scale, rep),
                )
            self.cache = KVCache(
                k_pages=jax.device_put(self.cache.k_pages, self._cache_sharding),
                v_pages=jax.device_put(self.cache.v_pages, self._cache_sharding),
                **scale_kw,
            )

    def device_info(self) -> dict:
        """Device + model facts the bottleneck doctor grades decode
        windows against (engine/roofline.py denominators). Computed
        once per runner — the param-tree walk is not free — and stored
        in each job's flight-recorder attrs."""
        cached = getattr(self, "_device_info", None)
        if cached is not None:
            return cached
        from .roofline import param_bytes_of, param_count_of

        devs = jax.devices()
        info = {
            "device_kind": str(
                getattr(devs[0], "device_kind", "") if devs else ""
            ),
            "n_devices": len(devs),
            "param_bytes": param_bytes_of(self.params),
            "n_params": param_count_of(self.params),
            "num_layers": int(self.mcfg.num_layers),
            "kv_heads": int(self.mcfg.num_kv_heads),
            "head_dim": int(self.mcfg.head_dim),
            "kv_dtype_bytes": (
                1
                if getattr(self.ecfg, "kv_quantize", None) == "int8"
                else jnp.dtype(self.ecfg.activation_dtype).itemsize
            ),
        }
        self._device_info = info
        return info

    @staticmethod
    def _paged(cache: KVCache, page_table):
        """The ``paged_past`` tuple for transformer.forward: 3 elements
        for a bf16 cache, 5 (with per-token dequant scales) for int8."""
        if cache.quantized:
            return (
                cache.k_pages, cache.v_pages,
                cache.k_scale, cache.v_scale, page_table,
            )
        return (cache.k_pages, cache.v_pages, page_table)

    # ------------------------------------------------------------------
    # tiered-KV page migration (engine/kvtier.py)
    # ------------------------------------------------------------------

    def read_pages(self, page_ids) -> dict:
        """Materialized HOST copies of ``page_ids``'s K/V payloads —
        the only device->host read path the tiered pool uses. Shapes:
        ``k``/``v`` ``[L, n, PS, KD]`` in the pool dtype (int8 when the
        pool is quantized, plus ``ks``/``vs`` per-token scales). The
        returned arrays are synchronously fetched, so the caller may
        free/reuse the pages the moment this returns."""
        ids = jnp.asarray(np.asarray(page_ids, np.int32))
        c = self.cache
        out = {
            "k": np.asarray(c.k_pages[:, ids]),
            "v": np.asarray(c.v_pages[:, ids]),
        }
        if c.quantized:
            out["ks"] = np.asarray(c.k_scale[:, ids])
            out["vs"] = np.asarray(c.v_scale[:, ids])
        return out

    @functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
    def _upload_pages_jit(self, cache: KVCache, ids, k, v):
        return KVCache(
            k_pages=cache.k_pages.at[:, ids].set(k),
            v_pages=cache.v_pages.at[:, ids].set(v),
            k_scale=cache.k_scale,
            v_scale=cache.v_scale,
        )

    @functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
    def _upload_pages_q_jit(self, cache: KVCache, ids, k, v, ks, vs):
        return KVCache(
            k_pages=cache.k_pages.at[:, ids].set(k),
            v_pages=cache.v_pages.at[:, ids].set(v),
            k_scale=cache.k_scale.at[:, ids].set(ks),
            v_scale=cache.v_scale.at[:, ids].set(vs),
        )

    def write_pages(self, page_ids, payload: dict) -> None:
        """Upload tier payloads into freshly allocated pages (promotion
        / hibernation resume). ``payload`` is the tier's canonical int8
        form (values + per-token scales) or a raw-dtype payload from
        ``read_pages``; an int8 payload promotes into an unquantized
        pool by dequantizing on the way up (the round-4 int8 bound is
        the parity contract, tests/test_kv_tiers.py)."""
        ids = jnp.asarray(np.asarray(page_ids, np.int32))
        c = self.cache
        if c.quantized:
            self.cache = self._upload_pages_q_jit(
                c, ids,
                jnp.asarray(payload["k"]), jnp.asarray(payload["v"]),
                jnp.asarray(payload["ks"]), jnp.asarray(payload["vs"]),
            )
            return
        pool_dt = c.k_pages.dtype
        if payload["k"].dtype == np.int8:
            from .kvtier import dequantize_payload

            vals = dequantize_payload(payload, np.float32)
        else:
            vals = payload
        self.cache = self._upload_pages_jit(
            c, ids,
            jnp.asarray(vals["k"]).astype(pool_dt),
            jnp.asarray(vals["v"]).astype(pool_dt),
        )

    @staticmethod
    def _resolve_pallas(ecfg: EngineConfig) -> bool:
        if ecfg.use_pallas is not None:
            return ecfg.use_pallas
        return jax.default_backend() not in ("cpu",)

    # ------------------------------------------------------------------
    # prefill
    # ------------------------------------------------------------------

    @functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
    def _prefill_jit(
        self, params, cache: KVCache, ids, valid_len, page_table, start
    ):
        B, T = ids.shape
        positions = start[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
        if self.pp > 1:
            from ..parallel.pipeline import pipeline_forward

            logits, hidden, (k, v) = pipeline_forward(
                self.mcfg, params, ids, positions, valid_len, self.mesh,
                n_microbatches=min(
                    self.ecfg.pp_microbatches or self.pp, B
                ),
                use_pallas=self.use_pallas,
            )
        else:
            logits, hidden, (k, v) = transformer.forward(
                self.mcfg, params, ids, positions, valid_len,
                use_pallas=self.use_pallas,
                ring_mesh=self.mesh if self.sp > 1 else None,
                ep_mesh=self.ep_mesh,
            )
        cache = write_kv(
            cache, k, v, page_table, start, valid_len,
            use_pallas=self.use_pallas,
        )
        last = jnp.maximum(valid_len - 1, 0)
        last_logits = jnp.take_along_axis(
            logits, last[:, None, None], axis=1
        )[:, 0]
        return last_logits, cache

    @functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
    def _prefill_chunk_jit(
        self, params, cache: KVCache, ids, valid_len, page_table, start
    ):
        """One fixed-size chunk of a long prompt: attends over the pages
        written by earlier chunks (past_len = start), scatters its own K/V.
        A single compile serves every chunk of every long prompt."""
        B, C = ids.shape
        positions = start[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
        logits, _, (k, v) = transformer.forward(
            self.mcfg, params, ids, positions, valid_len,
            paged_past=self._paged(cache, page_table),
            past_len=start,
            use_pallas=self.use_pallas,
            ep_mesh=self.ep_mesh,
        )
        cache = write_kv(
            cache, k, v, page_table, start, valid_len,
            use_pallas=self.use_pallas,
        )
        last = jnp.maximum(valid_len - 1, 0)
        last_logits = jnp.take_along_axis(
            logits, last[:, None, None], axis=1
        )[:, 0]
        return last_logits, cache

    def prefill(
        self, token_ids: np.ndarray, page_table: np.ndarray,
        start: int = 0,
    ) -> np.ndarray:
        """One prompt ([T] int32) -> last-position logits [V]. ``page_table``
        is the slot's [MP] row.

        Long prompts (> ``prefill_chunk``) are processed in fixed-size
        chunks so attention transients stay O(chunk x ctx) instead of
        O(T^2) and one compile covers all lengths — except under
        sequence parallelism (sp > 1), where the ring path wants the full
        sequence resident and sharded (ops/ring_attention.py).

        ``start`` > 0 prefills a SUFFIX beginning at that global
        position, attending over pages that already hold positions
        < start (shared-prefix jobs: the common prefix was prefilled
        once into pages at the head of ``page_table``)."""
        if faults.ACTIVE is not None:
            faults.inject("runner.prefill")
        n = len(token_ids)
        C = self.ecfg.prefill_chunk
        # the chunked paged path does not route through the ring (sp) or
        # pipeline (pp) wrappers — guard BEFORE any start>0 branch
        assert start == 0 or (self.sp == 1 and self.pp == 1), (
            "suffix prefill is unsupported under sp/pp"
        )
        if start > 0 and n <= C:
            return self.prefill_batch_at(
                [token_ids], page_table[None, :], [start]
            )[0]
        if (start > 0 or n > C) and self.sp == 1 and self.pp == 1:
            table_dev = jnp.asarray(page_table[None, :], jnp.int32)
            for off in range(0, n, C):
                seg = token_ids[off : off + C]
                ids = np.zeros((1, C), np.int32)
                ids[0, : len(seg)] = seg
                logits, self.cache = self._prefill_chunk_jit(
                    self.params,
                    self.cache,
                    jnp.asarray(ids),
                    jnp.asarray([len(seg)], jnp.int32),
                    table_dev,
                    jnp.asarray([start + off], jnp.int32),
                )
            return np.asarray(logits[0])
        T = next_bucket(max(n, 1), lo=16, hi=self.ecfg.max_context())
        if T % self.sp:  # ring prefill shards T over the seq axis
            T = -(-T // self.sp) * self.sp
        ids = np.zeros((1, T), np.int32)
        ids[0, :n] = token_ids
        logits, self.cache = self._prefill_jit(
            self.params,
            self.cache,
            jnp.asarray(ids),
            jnp.asarray([n], jnp.int32),
            jnp.asarray(page_table[None, :], jnp.int32),
            jnp.asarray([0], jnp.int32),
        )
        return np.asarray(logits[0])

    def prefill_batch(
        self, rows: list, page_tables: np.ndarray
    ) -> np.ndarray:
        """Batched prefill: N prompts ([Ti] int32 each) in ONE device
        program -> last-position logits [N, V]. ``page_tables`` is
        [N, MP]. Rows are padded to a (power-of-two x power-of-two)
        [B, T] bucket so compile count stays O(log^2); padding rows carry
        ``valid_len`` 0 and an all-zero table, so their K/V land on the
        garbage page and their logits are discarded.

        This is the batch-throughput path for classify-style jobs (the
        reference's headline workload, /root/reference/README.md:36-38):
        prefill FLOPs for many short rows ride one MXU dispatch instead
        of one per row."""
        if faults.ACTIVE is not None:
            faults.inject("runner.prefill")
        n = len(rows)
        maxlen = max((len(r) for r in rows), default=1)
        T = next_bucket(max(maxlen, 1), lo=16, hi=self.ecfg.max_context())
        if T % self.sp:
            T = -(-T // self.sp) * self.sp
        B = next_bucket(n, lo=1, hi=1 << 16)
        ids = np.zeros((B, T), np.int32)
        lens = np.zeros((B,), np.int32)
        tables = np.zeros((B, page_tables.shape[1]), np.int32)
        for i, r in enumerate(rows):
            ids[i, : len(r)] = r
            lens[i] = len(r)
            tables[i] = page_tables[i]
        logits, self.cache = self._prefill_jit(
            self.params,
            self.cache,
            jnp.asarray(ids),
            jnp.asarray(lens),
            jnp.asarray(tables),
            jnp.zeros((B,), jnp.int32),
        )
        return np.asarray(logits[:n])

    def prefill_batch_at(
        self, rows: list, page_tables: np.ndarray, starts
    ) -> np.ndarray:
        """Batched SUFFIX prefill: like ``prefill_batch`` but each row
        begins at global position ``starts[i]``, attending over pages
        that already hold its earlier positions — the per-row dispatch
        for shared-prefix jobs (the common prefix occupies the head of
        every row's table; only the suffix rides this program). Padding
        rows carry ``valid_len`` 0, start 0 and an all-zero table, so
        their K/V land on the garbage page."""
        if faults.ACTIVE is not None:
            faults.inject("runner.prefill")
        n = len(rows)
        maxlen = max((len(r) for r in rows), default=1)
        T = next_bucket(max(maxlen, 1), lo=16, hi=self.ecfg.max_context())
        B = next_bucket(n, lo=1, hi=1 << 16)
        ids = np.zeros((B, T), np.int32)
        lens = np.zeros((B,), np.int32)
        st = np.zeros((B,), np.int32)
        tables = np.zeros((B, page_tables.shape[1]), np.int32)
        for i, r in enumerate(rows):
            ids[i, : len(r)] = r
            lens[i] = len(r)
            st[i] = starts[i]
            tables[i] = page_tables[i]
        logits, self.cache = self._prefill_chunk_jit(
            self.params,
            self.cache,
            jnp.asarray(ids),
            jnp.asarray(lens),
            jnp.asarray(tables),
            jnp.asarray(st),
        )
        return np.asarray(logits[:n])

    # ------------------------------------------------------------------
    # decode
    # ------------------------------------------------------------------

    def _trunk_decode(
        self, params, cache: KVCache, ids, positions, past_len,
        page_table, window_past=None, kv_chunk: int = 1, pfx=None,
    ):
        """One decode trunk forward over the paged past — the plain
        scanned forward, or the stage-local pipeline schedule under
        ``pipe > 1`` (parallel/pipeline.pipeline_decode).

        ``pfx`` = tuple of (pages [Pp_g] int32, pfx_len [B] int32)
        groups enabling Hydragen-style split decode over job-shared
        table-head prefixes (ops/attention.py); the prefix cache is
        disabled under pp, so the pipeline path never sees one."""
        B = ids.shape[0]
        ones = jnp.ones((B,), jnp.int32)
        if self.pp > 1:
            from ..parallel.pipeline import pipeline_decode

            return pipeline_decode(
                self.mcfg, params, ids, positions, ones,
                cache.k_pages, cache.v_pages, page_table, past_len,
                self.mesh, use_pallas=self.use_pallas,
                window_past=window_past,
            )
        return transformer.forward(
            self.mcfg, params, ids, positions, ones,
            paged_past=self._paged(cache, page_table),
            past_len=past_len,
            window_past=window_past,
            use_pallas=self.use_pallas,
            kv_chunk=kv_chunk,
            ep_mesh=self.ep_mesh,
            pfx_groups=pfx,
        )

    def _chunk_for_table(self, page_table: np.ndarray) -> int:
        """Static pages-per-DMA for this decode batch: the configured
        chunk when every row's table is one ascending run (zeros after),
        else 1 (per-page walk). At most two kernel specializations."""
        if self.kv_chunk <= 1:
            return 1
        t = np.asarray(page_table)
        if t.ndim == 1:
            t = t[None]
        nxt, prev = t[:, 1:], t[:, :-1]
        if bool(((nxt == prev + 1) | (nxt == 0)).all()):
            return self.kv_chunk
        return 1

    @functools.partial(
        jax.jit, static_argnums=(0, 12), donate_argnums=(2,)
    )
    def _decode_jit(
        self, params, cache: KVCache, ids, past_len, page_table,
        rng, temperature, top_p, top_k, allowed_packed, row_seeds,
        kv_chunk: int = 1, penalties=None, pfx=None,
    ):
        B = ids.shape[0]
        allowed = None
        if allowed_packed is not None:
            # FSM masks travel host->device bit-packed (8x less transfer
            # on the per-step critical path of constrained decoding)
            allowed = jnp.unpackbits(
                allowed_packed, axis=1, count=self.mcfg.vocab_size
            ).astype(bool)
        positions = past_len[:, None]  # current token position == past length
        logits, _, (k, v) = self._trunk_decode(
            params, cache, ids, positions, past_len, page_table,
            kv_chunk=kv_chunk, pfx=pfx,
        )
        cache = write_kv(
            cache, k, v, page_table, past_len, jnp.ones((B,), jnp.int32),
            use_pallas=self.use_pallas,
        )
        step_logits = logits[:, 0]  # [B, V]
        if penalties is not None:
            # pre-applied so the reported logprob is w.r.t. the
            # penalized distribution too (seen-bits arrive packed)
            from ..ops.sampling import apply_penalties

            seen_packed, ids_p, cnt_p, pres, freq, rep = penalties
            seen = jnp.unpackbits(
                seen_packed, axis=1, count=self.mcfg.vocab_size
            ).astype(bool)
            step_logits = apply_penalties(
                step_logits, seen, ids_p, cnt_p, pres, freq, rep
            )
        tok = sample(
            step_logits, rng,
            temperature=temperature, top_p=top_p, top_k=top_k,
            allowed=allowed, row_seeds=row_seeds,
        )
        logp = cumulative_logprob(step_logits, tok)
        return tok, logp, cache

    def decode_step(
        self,
        last_tokens: np.ndarray,     # [B] int32
        past_len: np.ndarray,        # [B] int32
        page_table: np.ndarray,      # [B, MP] int32
        rng: jax.Array,
        temperature: np.ndarray,     # [B]
        top_p: np.ndarray,           # [B]
        top_k: Optional[np.ndarray] = None,     # [B] int32; None => disabled
        allowed: Optional[np.ndarray] = None,   # [B, V] bool
        row_seeds: Optional[np.ndarray] = None,  # [B] int32
        penalties=None,  # (seen_packed [B, ceil(V/8)] uint8, pen_ids
        #                   [B,K], pen_cnt [B,K], presence [B],
        #                   frequency [B], repetition [B]) — seen bits
        #                   arrive PRE-PACKED (scheduler maintains them
        #                   incrementally; no O(B*V) host work here)
        pfx=None,  # tuple of (pages [Pp_g], pfx_len [B]) split-prefix groups
    ) -> Tuple[np.ndarray, np.ndarray]:
        if faults.ACTIVE is not None:
            faults.inject("runner.decode")
        B = len(last_tokens)
        if top_k is None:
            top_k = np.zeros((B,), np.int32)
        if penalties is not None:
            seen_packed, ids_p, cnt_p, pres, freq, rep = penalties
            penalties = (
                jnp.asarray(seen_packed, jnp.uint8),
                jnp.asarray(ids_p, jnp.int32),
                jnp.asarray(cnt_p, jnp.float32),
                jnp.asarray(pres, jnp.float32),
                jnp.asarray(freq, jnp.float32),
                jnp.asarray(rep, jnp.float32),
            )
        tok, logp, self.cache = self._decode_jit(
            self.params,
            self.cache,
            jnp.asarray(last_tokens[:, None], jnp.int32),
            jnp.asarray(past_len, jnp.int32),
            jnp.asarray(page_table, jnp.int32),
            rng,
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(top_p, jnp.float32),
            jnp.asarray(top_k, jnp.int32),
            None
            if allowed is None
            else jnp.asarray(np.packbits(np.asarray(allowed, bool), axis=1)),
            None if row_seeds is None else jnp.asarray(row_seeds, jnp.int32),
            self._chunk_for_table(page_table),
            penalties,
            self._pfx_jnp(pfx),
        )
        return np.asarray(tok), np.asarray(logp)

    @staticmethod
    def _pfx_jnp(pfx):
        if not pfx:
            return None
        return tuple(
            (jnp.asarray(p, jnp.int32), jnp.asarray(n, jnp.int32))
            for p, n in pfx
        )

    # ------------------------------------------------------------------
    # multi-step decode
    # ------------------------------------------------------------------

    @functools.partial(
        jax.jit, static_argnums=(0, 9, 11), donate_argnums=(2,)
    )
    def _decode_multi_jit(
        self, params, cache: KVCache, last, past_len, page_table,
        rng, temperature, top_p, steps: int, top_k,
        kv_chunk: int = 1, pfx=None,
    ):
        """``steps`` decode iterations in ONE device program: the sampled
        token feeds the next step on-device, so the host pays one dispatch
        + one fetch per window instead of per token. This is the
        throughput path for unconstrained generation — constrained rows
        need the host FSM between steps (scheduler falls back to
        single-step).

        The page pool is NOT threaded through the step scan: a carried
        pool would be read (attention) and written (scatter) every
        iteration, and XLA copies the multi-GB buffer pair per step to
        keep that safe — measured ~17 ms/step on v5e vs ~2.6 ms for the
        whole 28-layer trunk. Instead each step's K/V lands in a small
        carried window buffer ([L, B, steps, KVH*Dh] fused, in-place
        dynamic_update_slice) that attention reads alongside the pages,
        and the pool takes ONE bulk write per window out here where
        donation makes it truly in-place."""
        B = last.shape[0]
        toks, logps, wk, wv = self._window_scan(
            params, cache, last, past_len, page_table, rng,
            temperature, top_p, steps, top_k, kv_chunk, pfx=pfx,
        )
        cache = write_kv(
            cache, wk, wv, page_table, past_len,
            jnp.full((B,), steps, jnp.int32),
            use_pallas=self.use_pallas,
        )
        return toks, logps, cache

    def _window_scan(
        self, params, cache: KVCache, last, past_len, page_table,
        rng, temperature, top_p, steps: int, top_k,
        kv_chunk: int = 1, allowed0=None, pfx=None,
    ):
        """The shared fused-window scan: ``steps`` trunk forwards over
        invariant pages + the carried window buffer, sampling on-device.
        Returns (toks [steps, B], logps [steps, B], wk, wv) with the
        window K/V NOT yet committed to pages — callers decide the
        commit (full window for unconstrained decode, verified prefix
        for speculative constrained decode).

        ``allowed0`` ([B, V] bool, optional) masks the FIRST step's
        logits only: a row whose previous window rejected a token takes
        its FSM-masked step INSIDE the next window (crossing the
        scaffold token), so one adversarial row no longer degrades the
        whole batch to masked single-steps."""
        B = last.shape[0]
        L = self.mcfg.num_layers
        KVH, Dh = self.mcfg.num_kv_heads, self.mcfg.head_dim
        KD = KVH * Dh
        # window buffers hold UNQUANTIZED step K/V (they are read by
        # attention before ever touching the pool; write_kv quantizes
        # at commit) — under an int8 pool they stay in compute dtype
        dtype = (
            jnp.dtype(self.ecfg.activation_dtype)
            if cache.quantized
            else cache.k_pages.dtype
        )
        # FUSED trailing axis (like the page pool, kvcache.py): the
        # unfused [.., KVH, Dh] form pads KVH up to a full sublane tile
        # on TPU — a 2x memory expansion on multi-GB buffers at large B
        wk0 = jnp.zeros((L, B, steps, KD), dtype)
        wv0 = jnp.zeros((L, B, steps, KD), dtype)

        def body(carry, step_idx):
            wk, wv, last = carry
            logits, _, (k, v) = self._trunk_decode(
                params, cache, last[:, None],
                (past_len + step_idx)[:, None], past_len, page_table,
                window_past=(wk, wv, step_idx), kv_chunk=kv_chunk,
                pfx=pfx,
            )
            wk = jax.lax.dynamic_update_slice(
                wk, k.astype(dtype).reshape(L, B, 1, KD),
                (0, 0, step_idx, 0),
            )
            wv = jax.lax.dynamic_update_slice(
                wv, v.astype(dtype).reshape(L, B, 1, KD),
                (0, 0, step_idx, 0),
            )
            step_logits = logits[:, 0]
            sample_logits = step_logits
            if allowed0 is not None:
                # masked sample == masked argmax for the greedy rows
                # this path serves; logp stays over the UNMASKED
                # logits — the same convention as the single-step path
                # (sample under the mask, report full-vocab logprob),
                # so cumulative_logprob is path-independent
                sample_logits = jnp.where(
                    step_idx == 0,
                    jnp.where(allowed0, step_logits, NEG_INF),
                    step_logits,
                )
            key = jax.random.fold_in(rng, step_idx)
            tok = sample(
                sample_logits, key,
                temperature=temperature, top_p=top_p, top_k=top_k,
            )
            logp = cumulative_logprob(step_logits, tok)
            return (wk, wv, tok), (tok, logp)

        (wk, wv, _), (toks, logps) = jax.lax.scan(
            body,
            (wk0, wv0, last),
            jnp.arange(steps, dtype=jnp.int32),
        )
        return toks, logps, wk, wv

    def decode_multi(
        self,
        last_tokens: np.ndarray,     # [B] int32
        past_len: np.ndarray,        # [B] int32
        page_table: np.ndarray,      # [B, MP] int32
        rng: jax.Array,
        temperature: np.ndarray,     # [B]
        top_p: np.ndarray,           # [B]
        steps: int,
        top_k: Optional[np.ndarray] = None,
        pfx=None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (tokens [steps, B], logprobs [steps, B])."""
        toks, logps = self.decode_multi_async(
            last_tokens, past_len, page_table, rng, temperature, top_p,
            steps, top_k=top_k, pfx=pfx,
        )
        return np.asarray(toks), np.asarray(logps)

    def decode_multi_async(
        self,
        last_tokens,                 # [B] int32 (numpy OR device array)
        past_len: np.ndarray,        # [B] int32
        page_table: np.ndarray,      # [B, MP] int32
        rng: jax.Array,
        temperature: np.ndarray,     # [B]
        top_p: np.ndarray,           # [B]
        steps: int,
        top_k: Optional[np.ndarray] = None,
        pfx=None,  # tuple of (pages [Pp_g], pfx_len [B]) split-prefix groups
    ) -> Tuple[jax.Array, jax.Array]:
        """Like ``decode_multi`` but returns DEVICE arrays without
        blocking: dispatch is async, so callers can chain the next
        window off ``toks[-1]`` (still on device) before this window's
        results ever cross the host link. That hides the full
        host<->device round trip — the dominant cost when the chip sits
        behind a network tunnel (PERF.md round-2 profile: ~135 ms RTT vs
        ~16 ms device compute per step)."""
        if faults.ACTIVE is not None:
            faults.inject("runner.decode")
        B = past_len.shape[0]
        if top_k is None:
            top_k = np.zeros((B,), np.int32)
        toks, logps, self.cache = self._decode_multi_jit(
            self.params,
            self.cache,
            jnp.asarray(last_tokens, jnp.int32),
            jnp.asarray(past_len, jnp.int32),
            jnp.asarray(page_table, jnp.int32),
            rng,
            jnp.asarray(temperature, jnp.float32),
            jnp.asarray(top_p, jnp.float32),
            steps,
            jnp.asarray(top_k, jnp.int32),
            self._chunk_for_table(page_table),
            self._pfx_jnp(pfx),
        )
        return toks, logps

    # ------------------------------------------------------------------
    # n-gram speculative verification (greedy prompt-lookup decoding)
    # ------------------------------------------------------------------

    def _verify_forward(
        self, params, cache: KVCache, ids, valid_len, page_table, start
    ):
        """Shared verify trunk: one forward over [B, C] known tokens
        against the paged past, K/V written for the inputs, plus the
        plain greedy choice per position. Both verify jits build on
        this so the dispatch wiring cannot drift between them."""
        C = ids.shape[1]
        positions = start[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
        logits, _, (k, v) = transformer.forward(
            self.mcfg, params, ids, positions, valid_len,
            paged_past=self._paged(cache, page_table),
            past_len=start,
            use_pallas=self.use_pallas,
            ep_mesh=self.ep_mesh,
        )
        cache = write_kv(
            cache, k, v, page_table, start, valid_len,
            use_pallas=self.use_pallas,
        )
        lg = logits.astype(jnp.float32)                       # [B, C, V]
        plain = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        plain_lp = jnp.take_along_axis(
            jax.nn.log_softmax(lg, axis=-1), plain[..., None], axis=-1
        )[..., 0]
        return lg, plain, plain_lp, cache

    @functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
    def _verify_cand_jit(
        self, params, cache: KVCache, ids, valid_len, page_table, start,
        cand, cand_n,
    ):
        """Masked-candidate verification (FSM fast-forward over BPE-style
        vocabs): position (b, j)'s choice is the argmax over its SMALL
        candidate id list — exactly the masked-path token, without
        shipping [B, C, V] masks (the candidate operand is [B, C, M]
        ids, ~KBs). Also returns the plain greedy tokens so rows
        without a plan ride the dispatch as ordinary greedy steps.
        Candidate logprobs are w.r.t. the FULL-vocab softmax — the same
        distribution ``cumulative_logprob`` reports on the masked
        single-step path (which samples under the mask but reports
        unmasked logprobs), so a row's cumulative_logprob no longer
        depends on which path committed each token."""
        lg, plain, plain_lp, cache = self._verify_forward(
            params, cache, ids, valid_len, page_table, start
        )
        g = jnp.take_along_axis(lg, cand, axis=2)             # [B, C, M]
        M = cand.shape[2]
        ok = (
            jnp.arange(M, dtype=jnp.int32)[None, None, :]
            < cand_n[..., None]
        )
        g = jnp.where(ok, g, NEG_INF)
        idx = jnp.argmax(g, axis=-1)                          # [B, C]
        ctok = jnp.take_along_axis(cand, idx[..., None], axis=2)[..., 0]
        lse_v = jax.scipy.special.logsumexp(lg, axis=-1)      # [B, C]
        clp = (
            jnp.take_along_axis(lg, ctok[..., None], axis=-1)[..., 0]
            - lse_v
        )
        return ctok.astype(jnp.int32), clp, plain, plain_lp, cache

    def verify_candidates(
        self,
        last_tokens: np.ndarray,   # [B] int32
        drafts: np.ndarray,        # [B, K] int32 (pad anything)
        draft_len: np.ndarray,     # [B] int32
        cand: np.ndarray,          # [B, K+1, M] int32 (pad id 0)
        cand_n: np.ndarray,        # [B, K+1] int32 — 0 = unplanned pos
        past_len: np.ndarray,      # [B] int32
        page_table: np.ndarray,    # [B, MP] int32
    ):
        """Returns (cand_toks, cand_logps, plain_toks, plain_logps),
        each [B, K+1]. Input row b is ``[last, d0..d_{L-1}]`` with
        valid_len L+1 (K/V written for inputs; an accepted output
        token's K/V is written by the next dispatch that consumes it,
        as in verify_greedy)."""
        B, K = drafts.shape
        ids = np.zeros((B, K + 1), np.int32)
        ids[:, 0] = last_tokens
        ids[:, 1:] = drafts
        ct, cl, pt, pl, self.cache = self._verify_cand_jit(
            self.params,
            self.cache,
            jnp.asarray(ids),
            jnp.asarray(draft_len + 1, jnp.int32),
            jnp.asarray(page_table, jnp.int32),
            jnp.asarray(past_len, jnp.int32),
            jnp.asarray(cand, jnp.int32),
            jnp.asarray(cand_n, jnp.int32),
        )
        return (
            np.asarray(ct), np.asarray(cl),
            np.asarray(pt), np.asarray(pl),
        )

    @functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
    def _verify_jit(
        self, params, cache: KVCache, ids, valid_len, page_table, start
    ):
        """One parallel forward over ``[B, 1+K]`` tokens (each row's
        last token + its n-gram draft) against the paged past: returns
        the per-position GREEDY tokens and their logprobs. Device-side
        argmax keeps the [B, C, V] logits tensor off the host link.
        All input positions' K/V are written to pages — rejected
        positions become dead stores beyond the row's accepted ``pos``
        (masked by past_len, overwritten as decode proceeds)."""
        _, toks, logp, cache = self._verify_forward(
            params, cache, ids, valid_len, page_table, start
        )
        return toks, logp, cache

    def verify_greedy(
        self,
        last_tokens: np.ndarray,   # [B] int32
        drafts: np.ndarray,        # [B, K] int32 (pad anything)
        draft_len: np.ndarray,     # [B] int32 — valid draft tokens
        past_len: np.ndarray,      # [B] int32
        page_table: np.ndarray,    # [B, MP] int32
    ):
        """Greedy verification dispatch: row b's inputs are
        ``[last, d0..d_{L-1}]`` (L = draft_len[b]); position t's output
        is the model's next token AFTER input t. The scheduler accepts
        the longest matching draft prefix plus the standard bonus token
        at the first mismatch. Rows with draft_len 0 just take a plain
        greedy step (their padding positions carry valid_len)."""
        B, K = drafts.shape
        ids = np.zeros((B, K + 1), np.int32)
        ids[:, 0] = last_tokens
        ids[:, 1:] = drafts
        toks, logp, self.cache = self._verify_jit(
            self.params,
            self.cache,
            jnp.asarray(ids),
            jnp.asarray(draft_len + 1, jnp.int32),
            jnp.asarray(page_table, jnp.int32),
            jnp.asarray(past_len, jnp.int32),
        )
        return np.asarray(toks), np.asarray(logp)

    @functools.partial(jax.jit, static_argnums=(0,))
    def _merge_last_jit(self, prev_last, refresh_mask, refresh_vals):
        """Device-side merge for pipelined windows: rows whose slot was
        re-admitted between dispatches take their host-known first token;
        everyone else chains the previous window's last sampled token.
        No host sync — all inputs are uploads or device arrays."""
        return jnp.where(refresh_mask, refresh_vals, prev_last)

    def merge_last(self, prev_last, refresh_mask, refresh_vals):
        return self._merge_last_jit(
            prev_last,
            jnp.asarray(refresh_mask, bool),
            jnp.asarray(refresh_vals, jnp.int32),
        )

    # ------------------------------------------------------------------
    # speculative window decode (constrained rows)
    # ------------------------------------------------------------------

    @functools.partial(jax.jit, static_argnums=(0, 8, 11))
    def _decode_window_jit(
        self, params, cache: KVCache, last, past_len, page_table,
        rng, temperature, steps: int, top_p, top_k,
        kv_chunk: int = 1, allowed0=None, pfx=None,
    ):
        """Like ``_decode_multi_jit`` but WITHOUT the page commit: the
        sampled window and its K/V buffers return to the host, which
        verifies constrained rows against their FSMs and commits only
        each row's accepted prefix (``commit_window``). The cache is a
        read-only input here, so a rejected suffix costs nothing."""
        return self._window_scan(
            params, cache, last, past_len, page_table, rng,
            temperature, top_p, steps, top_k, kv_chunk,
            allowed0=allowed0, pfx=pfx,
        )

    @functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
    def _commit_window_jit(
        self, cache: KVCache, wk, wv, page_table, past_len, accepted
    ):
        return write_kv(
            cache, wk, wv, page_table, past_len, accepted,
            use_pallas=self.use_pallas,
        )

    def decode_window(
        self,
        last_tokens: np.ndarray,     # [B] int32
        past_len: np.ndarray,        # [B] int32
        page_table: np.ndarray,      # [B, MP] int32
        rng: jax.Array,
        temperature: np.ndarray,     # [B]
        top_p: np.ndarray,           # [B]
        steps: int,
        top_k: Optional[np.ndarray] = None,
        allowed0: Optional[np.ndarray] = None,  # [B, V] bool, step 0 only
        pfx=None,  # tuple of (pages [Pp_g], pfx_len [B]) split-prefix groups
    ):
        """Speculative window: returns (tokens [steps, B], logprobs
        [steps, B], window_kv handle). Pages are NOT written — call
        ``commit_window(handle, accepted)`` with per-row accepted token
        counts. ``allowed0`` FSM-masks the first step for rows whose
        previous window rejected a token (scheduler per-row recovery)."""
        if faults.ACTIVE is not None:
            faults.inject("runner.decode")
        B = len(last_tokens)
        if top_k is None:
            top_k = np.zeros((B,), np.int32)
        toks, logps, wk, wv = self._decode_window_jit(
            self.params,
            self.cache,
            jnp.asarray(last_tokens, jnp.int32),
            jnp.asarray(past_len, jnp.int32),
            jnp.asarray(page_table, jnp.int32),
            rng,
            jnp.asarray(temperature, jnp.float32),
            steps,
            jnp.asarray(top_p, jnp.float32),
            jnp.asarray(top_k, jnp.int32),
            self._chunk_for_table(page_table),
            None if allowed0 is None else jnp.asarray(allowed0, bool),
            self._pfx_jnp(pfx),
        )
        # copy: callers may pass live views (native runtime) that mutate
        # during host-side verification before commit_window
        handle = (
            wk, wv,
            np.array(past_len, np.int32, copy=True),
            np.array(page_table, np.int32, copy=True),
        )
        return np.asarray(toks), np.asarray(logps), handle

    def commit_window(self, handle, accepted: np.ndarray) -> None:
        """Write each row's accepted window prefix into the page pool."""
        wk, wv, past_len, page_table = handle
        self.cache = self._commit_window_jit(
            self.cache, wk, wv,
            jnp.asarray(page_table, jnp.int32),
            jnp.asarray(past_len, jnp.int32),
            jnp.asarray(accepted, jnp.int32),
        )

    # ------------------------------------------------------------------
    # embeddings
    # ------------------------------------------------------------------

    @functools.partial(jax.jit, static_argnums=(0,))
    def _embed_jit(self, params, ids, valid_len):
        B, T = ids.shape
        positions = jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[None, :], (B, T)
        )
        emb, _, _ = transformer.forward(
            self.mcfg, params, ids, positions, valid_len,
            use_pallas=self.use_pallas,
        )
        return emb

    def embed_batch(self, rows: list) -> np.ndarray:
        """List of token-id arrays -> [N, H] float32 embeddings."""
        if faults.ACTIVE is not None:
            faults.inject("runner.embed")
        n = len(rows)
        maxlen = max((len(r) for r in rows), default=1)
        T = next_bucket(max(maxlen, 1), lo=16, hi=self.ecfg.max_context())
        ids = np.zeros((n, T), np.int32)
        lens = np.zeros((n,), np.int32)
        for i, r in enumerate(rows):
            ids[i, : len(r)] = r
            lens[i] = len(r)
        emb = self._embed_jit(
            self.params, jnp.asarray(ids), jnp.asarray(lens)
        )
        return np.asarray(emb, np.float32)
