"""Deterministic, seeded fault injection + transient-fault retry.

The CRASH_MATRIX era debugged engine failures by hand; this module makes
them injectable, seeded, and assertable in CI (tests/test_chaos.py). A
*fault plan* names engine seams ("sites") and what breaks there; the
engine's job is then to survive what the plan injects — row-level
quarantine, bounded I/O retries with backoff, coordinator liveness —
with every recovery recorded in the job's ``failure_log[]``.

Sites threaded through the engine (see FAILURES.md for the catalog):

====================== ====================================================
site                   where it fires
====================== ====================================================
runner.prefill         ModelRunner prefill dispatch (whole batch)
runner.decode          ModelRunner decode dispatch (whole batch)
runner.embed           ModelRunner embed_batch
row.decode             scheduler token accept, per row (row failure domain)
constrain.compile      lazy-constraint materialization, per row
tokenizer.encode       _GenSession prompt tokenize, per row
jobstore.flush_partial partial-chunk flush (``torn`` writes a torn file)
jobstore.finalize      write_results_streamed
dphost.send            worker result send (``drop`` tears the frame)
dphost.worker_done     worker before its done message (``hang``/``crash``)
dphost.join            elastic worker right after admission — join churn
                       (``crash`` closes the channel first)
dphost.preempt         elastic worker cancel poll: any firing spec requests
                       a preemption drain (``hang`` sleeps first to widen
                       the preempt/steal race); no raise
dphost.steal           elastic coordinator steal planner: a firing spec
                       forces a steal without waiting out
                       SUTRO_DP_STEAL_AFTER; no raise
serving.admit          interactive gateway submit (serving/gateway.py):
                       any raising kind rejects the request with a 503
                       before it touches the scheduler
serving.stream         interactive SSE write loop (server.py), per sent
                       frame: a raising kind mid-stream cancels the
                       request — its slot and KV pages free on the next
                       scheduler iteration, batch jobs unaffected
telemetry.monitor      live SLO monitor (telemetry/monitor.py): fires at
                       the top of every sampler tick AND inside the
                       alert flight-recorder dump (engine/api.py); any
                       raising kind degrades the monitor to disabled —
                       a broken monitor never fails a job
control.admit          control-plane admission (engine/control.py):
                       fires inside every token-bucket draw (batch AND
                       interactive); any raising kind degrades the
                       whole control plane to pass-through — buckets
                       and ladder off, all traffic admitted, a
                       ``control_degraded`` event in the failure logs
                       of in-flight jobs. Never fails a job.
control.actuate        control-plane autotuner (engine/control.py):
                       fires at the top of every monitor-tick
                       actuation; same pass-through degradation as
                       control.admit
prefixstore.lookup     radix prefix-store lookup (scheduler
                       _setup_prefix): any raising kind degrades to a
                       plain cache miss — the job pays full prefill
                       for its shell but NEVER fails, and the store
                       stays live for later jobs
kvtier.demote          tiered-KV demotion (engine/kvtier.py): fires in
                       the synchronous hibernation path AND in the
                       async migration worker. A torn demotion drops
                       the tier entry — the HBM copy (hibernation: the
                       regenerate path) stays authoritative; pages are
                       never freed before the host copy landed
kvtier.promote         tiered-KV promotion (get_page/take_row): a
                       raising kind retries ONCE, then degrades to a
                       miss — the caller re-prefills the tokens it
                       asked for (resume falls back to regenerate)
kvtier.disk_write      host->disk spill (``torn`` lands a truncated
                       npz bundle at its final name, quarantined at
                       read time): the host copy stays authoritative —
                       a failed spill never loses the entry
fleet.probe            fleet router health probe (fleet/health.py), per
                       probe attempt; ``job=`` matches the replica id.
                       A raising kind counts as a probe failure and
                       drives the per-replica circuit breaker —
                       deterministic breaker/flap chaos without killing
                       the replica process
fleet.route            fleet router replica pick (fleet/router.py): a
                       raising kind fails the chosen replica for this
                       request only, forcing the pre-first-token
                       transparent-retry path onto another replica
fleet.replica_crash    replica-side (server.py): at request dispatch
                       AND per streamed frame inside the SSE/progress
                       loops. A firing spec closes the connection
                       abruptly WITHOUT a terminal frame and shuts the
                       HTTP server down — the daemon acts dead, the
                       router's breaker + jobstore failover must absorb
                       it (``job=`` matches the request path / id)
====================== ====================================================

Kinds: ``error`` (RuntimeError), ``oom`` (RESOURCE_EXHAUSTED-shaped
RuntimeError), ``ioerror`` (OSError), ``torn`` (site-cooperative torn
write, then OSError), ``drop`` (site-cooperative torn frame, then
OSError), ``hang`` (sleep ``delay`` seconds), ``crash`` (hard stop —
site closes its channel first).

Activation: per-job via ``EngineConfig.fault_plan`` or the
``SUTRO_FAULT_PLAN`` environment variable. The plan is a compact DSL —
semicolon-separated clauses ``site:kind[:key=value[,key=value...]]`` —
or a JSON list of clause objects. Matchers per clause:

- ``rows=3|7``     only these row ids (pipe-separated)
- ``job=substr``   only jobs whose id contains ``substr``
- ``nth=N``        arm on the N-th matching invocation (1-based)
- ``times=N``      fire at most N times (default: unlimited)
- ``p=0.1``        fire with probability p — DETERMINISTIC, derived from
  (seed, site, invocation count), so a given plan replays identically
- ``delay=S``      sleep length for ``hang`` (default 60)

Seed via a leading ``seed=N;`` clause (default 0). Example::

    SUTRO_FAULT_PLAN='row.decode:error:rows=3;jobstore.flush_partial:ioerror:times=2'

Zero overhead when disabled: every call site guards on the module-global
``ACTIVE is None`` — one load and one comparison, no call.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import math
import os
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)


class InjectedFault(RuntimeError):
    """Base class for injected faults (kind ``error``)."""

    def __init__(self, site: str, kind: str, detail: str = ""):
        self.site = site
        self.kind = kind
        super().__init__(
            f"injected fault at {site} ({kind})"
            + (f": {detail}" if detail else "")
        )


class SimulatedOOM(InjectedFault):
    """Shaped like a device RESOURCE_EXHAUSTED error (kind ``oom``)."""

    def __init__(self, site: str):
        super().__init__(site, "oom", "RESOURCE_EXHAUSTED: simulated "
                         "out of memory allocating device buffer")


class InjectedIOError(OSError):
    """Injected I/O failure (kinds ``ioerror`` / ``torn``) — an OSError
    so the transient-retry policy treats it exactly like a real one."""

    def __init__(self, site: str, kind: str = "ioerror"):
        self.site = site
        self.kind = kind
        super().__init__(f"injected {kind} at {site}")


@dataclasses.dataclass
class FaultSpec:
    """One clause of a fault plan. Mutable counters are plan-locked."""

    site: str
    kind: str = "error"
    rows: Optional[frozenset] = None     # row ids; None = any row
    job: Optional[str] = None            # substring of job id; None = any
    nth: Optional[int] = None            # arm on the nth matching call
    times: float = math.inf              # max fires
    p: float = 1.0                       # deterministic fire probability
    delay: float = 60.0                  # hang duration (seconds)
    # -- counters (guarded by the plan lock) --
    calls: int = 0
    fires: int = 0

    def trigger(self) -> None:
        """Raise (or sleep) for this spec's kind. Sites with
        kind-specific behavior (``torn``, ``drop``, ``crash``) act
        first, then call this for the terminal raise."""
        if self.kind == "hang":
            time.sleep(self.delay)
            return
        if self.kind == "oom":
            raise SimulatedOOM(self.site)
        if self.kind in ("ioerror", "torn"):
            raise InjectedIOError(self.site, self.kind)
        if self.kind == "drop":
            raise InjectedIOError(self.site, "drop")
        raise InjectedFault(self.site, self.kind)


class FaultPlan:
    """A parsed, seeded set of fault specs with deterministic matching."""

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        self.specs = specs
        self.seed = seed
        self._lock = threading.Lock()

    def fire(
        self, site: str, row: Optional[int] = None,
        job: Optional[str] = None,
    ) -> Optional[FaultSpec]:
        """Consume and return the first spec firing at this invocation,
        else None. Deterministic: counters and the seeded probability
        hash are the only state."""
        with self._lock:
            for spec in self.specs:
                if spec.site != site:
                    continue
                if spec.rows is not None and (
                    row is None or int(row) not in spec.rows
                ):
                    continue
                if spec.job is not None and (
                    job is None or spec.job not in str(job)
                ):
                    continue
                spec.calls += 1
                if spec.fires >= spec.times:
                    continue
                if spec.nth is not None and spec.calls < spec.nth:
                    continue
                if spec.p < 1.0:
                    # deterministic per-invocation draw in [0, 1)
                    h = zlib.crc32(
                        f"{self.seed}:{site}:{spec.calls}".encode()
                    )
                    if (h / 2**32) >= spec.p:
                        continue
                spec.fires += 1
                return spec
        return None


# -- plan parsing ------------------------------------------------------


def _parse_clause(d: Dict[str, Any]) -> FaultSpec:
    rows = d.get("rows")
    if isinstance(rows, str):
        rows = [int(x) for x in rows.split("|") if x != ""]
    return FaultSpec(
        site=str(d["site"]),
        kind=str(d.get("kind", "error")),
        rows=frozenset(int(r) for r in rows) if rows is not None else None,
        job=d.get("job"),
        nth=int(d["nth"]) if d.get("nth") is not None else None,
        times=float(d["times"]) if d.get("times") is not None else math.inf,
        p=float(d.get("p", 1.0)),
        delay=float(d.get("delay", 60.0)),
    )


def parse_plan(spec: str) -> FaultPlan:
    """Parse the DSL (or a JSON clause list) into a FaultPlan. Raises
    ValueError on malformed input — a mistyped plan must fail loudly,
    not silently inject nothing."""
    spec = spec.strip()
    if not spec:
        raise ValueError("empty fault plan")
    if spec[0] in "[{":
        data = json.loads(spec)
        if isinstance(data, dict):
            seed = int(data.get("seed", 0))
            clauses = data.get("faults", [])
        else:
            seed, clauses = 0, data
        return FaultPlan([_parse_clause(c) for c in clauses], seed=seed)
    seed = 0
    specs: List[FaultSpec] = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if clause.startswith("seed="):
            seed = int(clause[len("seed="):])
            continue
        parts = clause.split(":")
        if len(parts) > 3:
            raise ValueError(f"malformed fault clause {clause!r}")
        d: Dict[str, Any] = {"site": parts[0].strip()}
        if len(parts) > 1:
            d["kind"] = parts[1].strip()
        if len(parts) > 2:
            for kv in parts[2].split(","):
                if not kv.strip():
                    continue
                k, _, v = kv.partition("=")
                if not _:
                    raise ValueError(
                        f"malformed fault option {kv!r} in {clause!r}"
                    )
                d[k.strip()] = v.strip()
        specs.append(_parse_clause(d))
    return FaultPlan(specs, seed=seed)


# -- module-global activation ------------------------------------------
#
# ACTIVE is the single hot-path switch: call sites guard with
# ``if faults.ACTIVE is not None`` so the disabled engine pays one
# global load + comparison per site, nothing else.

ACTIVE: Optional[FaultPlan] = None


def install(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    global ACTIVE
    ACTIVE = plan
    return plan


def clear() -> None:
    install(None)


def configure(spec: Optional[str] = None) -> Optional[FaultPlan]:
    """Activate (or clear) the process fault plan: explicit ``spec``
    wins, else ``SUTRO_FAULT_PLAN``, else disabled. Called by
    LocalEngine at construction so per-job activation is just 'build
    the engine with EngineConfig.fault_plan set'."""
    if spec is None:
        spec = os.environ.get("SUTRO_FAULT_PLAN")
    if not spec:
        return install(None)
    plan = parse_plan(spec)
    logger.warning(
        "fault injection ACTIVE: %d clause(s), seed=%d",
        len(plan.specs), plan.seed,
    )
    return install(plan)


def fire(
    site: str, row: Optional[int] = None, job: Optional[str] = None
) -> Optional[FaultSpec]:
    """Consume a matching spec without raising — for sites that act on
    the kind themselves (torn writes, frame drops) before triggering."""
    plan = ACTIVE
    if plan is None:
        return None
    spec = plan.fire(site, row=row, job=job)
    if spec is not None:
        # count OUTSIDE the plan lock; imported lazily so the zero-
        # overhead guarantee for plan-off engines never pays an import
        from .. import telemetry

        if telemetry.ENABLED:
            telemetry.FAULTS_INJECTED_TOTAL.inc(1.0, site)
    return spec


def inject(
    site: str, row: Optional[int] = None, job: Optional[str] = None
) -> None:
    """Fire-and-raise helper for sites with no kind-specific behavior."""
    spec = fire(site, row=row, job=job)
    if spec is not None:
        spec.trigger()


# -- transient-fault retry policy --------------------------------------


def backoff_delay(
    attempt: int, base: float, cap: float, key: str = ""
) -> float:
    """Exponential backoff with deterministic jitter: base * 2^attempt
    capped at ``cap``, scaled by a [0.5, 1.5) factor derived from
    (key, attempt) — reproducible runs, no thundering herd."""
    delay = min(base * (2.0 ** attempt), cap)
    jitter = zlib.crc32(f"{key}:{attempt}".encode()) / 2**32
    return delay * (0.5 + jitter)


def retry_transient(
    fn: Callable[[], Any],
    *,
    attempts: int = 4,
    base: float = 0.05,
    cap: float = 2.0,
    retry_on: Tuple[type, ...] = (OSError,),
    on_retry: Optional[Callable[[int, float, BaseException], None]] = None,
    what: str = "operation",
) -> Any:
    """Run ``fn`` with BOUNDED retries and exponential backoff + jitter
    on transient failures. ``on_retry(attempt, delay, exc)`` fires
    before each sleep (the failure_log hook). The final failure
    re-raises — a persistent fault stays a fault, just a slower one."""
    attempts = max(1, int(attempts))
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if attempt + 1 >= attempts:
                raise
            from .. import telemetry

            if telemetry.ENABLED:
                # label = the operation class, never the bracketed job
                # id (fixed cardinality)
                telemetry.IO_RETRIES_TOTAL.inc(
                    1.0, what.split("[", 1)[0]
                )
            delay = backoff_delay(attempt, base, cap, key=what)
            if on_retry is not None:
                try:
                    on_retry(attempt + 1, delay, e)
                except Exception:
                    logger.warning(
                        "retry observer failed for %s", what, exc_info=True
                    )
            logger.warning(
                "%s failed (attempt %d/%d, retrying in %.3fs): %s",
                what, attempt + 1, attempts, delay, e,
            )
            time.sleep(delay)
    raise AssertionError("unreachable")  # attempts >= 1 always returns/raises
