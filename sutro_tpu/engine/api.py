"""LocalEngine: the in-process replacement for api.sutro.sh.

Implements the reference's wire contract (SURVEY §3.6) as direct calls — the
service behind ``POST /batch-inference``, ``GET /stream-job-progress``,
``POST /job-results``, etc. becomes an in-process object the SDK dispatches
to when ``backend="tpu"`` (the default).

Threading model: one worker thread drains a priority queue of jobs
(priority, then submit order — reference ``job_priority`` semantics,
interfaces.py:45 / README two-priority model). The worker is the single
writer for running jobs (jobstore invariant). Cancellation is a flag the
scheduler polls between decode steps. Detach/attach works because the job
runs in this background thread while the SDK returns; progress replays
through the metrics bus, and results/status are durable in the jobstore, so
a *new* process can still see and resume finished/partial work
(row-granular resume per SURVEY §5.3).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import warnings
import traceback
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

from .. import telemetry
from ..telemetry import monitor as tmonitor
from ..common import MODEL_CATALOG
from ..interfaces import JobStatus
from ..models.configs import MODEL_CONFIGS, ModelConfig
from . import faults
from .config import EngineConfig, load_engine_config
from .datasets import DatasetStore
from .jobstore import JobRecord, JobStore, estimate_cost
from .metrics import MetricsBus, Throughput
from .runner import ModelRunner
from .scheduler import ContinuousBatcher, GenRequest, GenResult
from .tokenizer import BaseTokenizer, load_tokenizer

_PARTIAL_FLUSH_EVERY = 256

# close() sentinel: sorts ahead of every real queue entry (priorities
# are small non-negative ints), and its job_id None is never compared
# because the (priority, seq) prefix is unique
_WORKER_STOP = (-(1 << 60), -1, None)


def _read_url_rows(url: str, column: "str | None") -> list:
    """Resolve an http(s) parquet/csv URL into a row list (the engine-side
    half of prepare_input_data's URL pass-through, common.py)."""
    import pandas as pd

    try:
        if url.split("?")[0].endswith((".csv", ".csv.gz")):
            df = pd.read_csv(url)
        else:
            df = pd.read_parquet(url)
    except Exception as e:
        raise ValueError(f"Could not fetch input URL {url!r}: {e}") from e
    if column is None:
        if len(df.columns) != 1:
            raise ValueError(
                f"URL input has columns {list(df.columns)}; pass `column` "
                "to select one"
            )
        column = df.columns[0]
    if column not in df.columns:
        raise ValueError(
            f"URL input has no column {column!r} (has {list(df.columns)})"
        )
    return df[column].astype(str).tolist()


def resolve_model(model: str) -> Tuple[str, ModelConfig, Dict[str, Any]]:
    """Public model name (or raw engine key) -> (engine_key, config, meta)."""
    meta = MODEL_CATALOG.get(model)
    if meta is not None:
        key = meta["engine_key"]
    elif model in MODEL_CONFIGS:
        key, meta = model, {"engine_key": model, "thinking": False,
                            "embedding": MODEL_CONFIGS[model].head == "embedding"}
    else:
        raise ValueError(
            f"Unknown model {model!r}. Catalog: {sorted(MODEL_CATALOG)} "
            f"(or an engine key from models.configs.MODEL_CONFIGS)"
        )
    return key, MODEL_CONFIGS[key], meta


class LocalEngine:
    def __init__(self, ecfg: Optional[EngineConfig] = None):
        self.ecfg = ecfg or load_engine_config()
        # per-job fault-injection activation (EngineConfig.fault_plan or
        # SUTRO_FAULT_PLAN; None clears — a fresh engine with no plan
        # runs injection-free at zero overhead)
        faults.configure(self.ecfg.fault_plan)
        # dp channel liveness knobs promoted from env-only to
        # EngineConfig (validated >= 0 here; the SUTRO_DP_* environment
        # variables still override when set)
        from .dphost import configure_channel

        configure_channel(
            stall_timeout=self.ecfg.dp_stall_timeout,
            heartbeat=self.ecfg.dp_heartbeat,
        )
        self.jobs = JobStore(
            io_retries=self.ecfg.io_retries,
            io_backoff=self.ecfg.io_backoff_base,
            io_backoff_cap=self.ecfg.io_backoff_cap,
        )
        self.metrics = MetricsBus()
        self.datasets = DatasetStore()
        self._queue: "queue.PriorityQueue" = queue.PriorityQueue()
        self._seq = 0
        self._cancel: set = set()
        self._queued: set = set()
        self._queued_prio: Dict[str, int] = {}  # queued job -> priority
        self._current_job: Optional[str] = None
        # jobs pulled out of the queue into the RUNNING co-batched
        # session (cross-job co-batching) — busy for resume purposes
        self._attached: set = set()
        # job_id -> (attach engine key | None,) — immutable verdicts
        # cached so the scheduler-cadence queue scans don't re-read
        # job records from disk
        self._attach_info: Dict[str, tuple] = {}
        self._lock = threading.Lock()
        self._runner_cache: Dict[str, Tuple[ModelRunner, BaseTokenizer]] = {}
        self._tok_cache: Dict[str, BaseTokenizer] = {}
        # Engine-lifetime radix prefix stores, one per resident runner
        # (engine/prefixstore.py): keep template-shell KV pages warm
        # ACROSS batcher sessions so repeat jobs/requests prefill only
        # their novel tails. Keyed alongside _runner_cache because the
        # pages live in that runner's KV pool — evicting the runner
        # closes its store.
        self._prefix_stores: Dict[str, Any] = {}
        # Tiered KV pools (engine/kvtier.py): host/disk backing for the
        # runner's paged-KV HBM pool — cold prefix-store leaves demote
        # instead of dropping, preempted rows hibernate for page-upload
        # resume. Same lifetime story as _prefix_stores.
        self._kv_tiers: Dict[str, Any] = {}
        # Interactive serving tier: constructed ONLY when the reserved
        # slot budget is on — at the default 0 the serving package is
        # never imported and every batch code path is unchanged.
        self.gateway = None
        if getattr(self.ecfg, "interactive_slots", 0) > 0:
            from ..serving.gateway import InteractiveGateway

            self.gateway = InteractiveGateway(self)
        # Live SLO monitor (telemetry/monitor.py): per-engine sampler —
        # NOT a package singleton, so parallel test engines don't share
        # alert state. Constructed only when telemetry AND the monitor
        # switch are on; with either off, zero threads and zero work.
        self.monitor = None
        if telemetry.ENABLED and tmonitor.monitor_enabled():
            self.monitor = tmonitor.Monitor(
                jobs_provider=self._monitor_jobs,
                alert_dump=self._monitor_alert_dump,
            ).start()
        # SLO enforcement control plane (engine/control.py): per-tenant
        # admission buckets + preemptive priority ladder + closed-loop
        # autotuner. Constructed ONLY when SUTRO_CONTROL /
        # EngineConfig.control resolves on — at the default None every
        # hot path is an is-None check and batch results are
        # bit-identical. A construction failure means OFF, never a
        # broken engine.
        self.control = None
        from . import control as _control

        _spec = _control.resolve_spec(getattr(self.ecfg, "control", None))
        if _spec is not None:
            try:
                self.control = _control.ControlPlane(
                    _spec,
                    ecfg=self.ecfg,
                    jobs=self.jobs,
                    jobs_provider=self._monitor_jobs,
                    tier_pools=self._live_kv_tiers,
                )
                # terminal accounting refunds the unused reserve
                self.jobs.on_terminal = self.control.on_terminal
                # the autotuner closes the loop off the monitor's tick
                if self.monitor is not None:
                    self.monitor.on_tick = self.control.on_monitor_tick
            except Exception:  # noqa: BLE001 — enforcement is opt-in
                # armor, never a reason the engine fails to come up
                logger.warning(
                    "control plane failed to construct — running "
                    "without enforcement", exc_info=True,
                )
                self.control = None
        self._worker = threading.Thread(
            target=self._worker_loop, daemon=True, name="sutro-engine"
        )
        self._worker.start()

    # ------------------------------------------------------------------
    # Public API (the §3.6 endpoints, as methods)
    # ------------------------------------------------------------------

    def submit_batch_inference(self, payload: Dict[str, Any]) -> str:
        """POST /batch-inference equivalent. Returns job_id (for dry runs the
        job completes immediately with a cost_estimate in its record)."""
        model = payload.get("model", "qwen-3-4b")
        engine_key, mcfg, meta = resolve_model(model)
        inputs = payload["inputs"]
        if isinstance(inputs, str) and inputs.startswith("dataset-"):
            inputs = self.datasets.read_rows(
                inputs, column=payload.get("column")
            )
        elif isinstance(inputs, str) and inputs.startswith(
            ("http://", "https://")
        ):
            # prepare_input_data passes URLs through for engine-side
            # resolution (reference sdk accepts parquet/csv URLs)
            inputs = _read_url_rows(inputs, payload.get("column"))
        if not isinstance(inputs, list):
            raise ValueError(
                "inputs must be a list of strings, a dataset-<id>, or an "
                "http(s) URL to a parquet/csv file"
            )
        inputs = [str(x) for x in inputs]

        sampling = dict(payload.get("sampling_params") or {})
        sampling.setdefault("max_new_tokens", self.ecfg.max_new_tokens)
        if payload.get("output_schema"):
            # The schema guarantee ("output_schema => complete JSON")
            # must stay feasible: raise the row cap to the schema's
            # shortest accepting output BEFORE quota/cost accounting, so
            # the effective cap is what gets admitted, estimated, and
            # persisted. Schema compile errors surface when the job runs.
            try:
                from .constrain import schema_constraint_factory
                from .constrain.fsm import constraint_room

                probe = schema_constraint_factory(
                    payload["output_schema"],
                    self._get_tokenizer(engine_key, mcfg),
                )()
                # same room rule the scheduler's truncation reserve uses
                room = constraint_room(probe)
                if int(sampling["max_new_tokens"]) < room:
                    sampling["max_new_tokens"] = room
            except Exception:
                # deliberate: a schema that fails to compile here fails
                # the JOB with a real error when it runs; the submit
                # path only loses the feasibility cap raise
                logger.debug(
                    "schema feasibility probe failed at submit; "
                    "surfacing when the job runs",
                    exc_info=True,
                )
        tenant = str(payload.get("tenant") or "default").strip() or "default"
        # PAPER.md semantics: job_priority indexes the quota table, so
        # an out-of-range value is a structured caller error
        # (jobstore.InvalidPriority -> HTTP 400) BEFORE any record
        # exists — never silently clamped into another level's quota
        # and queue position
        job_priority = self.jobs.validate_priority(
            payload.get("job_priority", 0)
        )
        # Stage-graph jobs (engine/stagegraph.py): validate the DAG
        # BEFORE any record exists — a cyclic or dangling-edge graph is
        # a structured InvalidGraph -> HTTP 400, mirroring the
        # InvalidPriority contract above. A stage-less payload takes
        # none of these branches (off switch: byte-identical wire,
        # bit-identical results).
        graph = None
        if payload.get("stages") is not None:
            from .stagegraph import graph_cost_bounds, initial_stages_state
            from .stagegraph import parse_graph

            graph = parse_graph(
                payload["stages"], default_model=model,
                resolve=resolve_model,
            )
        rec = self.jobs.create(
            name=payload.get("name"),
            description=payload.get("description"),
            model=model,
            engine_key=engine_key,
            num_rows=len(inputs),
            job_priority=job_priority,
            output_schema=payload.get("output_schema"),
            system_prompt=payload.get("system_prompt"),
            sampling_params=sampling,
            truncate_rows=bool(payload.get("truncate_rows", True)),
            dry_run=bool(payload.get("dry_run", False)),
            random_seed_per_input=bool(
                payload.get("random_seed_per_input", False)
            ),
            tenant=tenant,
            stages=graph.to_payload() if graph is not None else None,
            stages_state=(
                initial_stages_state(graph, len(inputs))
                if graph is not None else None
            ),
        )
        if telemetry.ENABLED:
            # tenant attribution starts at submit: the identity rides
            # the job's telemetry attrs (flight-recorder dumps carry
            # it) and the capped tenant series (registry collapses an
            # abusive id space into "_overflow")
            telemetry.job(rec.job_id).attrs["tenant"] = tenant
            telemetry.TENANT_REQUESTS_TOTAL.inc(1.0, tenant, "batch")
        self.jobs.write_inputs(rec.job_id, inputs)

        # Quota gate (reference /get-quotas semantics). Token honesty
        # without tokenizing every submit: a BPE token consumes >= 1
        # UTF-8 byte, so byte length is a sound upper bound — jobs whose
        # bound fits the quota pass immediately; only jobs near the
        # quota pay exact tokenize-and-count (SURVEY §7.3 cost-model
        # honesty; the old chars//3 heuristic undercounted CJK ~3x).
        max_new_total = len(inputs) * int(sampling["max_new_tokens"])
        overhead = len(
            (rec.system_prompt or "").encode("utf-8")
        ) + 64  # per-row chat-template + system-prompt bound
        if graph is not None:
            # price the WHOLE DAG at submit: downstream map stages add
            # their own input (bounded by upstream max_new + template
            # overhead) and output tokens to the quota/admission draw
            extra_in, extra_new = graph_cost_bounds(
                graph, len(inputs), int(sampling["max_new_tokens"])
            )
            max_new_total += extra_new
            overhead_extra = extra_in
        else:
            overhead_extra = 0
        bound = (
            sum(len(r.encode("utf-8")) for r in inputs)
            + len(inputs) * overhead
            + max_new_total
            + overhead_extra
        )
        # row quota first on its own: tokenizing cannot change a
        # row-count failure, so never pay the exact pass for one
        quota_err = self.jobs.check_quota(rec.job_priority, len(inputs), 0)
        if quota_err is None:
            quota_err = self.jobs.check_quota(rec.job_priority, 0, bound)
            if quota_err:
                from .tokenizer import encode_chat_batch

                tok = self._get_tokenizer(engine_key, mcfg)
                exact = (
                    sum(
                        len(ids)
                        for ids in encode_chat_batch(
                            tok,
                            inputs,
                            rec.system_prompt,
                            mcfg.chat_template,
                            threads=self.ecfg.tokenize_threads,
                        )
                    )
                    + max_new_total
                    + overhead_extra  # downstream stage inputs: bound only
                )
                quota_err = self.jobs.check_quota(
                    rec.job_priority, 0, exact
                )
        if quota_err:
            self.jobs.append_failure_log(
                rec.job_id, {"event": "job_failed", "error": quota_err}
            )
            self.jobs.set_status(
                rec.job_id,
                JobStatus.FAILED,
                failure_reason={"message": quota_err},
            )
            return rec.job_id

        # Control-plane admission (engine/control.py): the per-SUBMIT
        # quota above is a size cap; this is the per-tenant sustained
        # RATE — a token-bucket draw with bounded-wait backpressure.
        # Dry runs cost nothing real and skip the draw.
        if self.control is not None and not rec.dry_run:
            admit_err = self.control.admit_batch(
                tenant, rec.job_priority, len(inputs), float(bound),
                job_id=rec.job_id,
            )
            if admit_err:
                self.jobs.append_failure_log(
                    rec.job_id,
                    {"event": "admission_rejected", "error": admit_err},
                )
                self.jobs.set_status(
                    rec.job_id,
                    JobStatus.FAILED,
                    failure_reason={
                        "message": admit_err,
                        "code": "QUOTA_EXCEEDED",
                    },
                )
                return rec.job_id

        self._enqueue(rec.job_priority, rec.job_id)
        return rec.job_id

    def _higher_priority_waiting(self, my_priority: int) -> bool:
        """True when a strictly-higher-priority (lower number) job sits
        in the queue — the preemption predicate. Interactive jobs
        preempt the running batch at decode-step granularity (reference
        two-priority model, README.md:168-171): the running batcher
        yields, requeues itself at its original priority, and resumes
        row-granularly after the higher-priority job drains. Reading the
        queued-priority map under the lock (rather than flagging the
        current job at submit time) makes preemption race-free against
        the worker's pop/requeue windows."""
        with self._lock:
            return any(
                p < my_priority for p in self._queued_prio.values()
            )

    def _attach_key(self, jid: str) -> Optional[str]:
        """The engine key a queued job would attach under, or None when
        it can never attach (different head, dry run, unresolvable).
        Cached: the verdict is immutable per job, and this runs on the
        scheduler loop's cadence — it must not re-read job records from
        disk every decode window."""
        if jid.startswith("serve:"):
            # serving-wake sentinel (_enqueue_serving): attaches to a
            # same-key session; for any other session it reads as an
            # unattachable higher-priority entry, forcing the yield that
            # gets the interactive request onto the device
            return jid[6:]
        cached = self._attach_info.get(jid)
        if cached is not None:
            return cached[0]
        try:
            rec = self.jobs.get(jid)
            key, mcfg, _meta = resolve_model(rec.model)
            info = (
                None
                if (rec.dry_run or mcfg.head == "embedding")
                else key,
            )
        except Exception:
            info = (None,)
        if len(self._attach_info) > 4096:  # bound a long-lived daemon
            self._attach_info.clear()
        self._attach_info[jid] = info
        return info[0]

    def _unattachable_higher_waiting(
        self, my_priority: int, engine_key: str
    ) -> bool:
        """Preemption predicate for a CO-BATCHED generation session: a
        strictly-higher-priority queued job forces a yield ONLY when it
        cannot simply attach to the running session (different model,
        embedding head, or dry run). Same-model generation jobs ride
        free slots with priority-ordered admission instead — interactive
        latency without preempting the batch's active rows."""
        with self._lock:
            items = [
                (j, p)
                for j, p in self._queued_prio.items()
                if p < my_priority
            ]
        for jid, _p in items:
            if jid in self._cancel:
                continue  # will be discarded at pop, not run
            if self._attach_key(jid) != engine_key:
                return True
        return False

    def _pop_attachable(self, engine_key: str):
        """Remove and return ``(job_id, seq)`` for the NEXT queued
        generation job that can join the running co-batched session
        (same engine model, not embedding, not a dry run), or None.

        FIFO fairness: the scan walks the queue in (priority, seq)
        order and STOPS at the first unattachable entry — a same-model
        job submitted after a different-model job must not jump it
        indefinitely (the old strict queue order is preserved across
        models; only jobs ahead of every unattachable entry attach).

        Safe against the worker's own queue use: only the worker thread
        calls this (from inside the session it is running), so there is
        no concurrent ``get``; submitters' ``put`` calls serialize on
        the queue mutex."""
        import heapq

        with self._queue.mutex:
            cands = sorted(self._queue.queue)
        for item in cands:
            _prio, seq, jid = item
            if jid is None:
                # _WORKER_STOP sentinel (sorts first): the daemon is
                # closing — a live session must stop adopting new jobs,
                # not crash mid-drain on the sentinel's None job id
                break
            if self._attach_key(jid) != engine_key:
                if jid in self._cancel:
                    continue  # discarded at pop — doesn't hold a turn
                break  # FIFO: don't attach past an unattachable job
            with self._queue.mutex:
                try:
                    self._queue.queue.remove(item)
                except ValueError:
                    continue  # taken since the snapshot
                heapq.heapify(self._queue.queue)
            with self._lock:
                self._queued.discard(jid)
                self._queued_prio.pop(jid, None)
            self._attach_info.pop(jid, None)
            if jid.startswith("serve:"):
                # same-key serving sentinel: the running session polls
                # the gateway directly (poll_new), so the wake-up is
                # already served — consume it and keep scanning
                if self.gateway is not None:
                    self.gateway.sentinel_popped(engine_key)
                continue
            if jid in self._cancel:
                # mirrors the worker-pop cancel check
                self.jobs.set_status(jid, JobStatus.CANCELLED)
                continue
            return jid, seq
        return None

    def _reserve_queue_entry(self, priority: int, job_id: str) -> int:
        """Caller must hold ``self._lock``. Registers the job as queued
        and returns its FIFO sequence number; the caller must follow up
        with ``self._queue.put((priority, seq, job_id))`` (possibly
        after releasing the lock) or roll back by discarding the id from
        ``self._queued`` and ``self._queued_prio``."""
        self._seq += 1
        self._queued.add(job_id)
        self._queued_prio[job_id] = priority
        return self._seq

    def _enqueue(self, priority: int, job_id: str) -> None:
        with self._lock:
            seq = self._reserve_queue_entry(priority, job_id)
            self._queue.put((priority, seq, job_id))

    def _enqueue_serving(self, engine_key: str) -> None:
        """Wake the worker for a parked interactive request: a
        ``serve:<engine_key>`` sentinel at priority -1 — ahead of every
        batch priority (all non-negative), so an idle worker starts a
        serving session immediately and a busy different-model session
        sees an unattachable higher entry and yields."""
        self._enqueue(-1, f"serve:{engine_key}")

    def job_status(self, job_id: str) -> str:
        return self.jobs.status(job_id).value

    def get_job(self, job_id: str) -> Dict[str, Any]:
        d = self.jobs.get(job_id).to_dict()
        # surfaced so clients (``sutro jobs status``) can hint at the
        # flight-recorder dump without fetching the whole document
        d["has_telemetry_dump"] = (
            self.jobs._dir(job_id) / "telemetry.json"
        ).exists()
        return d

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self.jobs.list_jobs()

    def cancel_job(self, job_id: str) -> Dict[str, Any]:
        status = self.jobs.status(job_id)
        if status.is_terminal():
            return {"status": status.value}
        # monotonic one-way flag: GIL-atomic set membership, polled by
        # the worker at pop/row boundaries; staleness is bounded by the
        # next poll and the flag never un-sets while a job is live
        self._cancel.add(job_id)  # graftlint: disable=shared-state-unlocked
        if status == JobStatus.QUEUED:
            self.jobs.set_status(job_id, JobStatus.CANCELLED)
            return {"status": JobStatus.CANCELLED.value}
        self.jobs.set_status(job_id, JobStatus.CANCELLING)
        return {"status": JobStatus.CANCELLING.value}

    def job_results(
        self,
        job_id: str,
        include_inputs: bool = False,
        include_cumulative_logprobs: bool = False,
    ) -> Dict[str, Any]:
        """POST /job-results equivalent: {outputs[, inputs,
        cumulative_logprobs]} aligned 1:1 with inputs, order-preserving."""
        df = self.jobs.read_results(job_id)
        if not df["row_id"].is_monotonic_increasing:
            df = df.sort_values("row_id")  # streamed results are
            #                                already row-ordered
        out: Dict[str, Any] = {"outputs": df["outputs"].tolist()}
        if "error" in df.columns and df["error"].notna().any():
            # quarantined rows (row-level failure domain): 1:1 with
            # outputs, None for clean rows
            out["errors"] = [
                None if v is None or (isinstance(v, float) and v != v)
                else str(v)
                for v in df["error"].tolist()
            ]
        if include_inputs:
            out["inputs"] = self.jobs.read_inputs(job_id)
        if include_cumulative_logprobs and "cumulative_logprobs" in df:
            out["cumulative_logprobs"] = df["cumulative_logprobs"].tolist()
            if "gen_tokens" in df:  # sampled-token counts per row
                out["gen_tokens"] = [
                    int(x) for x in df["gen_tokens"].fillna(0)
                ]
        return out

    def stream_job_progress(self, job_id: str) -> Iterator[Dict[str, Any]]:
        """GET /stream-job-progress equivalent (NDJSON update dicts)."""
        status = self.jobs.status(job_id)
        jm = self.metrics.job(job_id)
        if status.is_terminal():
            rec = self.jobs.get(job_id)
            yield {"update_type": "progress", "result": rec.num_rows
                   if status == JobStatus.SUCCEEDED else jm.rows_completed}
            return
        yield from jm.subscribe()

    def resume_job(self, job_id: str) -> Dict[str, Any]:
        """Row-granular resume (SURVEY §5.3): re-queue a FAILED/CANCELLED
        job — or one left RUNNING/STARTING by a dead engine process. Rows
        already flushed to the partial store are not recomputed
        (_run_job reads them back and skips)."""
        import time as _time

        status = self.jobs.status(job_id)
        deadline = _time.monotonic() + 5.0
        while True:
            # Atomic not-busy check AND re-queue under ONE lock hold:
            # two concurrent resume calls must not both observe not-busy
            # and double-enqueue the job (it would run twice).
            with self._lock:
                busy = (
                    job_id in self._queued
                    or job_id == self._current_job
                    or job_id in self._attached
                )
                if not busy:
                    # re-read status under the lock: a stale pre-lock
                    # read could race job completion and re-run a
                    # SUCCEEDED job
                    status = self.jobs.status(job_id)
                    if status == JobStatus.SUCCEEDED:
                        from .dphost import DPWorld

                        dp = DPWorld.from_env()
                        if dp is None:
                            return {
                                "status": status.value,
                                "resumed": False,
                                "detail": "job already succeeded",
                            }
                        # Under DP, EVERY rank re-queues on resume —
                        # including rank 0 and even when locally
                        # SUCCEEDED. A worker's SUCCEEDED only means
                        # "my shard streamed" (the authoritative state
                        # is the coordinator's), and a refusing
                        # coordinator would leave re-queued workers
                        # retrying a port nobody serves until timeout.
                        # The re-run is a cheap no-op round: the
                        # coordinator's resume set already contains
                        # every row, so all shards are empty and the
                        # job re-finalizes identically.
                    # fetch BEFORE registering as queued: a raise here
                    # must not leave the id poisoning _queued
                    rec = self.jobs.get(job_id)
                    seq = self._reserve_queue_entry(
                        rec.job_priority, job_id
                    )
                    break
            # terminal status + still "current": the worker is in its
            # epilogue (flush/metrics) — wait for it to let go rather
            # than refusing a resume the caller can see is legitimate
            if not status.is_terminal() or _time.monotonic() > deadline:
                return {"status": status.value, "resumed": False,
                        "detail": "job is already queued or running"}
            _time.sleep(0.02)
            status = self.jobs.status(job_id)
        try:
            self._cancel.discard(job_id)
            self.metrics.drop(job_id)  # fresh stream for the re-run
            self.jobs.set_status(
                job_id, JobStatus.QUEUED, failure_reason=None
            )
            self._queue.put((rec.job_priority, seq, job_id))
        except Exception:
            with self._lock:
                self._queued.discard(job_id)
                self._queued_prio.pop(job_id, None)
            raise
        # mirror _run_job's resume filter: cancelled-truncated rows are
        # regenerated, so they don't count as already done (meta-only
        # read: no output columns materialize)
        done = sum(
            1
            for reason in self.jobs.read_partial_meta(job_id).values()
            if reason != "cancelled"
        )
        return {
            "status": JobStatus.QUEUED.value,
            "resumed": True,
            "rows_already_done": done,
        }

    def get_quotas(self) -> List[Dict[str, int]]:
        return self.jobs.get_quotas()

    def try_authentication(self) -> Dict[str, Any]:
        return {"authenticated": True}  # local engine needs no key

    def job_telemetry(
        self, job_id: str, write: bool = True
    ) -> Dict[str, Any]:
        """Per-job telemetry document: the flight recorder's span
        timeline for this job plus its exact counters (rows by outcome,
        tokens in/out). ``write`` persists it as
        ``jobs/<job_id>/telemetry.json`` (the same artifact the engine
        dumps automatically when a job FAILs). Falls back to a
        previously persisted dump when this process has no live state
        for the job (engine restarted)."""
        self.jobs.get(job_id)  # KeyError -> 404 upstream if unknown
        doc = telemetry.job_doc(job_id)
        if not doc["spans"] and not doc["counters"]:
            persisted = telemetry.load_job_dump(self.jobs._dir(job_id))
            if persisted is not None:
                return persisted
        if write and telemetry.enabled():
            telemetry.dump_job(self.jobs._dir(job_id), job_id)
        return doc

    def _dump_telemetry(self, job_id: str) -> None:
        """Flight-recorder postmortem on job failure (best-effort)."""
        if telemetry.enabled():
            # only failure paths land here — mark the job's forensics
            # trace (no-op if the job never got one)
            telemetry.TRACES.end_trace(f"tr-{job_id}", "error")
        telemetry.dump_job(self.jobs._dir(job_id), job_id)

    def get_trace(self, ident: str) -> Dict[str, Any]:
        """Chrome trace-event JSON (Perfetto-loadable) for ``ident``:
        a forensics trace id (``tr-...``, from an alert exemplar or a
        request's telemetry), a request/job id whose trace is still in
        the ring, or a plain job id — the latter renders the job's
        whole flight record instead. KeyError -> 404 upstream."""
        from ..telemetry import doctor, traceexport

        doc = telemetry.TRACES.doc(ident)
        if doc is None and not ident.startswith("tr-"):
            doc = telemetry.TRACES.doc(f"tr-{ident}")
        if doc is not None:
            chrome = traceexport.trace_to_chrome(doc)
            chrome["otherData"]["verdict"] = doctor.diagnose_request(doc)
            return chrome
        # fall back to the whole-job flight record
        jid = ident[3:] if ident.startswith("tr-") else ident
        jdoc = telemetry.job_doc(jid)
        if not jdoc["spans"] and not jdoc["counters"]:
            persisted = None
            try:
                self.jobs.get(jid)
                persisted = telemetry.load_job_dump(self.jobs._dir(jid))
            except KeyError:
                pass
            if persisted is None:
                raise KeyError(f"no trace or job telemetry for {ident!r}")
            jdoc = persisted
        return traceexport.job_doc_to_chrome(jdoc)

    def diagnose_job(self, job_id: str) -> Dict[str, Any]:
        """Bottleneck doctor (OBSERVABILITY.md "Doctor"): analyze the
        job's merged cross-process telemetry document — per-process
        stage attribution, roofline grades for device windows, and one
        named bottleneck verdict with evidence lines."""
        from ..telemetry import doctor

        rec = self.jobs.get(job_id)
        return doctor.diagnose(
            self.job_telemetry(job_id, write=False),
            status=rec.status,
            num_rows=rec.num_rows,
        )

    # -- fleet router load report (fleet/frames.py) --------------------

    def fleet_state(self) -> Dict[str, Any]:
        """Load + readiness report the fleet router's least-loaded
        policy consumes (served as a ``fleet_state`` frame by
        ``GET /fleet-state``). Cheap: lock-held counter reads only."""
        with self._lock:
            queued = len(
                [j for j in self._queued if not j.startswith("serve:")]
            )
            running = len(
                [j for j in self._attached if not j.startswith("serve:")]
            )
            cur = self._current_job
            if cur is not None and not cur.startswith("serve:"):
                running += 1
            models = sorted(self._runner_cache.keys())
        gw = self.gateway
        return {
            "ready": True,
            "draining": bool(gw is not None and gw.draining),
            "load": {
                "jobs_queued": queued,
                "jobs_running": running,
                "interactive_active": (
                    gw.active_count() if gw is not None else 0
                ),
                "interactive_slots": int(
                    getattr(self.ecfg, "interactive_slots", 0)
                ),
            },
            "models": models,
        }

    # -- live monitor (telemetry/monitor.py) ---------------------------

    def _monitor_jobs(self) -> List[Tuple[str, str]]:
        """RUNNING jobs for the monitor's continuous doctor: the
        worker's current job plus every co-batched attached job
        (serve-wake sentinels excluded — the interactive tier is
        monitored through its own histograms, not job records)."""
        with self._lock:
            ids = set(self._attached)
            if self._current_job is not None:
                ids.add(self._current_job)
        return [
            (jid, JobStatus.RUNNING.value)
            for jid in sorted(ids)
            if not jid.startswith("serve:")
        ]

    def _live_kv_tiers(self) -> List[Any]:
        """Live tier pools for the autotuner's kv_tier_host_pages
        actuation (pools built after a move read the knob off ecfg)."""
        with self._lock:
            return list(self._kv_tiers.values())

    def _monitor_alert_dump(
        self, job_id: str, alert: Dict[str, Any]
    ) -> None:
        """A firing alert persists the flight recorder next to the job
        — the same ``telemetry.json`` artifact FAILED leaves, written
        while the incident is live. Covered by the alert-dump leg of
        the ``telemetry.monitor`` fault site."""
        if faults.ACTIVE is not None:
            faults.inject("telemetry.monitor", job=job_id)
        telemetry.dump_job(self.jobs._dir(job_id), job_id)

    def monitor_doc(self) -> Dict[str, Any]:
        """The ``GET /monitor`` document (history + active alerts +
        live doctor verdicts). KeyError when the monitor is disabled
        (telemetry off or SUTRO_MONITOR=0) — the daemon maps it to 404,
        same contract as the serving tier's endpoints."""
        if self.monitor is None:
            raise KeyError(
                "live monitor disabled (SUTRO_TELEMETRY=0 or "
                "SUTRO_MONITOR=0)"
            )
        doc = self.monitor.snapshot_doc()
        if self.control is not None:
            doc["enforcement"] = self.control.snapshot()
        return doc

    def job_fleet(self, job_id: str) -> Dict[str, Any]:
        """Elastic dp fleet view: the coordinator's live membership
        snapshot while this process serves the job's round (per-rank
        state, row ownership, requeue/steal counters), else the
        snapshot persisted at round end (``jobs/<id>/fleet.json``).
        Jobs that never ran an elastic round report
        ``{"elastic": False}``."""
        import json as _json

        from .dphost import fleet_view

        self.jobs.get(job_id)  # KeyError -> 404 upstream if unknown
        snap = fleet_view(job_id)
        if snap is not None:
            snap["live"] = True
            return snap
        path = self.jobs._dir(job_id) / "fleet.json"
        if path.exists():
            try:
                snap = _json.loads(path.read_text())
                snap["live"] = False
                return snap
            except (OSError, ValueError) as e:
                logger.warning(
                    "unreadable fleet.json for %s: %s", job_id, e
                )
        return {"job_id": job_id, "elastic": False}

    def _persist_fleet(self, job_id: str) -> None:
        """Coordinator round end: persist the final membership snapshot
        (``jobs/<id>/fleet.json``) and stamp a doctor-readable summary
        into the job's telemetry attrs. Best-effort — fleet bookkeeping
        must never change a round's outcome."""
        import json as _json

        from .dphost import fleet_view

        snap = fleet_view(job_id)
        if snap is None:
            return
        try:
            path = self.jobs._dir(job_id) / "fleet.json"
            tmp = path.with_suffix(".json.tmp")
            tmp.write_text(_json.dumps(snap, indent=2))
            tmp.replace(path)
        except OSError:
            logger.warning(
                "fleet snapshot persist failed for %s", job_id,
                exc_info=True,
            )
        if telemetry.enabled():
            ranks = snap.get("ranks", {})
            c = snap.get("counters", {})
            telemetry.job(job_id).attrs["dp_fleet"] = {
                "live_ranks": snap.get("live_ranks", 0),
                "requeued_rows": c.get("requeued_rows", 0),
                "stolen_rows": c.get("stolen_rows", 0),
                "duplicate_results_dropped": c.get(
                    "duplicate_results_dropped", 0
                ),
                "lost_ranks": sorted(
                    r for r, v in ranks.items()
                    if v.get("state") == "lost"
                ),
                "drained_ranks": sorted(
                    r for r, v in ranks.items()
                    if v.get("state") == "drained"
                ),
                "late_joiners": sorted(
                    r for r, v in ranks.items() if v.get("late_join")
                ),
            }

    # ------------------------------------------------------------------
    # Worker
    # ------------------------------------------------------------------

    def _weights_dir_for(self, engine_key: str) -> Optional[str]:
        if self.ecfg.weights_dir:
            import os

            cand = os.path.join(self.ecfg.weights_dir, engine_key)
            if os.path.isdir(cand):
                return cand
        return None

    def _get_tokenizer(
        self, engine_key: str, mcfg: ModelConfig
    ) -> BaseTokenizer:
        """Tokenizer WITHOUT building the runner (quota gate / dry runs
        must not pay model init). Called from the worker loop AND the
        overlapped session-build thread: cache lookups/publishes hold
        ``self._lock``; the build itself runs unlocked (a lost build
        race costs one redundant tokenizer load, and ``setdefault``
        keeps the first published instance)."""
        with self._lock:
            cached = self._runner_cache.get(engine_key)
            if cached is not None:
                return cached[1]
            tok = self._tok_cache.get(engine_key)
        if tok is None:
            tok = load_tokenizer(
                self._weights_dir_for(engine_key),
                vocab_size=mcfg.vocab_size,
            )
            with self._lock:
                tok = self._tok_cache.setdefault(engine_key, tok)
        return tok

    def _get_runner(
        self, engine_key: str, mcfg: ModelConfig
    ) -> Tuple[ModelRunner, BaseTokenizer]:
        with self._lock:
            cached = self._runner_cache.get(engine_key)
        if cached is not None:
            return cached
        # only the worker thread builds runners, so the unlocked build
        # below cannot double-build; the lock covers the cache maps the
        # session-build thread and gateway probe read concurrently
        weights_dir = self._weights_dir_for(engine_key)
        tok = self._get_tokenizer(engine_key, mcfg)
        params = None
        if weights_dir:
            from .weights import load_checkpoint

            params = load_checkpoint(weights_dir, mcfg, self.ecfg)
        runner = ModelRunner(mcfg, self.ecfg, params=params)
        evicted_store = evicted_tier = None
        with self._lock:
            # keep at most two runners resident (HBM budget)
            if len(self._runner_cache) >= 2:
                evicted = next(iter(self._runner_cache))
                self._runner_cache.pop(evicted)
                # the evicted runner's KV pool dies with it — its
                # prefix store's pages are gone, so the store closes
                # too
                evicted_store = self._prefix_stores.pop(evicted, None)
                evicted_tier = self._kv_tiers.pop(evicted, None)
            self._runner_cache[engine_key] = (runner, tok)
        if evicted_store is not None:
            evicted_store.close()
        if evicted_tier is not None:
            evicted_tier.close()
        return runner, tok

    def _prefix_store_for(self, engine_key: str):
        """The engine-lifetime radix prefix store for this runner, or
        None when the subsystem is off. ``SUTRO_PREFIX_STORE`` overrides
        ``EngineConfig.prefix_store``; ``0``/``off`` disables — and OFF
        means the scheduler holds None and runs the per-job path
        bit-identically (asserted by tests/test_prefix_store.py)."""
        import os

        env = os.environ.get("SUTRO_PREFIX_STORE")
        if env is not None:
            enabled = env.strip().lower() not in ("0", "off", "false", "")
        else:
            enabled = bool(getattr(self.ecfg, "prefix_store", True))
        if not enabled:
            return None
        with self._lock:
            store = self._prefix_stores.get(engine_key)
            if store is None:
                from .prefixstore import PrefixStore

                store = PrefixStore(self.ecfg.kv_page_size)
                self._prefix_stores[engine_key] = store
        return store

    def _kv_tier_for(self, engine_key: str):
        """The engine-lifetime tiered KV pool (HBM → pinned host →
        disk) for this runner, or None when tiering is off.
        ``SUTRO_KV_TIERS`` overrides ``EngineConfig.kv_tiers``; the
        default is OFF — and OFF means the scheduler holds None and
        every demote/promote/hibernate path is dead code, bit-identical
        to the pre-tier engine (asserted by tests/test_kv_tiers.py)."""
        import os

        env = os.environ.get("SUTRO_KV_TIERS")
        if env is not None:
            enabled = env.strip().lower() not in ("0", "off", "false", "")
        else:
            enabled = bool(getattr(self.ecfg, "kv_tiers", False))
        if not enabled:
            return None
        with self._lock:
            tier = self._kv_tiers.get(engine_key)
            if tier is None:
                from .config import sutro_home
                from .kvtier import KVTierPool

                disk_dir = None
                if getattr(self.ecfg, "kv_tier_disk", True):
                    disk_dir = sutro_home() / "kvtier"
                tier = KVTierPool(
                    self.ecfg.kv_page_size,
                    host_pages=getattr(
                        self.ecfg, "kv_tier_host_pages", 4096
                    ),
                    disk_dir=disk_dir,
                )
                self._kv_tiers[engine_key] = tier
        return tier

    def prefix_warm_tokens(self, engine_key: str, ids) -> int:
        """Non-mutating warm-prefix probe for the serving gateway: how
        many leading tokens of ``ids`` already have resident KV. Zero
        when the store is off/cold — never raises."""
        with self._lock:
            store = self._prefix_stores.get(engine_key)
        if store is None:
            return 0
        try:
            return store.peek(ids)
        except Exception:  # graftlint: disable=silent-except
            return 0

    def close(self, timeout: float = 10.0) -> bool:
        """Stop the worker thread with a bounded join (thread-hygiene
        teardown: the worker must not outlive the engine unobserved).
        The sentinel sorts ahead of every real job, so an idle worker
        exits immediately; a worker mid-job finishes that job first and
        the join may time out — the thread is daemonic either way.
        Returns True when the worker actually exited. A closed engine
        no longer runs queued jobs (their records stay resumable by a
        fresh engine process)."""
        if self.monitor is not None:
            self.monitor.stop()
        self._queue.put(_WORKER_STOP)
        self._worker.join(timeout=timeout)
        # drop every prefix store: their pinned pages die with the
        # runners' pools, and a closed store refuses new extends, so a
        # racing session degrades to the storeless per-job path
        with self._lock:
            stores = list(self._prefix_stores.values())
            self._prefix_stores.clear()
            tiers = list(self._kv_tiers.values())
            self._kv_tiers.clear()
        for store in stores:
            store.close()
        # tier pools park their migration worker; queued async demotes
        # are dropped (lossy by contract — the HBM copy was freed by
        # the store, these were cache-only pages)
        for tier in tiers:
            tier.close()
        return not self._worker.is_alive()

    def _worker_loop(self) -> None:
        while True:
            _, _, job_id = self._queue.get()
            if job_id is None:  # close() sentinel
                return
            with self._lock:
                self._queued.discard(job_id)
                self._queued_prio.pop(job_id, None)
                self._current_job = job_id
            if job_id.startswith("serve:"):
                # serving-wake sentinel: run an interactive session for
                # the key (no job record, no jobstore epilogue)
                engine_key = job_id[6:]
                if self.gateway is not None:
                    self.gateway.sentinel_popped(engine_key)
                try:
                    self._run_serving_session(engine_key)
                except Exception:  # noqa: BLE001 — session isolation
                    traceback.print_exc()
                finally:
                    with self._lock:
                        self._current_job = None
                continue
            if telemetry.enabled():
                with self._lock:
                    n_attached = len(self._attached)
                telemetry.JOBS_RUNNING.set(1 + n_attached)
            requeue_priority = None
            try:
                if job_id in self._cancel:
                    self.jobs.set_status(job_id, JobStatus.CANCELLED)
                    continue
                requeue_priority = self._run_job(job_id)
            except Exception as e:  # noqa: BLE001 — job isolation boundary
                traceback.print_exc()
                # terminal failure_log entry BEFORE the status flip, so
                # a watcher that sees FAILED also sees why
                self.jobs.append_failure_log(
                    job_id,
                    {"event": "job_failed",
                     "error": f"{type(e).__name__}: {e}"},
                )
                # crash-time postmortem BEFORE the status flip, same
                # rule as the failure_log entry: a watcher that sees
                # FAILED finds telemetry.json already in place
                self._dump_telemetry(job_id)
                try:
                    self.jobs.set_status(
                        job_id,
                        JobStatus.FAILED,
                        failure_reason={"message": f"{type(e).__name__}: {e}"},
                    )
                except Exception:
                    pass
            finally:
                if requeue_priority is None:
                    # finish metrics BEFORE releasing _current_job:
                    # resume_job waits on _current_job, and must not race
                    # this epilogue into finishing the resumed run's
                    # fresh metrics stream
                    self.metrics.job(job_id).finish()
                else:
                    # preempted: keep the metrics stream alive (attached
                    # clients see progress stall, then resume) and
                    # requeue BEFORE releasing _current_job so a
                    # concurrent resume_job can never observe not-busy
                    # and double-enqueue
                    self.jobs.set_status(job_id, JobStatus.QUEUED)
                    self._enqueue(requeue_priority, job_id)
                with self._lock:
                    self._current_job = None
                if telemetry.enabled():
                    with self._lock:
                        n_attached = len(self._attached)
                    telemetry.JOBS_RUNNING.set(n_attached)

    def _run_job(self, job_id: str) -> Optional[int]:
        """Run one job to a terminal state. Returns None normally, or
        the job's priority when it yielded to a higher-priority job (the
        worker loop requeues it).

        Generation jobs run as a CO-BATCHED session: same-model jobs
        submitted while this one runs attach to the running batcher
        (scheduler.run_multi) and share its decode batch — each reaches
        its own terminal state the moment its rows finish."""
        rec = self.jobs.get(job_id)
        self.jobs.set_status(job_id, JobStatus.STARTING)
        engine_key, mcfg, meta = resolve_model(rec.model)
        runner, tok = self._get_runner(engine_key, mcfg)
        if telemetry.enabled():
            # the doctor's roofline denominator: device kind + model
            # byte counts land in the job's flight-recorder attrs
            # (probed: stub runners in tests/benchmarks have no device)
            device_info = getattr(runner, "device_info", None)
            if device_info is not None:
                telemetry.job(job_id).attrs["device"] = device_info()

        if rec.stages:
            # stage-graph job (engine/stagegraph.py): the whole DAG —
            # map waves, host reduces, per-stage chunk stores, resume —
            # runs inside the runner; same return contract as below
            # (None, or the job's priority on yield)
            from .stagegraph import StageGraphRunner

            return StageGraphRunner(self, job_id, rec).run()

        if rec.dry_run or mcfg.head == "embedding":
            inputs = self.jobs.read_inputs(job_id)
            sampling = rec.sampling_params or {}
            max_new = int(
                sampling.get("max_new_tokens", self.ecfg.max_new_tokens)
            )
            from .tokenizer import encode_chat_batch

            token_rows = [
                np.array(ids, np.int32)
                for ids in encode_chat_batch(
                    tok,
                    inputs,
                    rec.system_prompt,
                    mcfg.chat_template,
                    threads=self.ecfg.tokenize_threads,
                )
            ]
            input_tokens = int(sum(len(r) for r in token_rows))
            if rec.dry_run:
                est_out = rec.num_rows * max_new
                cost = estimate_cost(engine_key, input_tokens, est_out)
                self.jobs.update(
                    job_id,
                    cost_estimate=cost,
                    input_tokens=input_tokens,
                )
                self.jobs.set_status(job_id, JobStatus.SUCCEEDED)
                return None
            self.jobs.set_status(job_id, JobStatus.RUNNING)
            jm = self.metrics.job(job_id)
            return self._run_embedding_job(
                job_id, rec, runner, tok, token_rows, jm
            )

        sess = _GenSession(self, job_id, rec, engine_key, mcfg, meta, tok)
        self.jobs.set_status(job_id, JobStatus.RUNNING)

        from .dphost import DPWorld
        from .profiling import job_trace

        batcher = ContinuousBatcher(
            runner,
            stop_ids=getattr(tok, "stop_ids", lambda: [tok.eos_id])(),
            seed=self.ecfg.seed,
            token_bytes=sess.token_bytes,
            prefix_store=self._prefix_store_for(engine_key),
            kv_tier=self._kv_tier_for(engine_key),
        )
        if self.control is not None:
            batcher.ladder = self.control.ladder
        dp = DPWorld.from_env()
        with job_trace(self.ecfg.profile_dir, job_id):
            if dp is not None:
                # engine-level multi-host DP (SURVEY §2.3 DP row): this
                # process runs its strided row shard on slice-local
                # devices; rank 0 merges every rank's stream through the
                # jobstore (order-preserving by row_id). Priority
                # preemption and cross-job co-batching are per-slice
                # concerns and disabled for DP jobs — yielding or
                # multiplexing one slice of a pod-spanning job would
                # stall, not help, the pod.
                import hashlib
                import json as _json

                # deterministic cross-rank job identity (job_ids are
                # per-process): guards the channel against rank-queue
                # divergence merging one job's rows into another. ALL
                # inputs feed the hash (length-delimited) — two jobs
                # differing only in middle rows must not share a key.
                # SUTRO_DP_SECRET (optional, same value on every rank)
                # seeds the hash so the key is not derivable from job
                # content alone (dphost.py trust model).
                import os as _os

                h = hashlib.sha256(
                    _os.environ.get("SUTRO_DP_SECRET", "").encode()
                )
                h.update(
                    _json.dumps(
                        [
                            rec.model,
                            rec.num_rows,
                            sess.sampling,
                            rec.system_prompt,
                            rec.output_schema,
                        ],
                        sort_keys=True,
                        default=str,
                    ).encode()
                )
                for row in sess.inputs:
                    rb = str(row).encode()
                    h.update(f"{len(rb)}:".encode())
                    h.update(rb)
                job_key = h.hexdigest()[:16]
                import functools

                # row retries ride the shard-owning rank's batcher;
                # row events reach the coordinator's failure_log via
                # the channel's fault messages (dphost). job_id tags
                # the run's spans so the shipped/merged timeline is
                # attributable to this job
                run_shard = functools.partial(
                    batcher.run, row_retries=self.ecfg.row_retries,
                    job_id=job_id,
                )
                # the whole request pool goes down — elastic rounds
                # re-shard it dynamically (rank 0 strides its own share;
                # workers receive row assignments in the handshake)
                outcome = self._dp_dispatch(
                    dp, run_shard, sess.requests,
                    job_id=job_id, job_key=job_key,
                    on_result=sess.on_result,
                    on_progress=sess.on_progress,
                    should_cancel=sess.should_cancel,
                    on_row_event=sess.on_row_event,
                    # the coordinator's partial store holds every
                    # rank's flushed rows — the done set lets
                    # relaunched workers resume row-granularly
                    done_rows=set(sess.done), num_rows=rec.num_rows,
                )
                if outcome is None:  # worker rank: terminal status set
                    return None
                sess.flush()
                if sess.cancelled["flag"]:
                    self.jobs.set_status(job_id, JobStatus.CANCELLED)
                    return None
                if outcome == "yielded":
                    return rec.job_priority
                sess.finalize_completed(batcher)
                return None
            return self._run_cobatch_session(
                job_id, engine_key, sess, batcher
            )

    def _run_serving_session(self, engine_key: str) -> None:
        """Serving-only co-batch session: no primary batch job, just
        interactive requests adopted through the gateway (plus any
        same-model batch jobs that attach mid-session via the normal
        queue scan)."""
        gw = self.gateway
        if gw is None or not gw.has_pending(engine_key):
            return
        mcfg = MODEL_CONFIGS.get(engine_key)
        if mcfg is None:
            return
        runner, tok = self._get_runner(engine_key, mcfg)
        token_bytes = getattr(tok, "token_bytes", None)
        if token_bytes is not None:
            try:
                token_bytes(0)
            except Exception:  # graftlint: disable=silent-except
                token_bytes = None  # base-class stub probe
        batcher = ContinuousBatcher(
            runner,
            stop_ids=getattr(tok, "stop_ids", lambda: [tok.eos_id])(),
            seed=self.ecfg.seed,
            token_bytes=token_bytes,
            prefix_store=self._prefix_store_for(engine_key),
            kv_tier=self._kv_tier_for(engine_key),
        )
        if self.control is not None:
            batcher.ladder = self.control.ladder
        self._run_cobatch_session(None, engine_key, None, batcher)

    def _run_cobatch_session(
        self, job_id: Optional[str], engine_key: str,
        sess: "Optional[_GenSession]", batcher,
    ) -> Optional[int]:
        """Drive the primary job and any attachable queued same-model
        jobs through ONE scheduler session (cross-job co-batching).
        Returns the primary's requeue priority on preemption yield, else
        None (each job's terminal state is set as it finishes).

        ``sess=None`` runs a SERVING-ONLY session (_run_serving_session):
        the loop starts empty and lives off gateway adoptions. Either
        way, when a gateway exists its parked interactive requests are
        adopted ahead of the queue scan — they are 1-row priority -1
        ctxs whose results ride the per-request channel, not a session."""
        sessions: Dict[str, _GenSession] = (
            {} if sess is None else {job_id: sess}
        )
        # live interactive ctxs by request id (gateway-owned lifecycle)
        iactive: Dict[str, Any] = {}
        gw = self.gateway
        # in-flight attach build: session construction tokenizes every
        # input row, so it runs on a BACKGROUND thread — the scheduler
        # loop keeps decoding live jobs while a 20k-row attach prepares.
        # One build at a time also rate-limits cascading attaches.
        build: Dict[str, Any] = {}

        def _build_session(jid: str, seq: int) -> None:
            try:
                rec2 = self.jobs.get(jid)
                self.jobs.set_status(jid, JobStatus.STARTING)
                _key2, mcfg2, meta2 = resolve_model(rec2.model)
                tok2 = self._get_tokenizer(_key2, mcfg2)
                s2 = _GenSession(
                    self, jid, rec2, _key2, mcfg2, meta2, tok2, seq=seq
                )
                self.jobs.set_status(jid, JobStatus.RUNNING)
                build["session"] = s2
            except Exception as e:  # noqa: BLE001 — job isolation
                traceback.print_exc()
                self.jobs.append_failure_log(
                    jid,
                    {"event": "job_failed",
                     "error": f"{type(e).__name__}: {e}"},
                )
                self._dump_telemetry(jid)
                try:
                    self.jobs.set_status(
                        jid,
                        JobStatus.FAILED,
                        failure_reason={
                            "message": f"{type(e).__name__}: {e}"
                        },
                    )
                except Exception:
                    pass
                self.metrics.job(jid).finish()
                with self._lock:
                    self._attached.discard(jid)
            finally:
                build["done"] = True

        def poll_new():
            # latency-priority adoption: a parked interactive request
            # enters the live window before any queued batch job
            if gw is not None:
                ictx = gw.take_pending(engine_key)
                if ictx is not None:
                    iactive[ictx.job_id] = ictx
                    return ictx
            if build:
                if not build.get("done"):
                    return None  # build in flight; keep decoding
                s2 = build.get("session")
                build.clear()
                if s2 is not None:
                    sessions[s2.job_id] = s2
                    return s2.ctx
                return None
            pop = self._pop_attachable(engine_key)
            if pop is None:
                return None
            jid, seq = pop
            with self._lock:
                self._attached.add(jid)
            build["job_id"] = jid
            t = threading.Thread(
                target=_build_session, args=(jid, seq), daemon=True,
                name=f"sutro-attach-{jid}",
            )
            build["thread"] = t
            t.start()
            return None

        def _drain_pending_build() -> None:
            """The session is ending with an attach build possibly in
            flight: wait for it, then REQUEUE the job (it was pulled
            from the queue but never ran a row — resume semantics make
            the requeue exact)."""
            if not build:
                return
            t = build.get("thread")
            if t is not None:
                t.join(timeout=600)
            s2 = build.get("session")
            build.clear()
            if s2 is None:
                return  # build failed: terminal status already set
            self.jobs.set_status(s2.job_id, JobStatus.QUEUED)
            self._enqueue(s2.rec.job_priority, s2.job_id)
            with self._lock:
                self._attached.discard(s2.job_id)

        def on_job_done(ctx, outcome: str) -> None:
            if ctx.job_id in iactive:
                iactive.pop(ctx.job_id, None)
                stats = gw.finish(ctx, outcome) if gw is not None else {}
                if stats:
                    # doctor evidence: co-resident batch jobs record the
                    # interactive traffic they shared the window with
                    for s2 in sessions.values():
                        if s2.finalized or s2.jtel is None:
                            continue
                        ia = s2.jtel.attrs.setdefault(
                            "interactive",
                            {"requests": 0, "starved": 0,
                             "ttft_max_s": 0.0},
                        )
                        ia["requests"] += 1
                        if stats.get("starved"):
                            ia["starved"] += 1
                        if stats.get("ttft_s") is not None:
                            ia["ttft_max_s"] = max(
                                ia["ttft_max_s"],
                                round(stats["ttft_s"], 3),
                            )
                return
            s = sessions[ctx.job_id]
            if s.jtel is not None and (
                getattr(ctx, "prefix_saved", 0)
                or getattr(ctx, "prefix_paid", 0)
            ):
                # saved-vs-paid shared-prefix prefill attribution: the
                # doctor's prefix_cold evidence line keys off this
                s.jtel.attrs["prefix"] = {
                    "saved_tokens": int(ctx.prefix_saved),
                    "paid_tokens": int(ctx.prefix_paid),
                }
            if s.jtel is not None and ctx.stats.get("preempted"):
                ia = s.jtel.attrs.setdefault(
                    "interactive",
                    {"requests": 0, "starved": 0, "ttft_max_s": 0.0},
                )
                ia["preempted_rows"] = ctx.stats["preempted"]
            if s.jtel is not None and (
                getattr(batcher, "_kv_tier", None) is not None
            ):
                # doctor evidence: kv_pressure / resume_bound verdicts
                # key off this (telemetry/doctor.py)
                s.jtel.attrs["kv_tier"] = {
                    "demotes": int(batcher.tier_demotes),
                    "promotes": int(batcher.tier_promotes),
                    "resumes_upload": int(
                        ctx.stats.get("resumes_upload", 0)
                    ),
                    "resumes_reprefill": int(
                        ctx.stats.get("resumes_reprefill", 0)
                    ),
                }
            # NO try/finally: a raised finalize (e.g. the store's
            # bounded I/O retries exhausted) must leave ``finalized``
            # False so the session-error path below — or the worker
            # loop for the primary — marks the job FAILED resumably
            # instead of abandoning it RUNNING with no owner
            if outcome == "completed":
                s.finalize_completed(batcher)
            else:
                s.finalize_cancelled()
            s.finalized = True
            if ctx.job_id != job_id:
                # the worker loop's epilogue only covers the
                # primary; attached jobs close out here
                self.metrics.job(ctx.job_id).finish()
                with self._lock:
                    self._attached.discard(ctx.job_id)

        def should_yield() -> bool:
            live = [
                s.ctx.priority
                for s in sessions.values()
                if not s.finalized
            ]
            # a live interactive request (priority -1) outranks every
            # queued batch job, so min(live) = -1 pins the session
            live += [c.priority for c in iactive.values() if not c.done]
            if not live:
                return False
            return self._unattachable_higher_waiting(
                min(live), engine_key
            )

        def _fail_live_interactive(outcome: str) -> None:
            for c in list(iactive.values()):
                if not c.done and gw is not None:
                    gw.finish(c, outcome)
            iactive.clear()

        try:
            state = batcher.run_multi(
                [sess.ctx] if sess is not None else [],
                on_job_done=on_job_done,
                poll_new=poll_new,
                should_yield=should_yield,
            )
        except Exception:
            _drain_pending_build()
            # live interactive requests have no resumable record —
            # their channels get the error and the client retries
            _fail_live_interactive("error")
            # fail attached non-terminal jobs; the worker loop's except
            # handles the primary — unless the primary already reached a
            # terminal state, in which case swallow (don't flip it)
            for jid2, s2 in list(sessions.items()):
                if s2.finalized or jid2 == job_id:
                    continue
                try:
                    s2.flush()
                except Exception:
                    logger.warning(
                        "partial flush failed while failing attached "
                        "job %s", jid2, exc_info=True,
                    )
                self.jobs.append_failure_log(
                    jid2,
                    {"event": "job_failed",
                     "error": "co-batched session error"},
                )
                self._dump_telemetry(jid2)
                try:
                    self.jobs.set_status(
                        jid2,
                        JobStatus.FAILED,
                        failure_reason={
                            "message": "co-batched session error"
                        },
                    )
                except Exception:
                    pass
                self.metrics.job(jid2).finish()
                with self._lock:
                    self._attached.discard(jid2)
            if sess is None:
                # serving-only session: no primary for the worker-loop
                # epilogue to fail — the error is fully handled here
                traceback.print_exc()
                return None
            if sessions[job_id].finalized:
                traceback.print_exc()
                return None
            raise
        _drain_pending_build()
        if state == "yielded":
            # interactive ctxs cannot suspend/resume (their consumer is
            # a live stream); only reachable if something outranks
            # priority -1, which the public surface never produces
            _fail_live_interactive("error")
            requeue = None
            for jid2, s2 in list(sessions.items()):
                if s2.finalized:
                    continue
                s2.flush()
                if jid2 == job_id:
                    requeue = s2.rec.job_priority  # worker requeues
                else:
                    # metrics stream stays alive across the preemption
                    # (attached clients see a stall, then resume)
                    self.jobs.set_status(jid2, JobStatus.QUEUED)
                    self._enqueue(s2.rec.job_priority, jid2)
                    with self._lock:
                        self._attached.discard(jid2)
            return requeue
        return None

    def _dp_dispatch(
        self, dp, run_shard, pool, *, job_id, job_key, on_result,
        on_progress, should_cancel, done_rows, num_rows,
        on_row_event=None,
    ) -> Optional[str]:
        """Execute one rank's share of a DP job. ``pool`` is the FULL
        request pool (not a pre-strided shard): elastic rounds re-shard
        it dynamically, so every rank needs the whole row universe —
        rank 0 strides its own share, workers run the row assignment
        received in the handshake (falling back to their stride against
        a pre-elastic coordinator). Returns the outcome on rank 0
        (coordinator: merges every rank through ``on_result``), or None
        on worker ranks after setting their terminal status — single
        policy copy for the generation AND embedding paths
        (never-served sentinel, CANCELLED-not-FAILED worker mapping,
        preemption-drain mapping, full-resume round skip).

        Distributed telemetry rides the channel here: rank 0 stamps a
        trace context into the round and ingests every worker's
        piggybacked shard (telemetry/distributed.py); worker ranks open
        the round under the received context and ship their bounded
        span/metrics shard on the terminal frame."""
        from ..telemetry import distributed
        from .dphost import (
            run_dp_coordinator,
            run_dp_worker,
            shard_requests,
        )

        tel_on = telemetry.enabled()
        if dp.rank == 0:
            tele_ctx = None
            on_worker_tele = None
            if tel_on:
                round_no = distributed.REMOTE.next_round(job_id)
                tele_ctx = distributed.trace_context(job_id, round_no)

                def on_worker_tele(rank: int, shard: Dict) -> None:
                    distributed.REMOTE.ingest(job_id, rank, shard)

            if len(done_rows) >= num_rows:
                # resume of a fully-merged job: serve a TRIVIAL round
                # (bind, send resume-all, drain dones briefly) so
                # pod-wide re-queued workers finish as SUCCEEDED no-ops
                # instead of spinning their full accept timeout against
                # an unbound port; workers that were not re-queued are
                # not expected and not errors
                from .dphost import serve_resume_round

                if not serve_resume_round(
                    dp, job_key=job_key, done_rows=done_rows,
                    tele_ctx=tele_ctx, on_worker_tele=on_worker_tele,
                ):
                    # port held by a dying predecessor through every
                    # bind retry: the job's rows are all merged, so
                    # still complete — record why re-queued workers
                    # may spin until their accept deadline
                    self.jobs.append_failure_log(
                        job_id,
                        {"event": "dp_resume_round_unserved",
                         "message": (
                             "coordinator port busy through bind "
                             "retries; re-queued workers retry until "
                             "their accept deadline — resume again "
                             "once the port frees"
                         )},
                    )
                return "completed"
            shard = shard_requests(pool, 0, dp.world)
            try:
                if tel_on:
                    with telemetry.RECORDER.span(
                        "dp_round", job_id, world=dp.world,
                        shard_rows=len(shard),
                    ):
                        t0 = time.monotonic()
                        try:
                            return run_dp_coordinator(
                                dp, run_shard, shard,
                                on_result=on_result,
                                on_progress=on_progress,
                                should_cancel=should_cancel,
                                job_key=job_key,
                                done_rows=done_rows,
                                on_row_event=on_row_event,
                                tele_ctx=tele_ctx,
                                on_worker_tele=on_worker_tele,
                                requests=pool,
                                job_id=job_id,
                            )
                        finally:
                            telemetry.stage_observe(
                                "dp_round", time.monotonic() - t0
                            )
                return run_dp_coordinator(
                    dp, run_shard, shard,
                    on_result=on_result,
                    on_progress=on_progress,
                    should_cancel=should_cancel,
                    job_key=job_key,
                    done_rows=done_rows,
                    on_row_event=on_row_event,
                    requests=pool,
                    job_id=job_id,
                )
            finally:
                # round over (any outcome): persist the final fleet
                # snapshot next to the job record and stamp the doctor
                # summary before the live registry entry ages out
                self._persist_fleet(job_id)
        if tel_on:
            # the worker's results leave through the channel, not
            # through the session's on_result — tally shard rows into
            # the LOCAL per-job counters so the shipped shard reports
            # what this rank executed. Registry rows_total is NOT
            # incremented here on purpose: rows count at the
            # coordinator's merge, so federated series sum to pod
            # totals instead of double-counting worker rows.
            from .dphost import _accepts_kwarg

            jtel = telemetry.job(job_id)
            inner_shard = run_shard

            def run_shard(rows, *, on_result, **kw):
                def tally(res):
                    err = getattr(res, "error", None)
                    fin = str(getattr(res, "finish_reason", ""))
                    outcome = (
                        "quarantined"
                        if err is not None or fin.startswith("error")
                        else "cancelled" if fin == "cancelled"
                        else "ok"
                    )
                    jtel.add(f"rows_{outcome}")
                    on_result(res)

                # this wrapper's **kw makes dphost's signature probe
                # over-permissive; re-probe the REAL shard runner
                if "on_row_event" in kw and not _accepts_kwarg(
                    inner_shard, "on_row_event"
                ):
                    kw.pop("on_row_event")
                return inner_shard(rows, on_result=tally, **kw)

        try:
            w_outcome = run_dp_worker(
                dp, run_shard, pool,
                job_key=job_key,
                should_cancel=should_cancel,
                tele=(
                    distributed.WorkerTelemetry(job_id, dp.rank)
                    if tel_on
                    else None
                ),
                elastic=True,
            )
        except RuntimeError as e:
            if "never served" not in str(e):
                raise
            # most likely a resume of an already-complete pod job where
            # rank 0 (correctly) skipped its round. CANCELLED, not
            # FAILED: nothing ran, the record is non-authoritative, and
            # CANCELLED stays resumable.
            self.jobs.set_status(
                job_id,
                JobStatus.CANCELLED,
                failure_reason={"message": str(e)},
            )
            return None
        # worker stores are not authoritative: results live on rank 0;
        # mark the local record terminal honestly (a cancelled shard,
        # e.g. coordinator death, is not a success)
        if w_outcome == "drained":
            self.jobs.set_status(
                job_id,
                JobStatus.CANCELLED,
                failure_reason={
                    "message": (
                        "worker preempted: drained in-flight rows "
                        "to the coordinator"
                    )
                },
            )
            return None
        self.jobs.set_status(
            job_id,
            JobStatus.SUCCEEDED
            if w_outcome == "completed"
            else JobStatus.CANCELLED,
        )
        return None

    def _run_embedding_job(
        self, job_id, rec, runner, tok, token_rows, jm
    ) -> Optional[int]:
        """Embedding path: pooled head, batched (BASELINE config #3).

        Row-granular durability like the generation path (SURVEY §5.3):
        embeddings flush to the partial store every few batches, so a
        1M-row job that dies at row 999k resumes from the flush point
        instead of row 0 — and the same mechanism serves preemption
        (returns the job priority when yielding to a higher-priority
        job) and cancel/resume."""
        bs = max(self.ecfg.decode_batch_size, 8)
        done_rows = self.jobs.read_partial(job_id)
        results: Dict[int, Any] = {
            i: (
                r["outputs"].tolist()
                if hasattr(r["outputs"], "tolist")
                else r["outputs"]
            )
            for i, r in done_rows.items()
        }
        pending_flush: List[Dict[str, Any]] = []

        def flush() -> None:
            if pending_flush:
                self.jobs.flush_partial(job_id, list(pending_flush))
                pending_flush.clear()

        todo = [i for i in range(len(token_rows)) if i not in results]
        # length-sorted batches: rows in a batch pad to the batch max,
        # so grouping similar lengths cuts padding FLOPs on mixed-length
        # datasets (results are keyed by row_id — output order is
        # unaffected, reference 1:1 contract intact)
        todo.sort(key=lambda i: len(token_rows[i]))
        jm.progress(len(results))

        import jax

        from .dphost import DPWorld, EmbResult

        dp = DPWorld.from_env()
        n_chips = max(jax.device_count(), 1) * (dp.world if dp else 1)
        # batch the progress bus (a 1M-row job would otherwise pay one
        # bus publish per row) — shared rule with the generation path
        from .metrics import BatchedProgress

        row_progress = BatchedProgress(jm, every_rows=bs)

        tel_on = telemetry.enabled()
        jtel = telemetry.job(job_id) if tel_on else None

        def record_result(r: "EmbResult") -> None:
            if tel_on:
                jtel.add("rows_ok")
                telemetry.ROWS_TOTAL.inc(1.0, "ok")
            results[r.row_id] = r.vector
            pending_flush.append(
                {"row_id": r.row_id, "outputs": r.vector,
                 "cumulative_logprobs": 0.0, "finish_reason": "stop"}
            )
            if len(pending_flush) >= _PARTIAL_FLUSH_EVERY:
                flush()
            row_progress.update(len(results))

        # rows/s for the embed workload (live on /metrics, satellite of
        # the distributed-telemetry PR: throughput gauges cover every
        # workload type, not just generate). Rate is measured over the
        # MERGED stream — under dp this is the coordinator's pod rate.
        rows_rate = Throughput(1)

        def embed_progress(p: Dict[str, Any]) -> None:
            tps = p.get("total_tokens_processed_per_second", 0.0)
            if tel_on:
                rows_rate.note_total(p.get("rows_completed", 0))
                telemetry.ROWS_PER_SECOND.set(
                    rows_rate.per_second(),
                    "dp" if dp is not None else "embed",
                )
                telemetry.TOKENS_PER_SECOND.set(tps)
                telemetry.TOKENS_PER_SECOND_PER_CHIP.set(tps / n_chips)
            jm.tokens(
                {
                    "input_tokens": p.get("input_tokens", 0),
                    "output_tokens": 0,
                    "total_tokens_processed_per_second": tps,
                    "tokens_per_second_per_chip": tps / n_chips,
                }
            )

        def embed_rows(
            pairs, *, on_result, on_progress=None, should_cancel=None,
            should_yield=None,
        ) -> str:
            """Embed ``pairs`` [(row_id, ids), ...] batch-wise. The one
            execution path for single-host, DP-coordinator-local, and
            DP-worker shards (dphost run_shard signature)."""
            done_n = 0
            in_toks = 0
            import time as _time

            t0 = _time.monotonic()
            for off in range(0, len(pairs), bs):
                if should_cancel and should_cancel():
                    return "cancelled"
                if should_yield and should_yield():
                    return "yielded"
                grp = pairs[off : off + bs]
                t0e = _time.monotonic() if tel_on else 0.0
                emb = runner.embed_batch(
                    [list(map(int, ids)) for _, ids in grp]
                )
                if tel_on:
                    dte = _time.monotonic() - t0e
                    telemetry.stage_observe("embed", dte)
                    telemetry.RECORDER.record(
                        "embed", job_id, t0e, dte, {"rows": len(grp)}
                    )
                for (i, ids), vec in zip(grp, emb.tolist()):
                    on_result(EmbResult(row_id=i, vector=vec))
                    done_n += 1
                    in_toks += len(ids)
                if on_progress:
                    dt = max(_time.monotonic() - t0, 1e-9)
                    on_progress(
                        {
                            "rows_completed": done_n,
                            "input_tokens": in_toks,
                            "output_tokens": 0,
                            "total_tokens_processed_per_second":
                                in_toks / dt,
                        }
                    )
            return "completed"

        if dp is not None:
            import hashlib

            # cross-rank identity from the tokenized rows (identical on
            # every rank: same inputs, same tokenizer); SUTRO_DP_SECRET
            # seeds it like the generation path (dphost.py trust model)
            import os as _os

            h = hashlib.sha256(
                _os.environ.get("SUTRO_DP_SECRET", "").encode()
            )
            h.update(f"embed:{rec.model}:{rec.num_rows}".encode())
            for r in token_rows:
                rb = np.asarray(r, np.int32).tobytes()
                h.update(f"{len(rb)}:".encode())
                h.update(rb)
            # full pool, not a pre-strided shard: elastic rounds
            # re-shard it dynamically (see _dp_dispatch)
            pool = [(i, token_rows[i]) for i in todo]
            outcome = self._dp_dispatch(
                dp, embed_rows, pool,
                job_id=job_id, job_key=h.hexdigest()[:16],
                on_result=record_result,
                on_progress=embed_progress,
                should_cancel=lambda: job_id in self._cancel,
                done_rows=set(results), num_rows=rec.num_rows,
                on_row_event=lambda ev: self.jobs.append_failure_log(
                    job_id, ev
                ),
            )
            if outcome is None:  # worker rank: terminal status set
                return None
        else:
            outcome = embed_rows(
                [(i, token_rows[i]) for i in todo],
                on_result=record_result,
                on_progress=embed_progress,
                should_cancel=lambda: job_id in self._cancel,
                should_yield=lambda: self._higher_priority_waiting(
                    rec.job_priority
                ),
            )
        if outcome == "cancelled":
            flush()
            self.jobs.set_status(job_id, JobStatus.CANCELLED)
            return None
        if outcome == "yielded":
            flush()
            return rec.job_priority
        flush()
        row_progress.flush(len(results))  # terminal count always lands
        input_tokens = int(sum(len(r) for r in token_rows))
        if tel_on:
            jtel.set("input_tokens", input_tokens)
            jtel.set("output_tokens", 0)
            telemetry.TOKENS_TOTAL.inc(float(input_tokens), "in")
        self.jobs.update(
            job_id,
            input_tokens=input_tokens,
            output_tokens=0,
            job_cost=estimate_cost(rec.engine_key, input_tokens, 0),
        )
        n = len(token_rows)
        self.jobs.finalize_results(
            job_id,
            {
                "row_id": list(range(n)),
                "outputs": [results[i] for i in range(n)],
                "cumulative_logprobs": [0.0] * n,
                "finish_reason": ["stop"] * n,
            },
        )
        return None


class _GenSession:
    """Engine-side context for ONE generation job inside a (possibly
    co-batched) batcher session: prompt build, resume filter, result
    rendering/flushing, metrics, and terminal-state transitions. The
    scheduler-side half is the ``JobCtx`` this owns (scheduler.run_multi
    drives many of these through one decode batch)."""

    def __init__(
        self, eng: "LocalEngine", job_id: str, rec, engine_key: str,
        mcfg, meta, tok, seq: int = 0,
    ):
        from .scheduler import JobCtx

        from .metrics import BatchedProgress

        self.eng = eng
        self.job_id = job_id
        self.rec = rec
        self.engine_key = engine_key
        self.tok = tok
        self.jm = eng.metrics.job(job_id)
        self.row_progress = BatchedProgress(
            self.jm, every_rows=eng.ecfg.decode_batch_size
        )
        self.finalized = False
        self.thinking = bool(meta.get("thinking"))
        inputs = eng.jobs.read_inputs(job_id)
        self.inputs = inputs
        sampling = rec.sampling_params or {}
        self.sampling = sampling
        max_new = int(
            sampling.get("max_new_tokens", eng.ecfg.max_new_tokens)
        )
        # stop sequences (vLLM-style sampling_params["stop"]): engine
        # detects via a rolling byte tail; exact truncation happens at
        # render time where the full decoded string exists
        raw_stop = sampling.get("stop") or []
        if isinstance(raw_stop, str):
            raw_stop = [raw_stop]
        if not all(isinstance(s, str) for s in raw_stop):
            raise ValueError(
                "sampling_params['stop'] must be a string or list of "
                f"strings, got {raw_stop!r}"
            )
        stop_strs = [s for s in raw_stop if s]
        if stop_strs and rec.output_schema:
            # a stop string can cut the constrained output mid-JSON —
            # the guaranteed-valid-JSON contract outranks it (the SDK
            # also warns at submit time, where the caller can see it)
            warnings.warn(
                "sampling_params['stop'] is ignored for output_schema "
                "jobs: stopping mid-JSON would break the schema "
                "guarantee (the schema's own closure ends generation)"
            )
            stop_strs = []
        self.stop_strs = stop_strs
        stop_seqs = [s.encode() for s in stop_strs] or None
        self.stop_seqs = stop_seqs
        # byte view of the vocab (probed once): the batcher needs it for
        # stop-seq detection of ANY co-batched job, so it is probed
        # unconditionally and warned about only when this job's stop
        # sequences actually need it
        token_bytes = getattr(tok, "token_bytes", None)
        if token_bytes is not None:
            try:  # base-class stubs raise; probe once
                token_bytes(0)
            except Exception:
                token_bytes = None
        self.token_bytes = token_bytes
        if stop_seqs and token_bytes is None:
            # no byte view: early stopping is off, but render-time
            # truncation below still applies
            warnings.warn(
                "tokenizer lacks token_bytes; stop sequences only "
                "truncate output, they cannot end generation early"
            )

        # Prompt build: system prompt + chat template, then tokenize —
        # ONE prefix-aware batched pass (tokenizer.encode_chat_batch):
        # the shared template shell (chat scaffold + system prompt)
        # encodes once, per-row suffixes in batch, bit-identical ids.
        # Row-level failure domain: if the batched pass raises, fall
        # back to per-row encodes and QUARANTINE only the failing rows
        # (``tokenizer.encode`` fault site) instead of failing the job.
        self._tel_on = telemetry.enabled()
        self.jtel = telemetry.job(job_id) if self._tel_on else None
        self.pre_quarantined: Dict[int, str] = {}
        t_tok = time.monotonic()
        self.token_rows = [
            np.array(ids, np.int32)
            for ids in self._encode_rows(inputs, rec, mcfg)
        ]
        if self._tel_on:
            # span only: the latency histogram sample comes from
            # encode_chat_batch itself (one sample per batched encode)
            telemetry.RECORDER.record(
                "tokenize", job_id, t_tok,
                time.monotonic() - t_tok, {"rows": len(inputs)},
            )
        self.input_tokens = int(sum(len(r) for r in self.token_rows))

        constraint_factory = None
        if rec.output_schema:
            from .constrain import schema_constraint_factory

            constraint_factory = schema_constraint_factory(
                rec.output_schema, tok
            )
            # (the schema-feasibility cap raise happens at submit time
            # so quota and dry-run cost account for the effective cap)

        # cancelled rows carry truncated output — regenerate on resume.
        # Only row ids + finish reasons are held in memory (the done
        # set); row CONTENT lives in the partial chunk store and is
        # merged back at finalize (write_results_streamed), so a
        # 20k-row job's host memory stays O(flush chunk).
        self.done: Dict[int, str] = {
            i: reason
            for i, reason in eng.jobs.read_partial_meta(job_id).items()
            if reason != "cancelled"
        }
        self.pending_flush: List[Dict[str, Any]] = []
        # rows whose tokenize failed never reach the scheduler: they
        # quarantine straight into the partial store as error rows
        for i, msg in self.pre_quarantined.items():
            if i in self.done:
                continue
            self.done[i] = "error"
            self.pending_flush.append(
                {"row_id": i, "outputs": None,
                 "cumulative_logprobs": 0.0, "gen_tokens": 0,
                 "finish_reason": "error", "error": msg}
            )
            self.on_row_event(
                {"event": "row_quarantined", "row_id": i,
                 "attempt": 0, "error": msg}
            )
            if self._tel_on:
                self.jtel.add("rows_quarantined")
                telemetry.ROWS_TOTAL.inc(1.0, "quarantined")

        import jax

        from .dphost import DPWorld

        dp = DPWorld.from_env()
        # under engine-level DP the merged progress stream carries POD
        # throughput, so per-chip numbers divide by pod chips
        # (homogeneous slices), not this rank's
        self.n_chips = max(jax.device_count(), 1) * (
            dp.world if dp else 1
        )
        self._dp = dp is not None
        self.tput = Throughput(self.n_chips)
        # rows/s gauge feed (all workloads live on /metrics): measured
        # over the merged done set — on a dp coordinator that is the
        # pod-wide completion rate
        self.rows_rate = Throughput(1)
        self.cancelled = {"flag": False}

        requests = []
        for i, ids in enumerate(self.token_rows):
            if i in self.done:
                continue
            requests.append(
                GenRequest(
                    row_id=i,
                    prompt_ids=ids,
                    max_new_tokens=max_new,
                    temperature=float(
                        sampling.get(
                            "temperature", eng.ecfg.temperature
                        )
                    ),
                    top_p=float(
                        sampling.get("top_p", eng.ecfg.top_p)
                    ),
                    top_k=int(sampling.get("top_k", eng.ecfg.top_k)),
                    # lazy: the FSM instantiates at ADMISSION time, on
                    # the batcher's prep thread while the device runs
                    # (double-buffered admission) — not 20k up front
                    constraint_factory=constraint_factory,
                    allow_truncate=rec.truncate_rows,
                    row_seed=(
                        i if rec.random_seed_per_input else None
                    ),
                    stop_seqs=stop_seqs,
                    presence_penalty=float(
                        sampling.get("presence_penalty", 0.0)
                    ),
                    frequency_penalty=float(
                        sampling.get("frequency_penalty", 0.0)
                    ),
                    repetition_penalty=float(
                        sampling.get("repetition_penalty", 1.0)
                    ),
                )
            )
        self.requests = requests
        self.ctx = JobCtx(
            job_id=job_id,
            pending=list(requests),
            on_result=self.on_result,
            on_progress=self.on_progress,
            should_cancel=self.should_cancel,
            priority=int(rec.job_priority or 0),
            seq=seq,
            row_retries=eng.ecfg.row_retries,
            on_row_event=self.on_row_event,
            # forensics queue_wait measures from here (build complete,
            # parked for a session) to scheduler adoption
            trace_enq_mono=time.monotonic() if self._tel_on else 0.0,
        )

    def _encode_rows(self, inputs, rec, mcfg) -> List[List[int]]:
        """Batched chat tokenize with per-row quarantine fallback.
        Quarantined rows land in ``self.pre_quarantined`` and get an
        empty token row (never admitted — they enter ``done`` as error
        rows before requests are built)."""
        from .tokenizer import encode_chat_batch

        eng, tok = self.eng, self.tok

        def _inject_rows() -> None:
            for i in range(len(inputs)):
                faults.inject(
                    "tokenizer.encode", row=i, job=self.job_id
                )

        try:
            if faults.ACTIVE is not None:
                _inject_rows()
            return encode_chat_batch(
                tok,
                inputs,
                rec.system_prompt,
                mcfg.chat_template,
                threads=eng.ecfg.tokenize_threads,
            )
        except Exception:  # noqa: BLE001 — row isolation: retry per row
            logger.warning(
                "batched tokenize failed for %s; per-row fallback",
                self.job_id, exc_info=True,
            )
        rows: List[List[int]] = []
        for i, row in enumerate(inputs):
            try:
                if faults.ACTIVE is not None:
                    faults.inject(
                        "tokenizer.encode", row=i, job=self.job_id
                    )
                rows.append(
                    encode_chat_batch(
                        tok, [row], rec.system_prompt, mcfg.chat_template
                    )[0]
                )
            except Exception as e:  # noqa: BLE001 — quarantine the row
                self.pre_quarantined[i] = f"{type(e).__name__}: {e}"
                rows.append([])
        return rows

    # -- streaming callbacks (scheduler thread) ------------------------

    def on_row_event(self, event: Dict[str, Any]) -> None:
        """failure_log sink: every scheduler retry/quarantine decision
        (and the session's own pre-run quarantines) lands on the durable
        job record."""
        self.eng.jobs.append_failure_log(self.job_id, dict(event))

    def render_output(self, token_ids) -> str:
        text = self.tok.decode(token_ids)
        stop_cut = False
        if self.stop_strs:
            # truncate at the FIRST occurrence of any stop string (the
            # stop string itself is excluded, vLLM semantics). Known
            # edge: detection is byte-level while this search is over
            # the decoder's string, so a decoder that normalizes (e.g.
            # strips a leading Metaspace space) can stop generation
            # without a matching cut here — output then keeps the
            # sequence rather than losing text.
            cut = min(
                (
                    p
                    for p in (text.find(s) for s in self.stop_strs)
                    if p >= 0
                ),
                default=-1,
            )
            if cut >= 0:
                text = text[:cut]
                stop_cut = True
        if self.thinking:
            # thinking models emit {content, reasoning_content} JSON so
            # the SDK's unpack contract applies (reference
            # sdk.py:1225-1234)
            reasoning, sep, content = text.partition("</think>")
            if sep:
                reasoning = reasoning.replace("<think>", "").strip()
                content = content.strip()
            elif stop_cut:
                # the stop hit INSIDE the reasoning section (the
                # separator never appeared): keep the chain of thought
                # in reasoning_content, not user-visible content
                reasoning = text.replace("<think>", "").strip()
                content = ""
            else:
                content, reasoning = text, ""
            import json as _json

            return _json.dumps(
                {"content": content, "reasoning_content": reasoning}
            )
        return text

    def on_result(self, res: GenResult) -> None:
        # row-level failure domain: quarantined rows (finish_reason
        # "error*") carry a null output + the error message; a decode
        # failure in the RENDERER is itself quarantined per row rather
        # than failing the job
        err = res.error
        if err is None and res.finish_reason.startswith("error"):
            err = res.finish_reason
        if err is not None:
            outputs = None
        else:
            try:
                outputs = self.render_output(res.token_ids)
            except Exception as e:  # noqa: BLE001 — row isolation
                err = f"{type(e).__name__}: {e}"
                outputs = None
                self.on_row_event(
                    {"event": "row_quarantined", "row_id": res.row_id,
                     "attempt": 0, "error": err}
                )
        row = {
            "row_id": res.row_id,
            "outputs": outputs,
            "cumulative_logprobs": res.cumulative_logprob,
            # true sampled-token count: the denominator matching
            # cumulative_logprobs (re-tokenizing the decoded text would
            # drop stop tokens and need not round-trip)
            "gen_tokens": len(res.token_ids),
            "finish_reason": res.finish_reason if err is None or
            res.finish_reason.startswith("error") else "error",
            "error": err,
        }
        if self._tel_on:
            # exact per-job accounting (reconciles against results):
            # quarantined beats cancelled beats ok
            outcome = (
                "quarantined" if err is not None
                else "cancelled" if res.finish_reason == "cancelled"
                else "ok"
            )
            self.jtel.add(f"rows_{outcome}")
            telemetry.ROWS_TOTAL.inc(1.0, outcome)
        self.done[res.row_id] = row["finish_reason"]
        self.pending_flush.append(row)
        if len(self.pending_flush) >= _PARTIAL_FLUSH_EVERY:
            self.flush()
        # batched row progress (same rule as the embedding path): rows
        # advance on the stream between the scheduler's 1 s ticks
        # without a per-row bus publish
        self.row_progress.update(len(self.done))

    def on_progress(self, p: Dict[str, Any]) -> None:
        self.row_progress.flush(len(self.done))
        self.tput.note_total(p["input_tokens"] + p["output_tokens"])
        if self._tel_on:
            # the Throughput estimator folded into registry gauges
            # (same per-chip division the progress stream reports)
            telemetry.TOKENS_PER_SECOND.set(
                p["total_tokens_processed_per_second"]
            )
            telemetry.TOKENS_PER_SECOND_PER_CHIP.set(
                p["total_tokens_processed_per_second"] / self.n_chips
            )
            self.rows_rate.note_total(len(self.done))
            telemetry.ROWS_PER_SECOND.set(
                self.rows_rate.per_second(),
                "dp" if self._dp else "generate",
            )
        self.jm.tokens(
            {
                "input_tokens": p["input_tokens"],
                "output_tokens": p["output_tokens"],
                "total_tokens_processed_per_second": p[
                    "total_tokens_processed_per_second"
                ],
                "tokens_per_second_per_chip": p[
                    "total_tokens_processed_per_second"
                ]
                / self.n_chips,
            }
        )

    def should_cancel(self) -> bool:
        if self.job_id in self.eng._cancel:
            self.cancelled["flag"] = True
            return True
        return False

    # -- terminal transitions (engine worker thread) -------------------

    def flush(self) -> None:
        if self.pending_flush:
            self.eng.jobs.flush_partial(
                self.job_id, list(self.pending_flush)
            )
            self.pending_flush.clear()

    def finalize_cancelled(self) -> None:
        self.flush()
        self.eng.jobs.set_status(self.job_id, JobStatus.CANCELLED)

    def finalize_completed(self, batcher) -> None:
        """Order, account, and persist final results (the 1:1
        input-order contract) via the jobstore's merge-on-read streamed
        writer — results assemble one chunk at a time from the partial
        store, never materializing the whole job. Output-token
        accounting rides the same pass (``on_chunk``). ``batcher.timer``
        is the SESSION's timer: under co-batching the perf profile
        spans every job that shared the batch."""
        self.flush()
        rec = self.rec
        counted = {"output_tokens": 0}

        def _count_chunk(df) -> None:
            counted["output_tokens"] += int(
                sum(
                    len(self.tok.encode(o)) if o else 0
                    for o in df["outputs"].tolist()
                )
            )

        self.eng.jobs.write_results_streamed(
            self.job_id, rec.num_rows, on_chunk=_count_chunk
        )
        output_tokens = counted["output_tokens"]
        perf = dict(batcher.timer.summary())
        drafted = self.ctx.stats.get("spec_drafted", 0)
        if drafted:
            # n-gram speculative acceptance rate (the VERDICT's metric)
            accepted = self.ctx.stats.get("spec_accepted", 0)
            perf["spec_ngram"] = {
                "drafted": drafted,
                "accepted": accepted,
                "acceptance_rate": round(accepted / drafted, 3),
            }
        ff = self.ctx.stats.get("ff_forced", 0)
        if ff:
            # FSM fast-forward: scaffold tokens committed through
            # parallel verify forwards instead of per-step windows
            perf["fastforward"] = {"forced_tokens": ff}
        if self._tel_on:
            self.jtel.set("input_tokens", self.input_tokens)
            self.jtel.set("output_tokens", output_tokens)
            telemetry.TOKENS_TOTAL.inc(float(self.input_tokens), "in")
            telemetry.TOKENS_TOTAL.inc(float(output_tokens), "out")
            # close the job's forensics trace (started at scheduler
            # adoption); interactive traces end in gateway.finish()
            telemetry.TRACES.end_trace(f"tr-{self.job_id}", "ok")
        self.eng.jobs.update(
            self.job_id,
            input_tokens=self.input_tokens,
            output_tokens=output_tokens,
            job_cost=estimate_cost(
                self.engine_key, self.input_tokens, output_tokens
            ),
            perf=perf,
        )
        self.jm.progress(rec.num_rows)
        # results.parquet is already fully written (atomic rename in
        # write_results_streamed) — flipping to SUCCEEDED last keeps the
        # results-before-status invariant
        self.eng.jobs.set_status(self.job_id, JobStatus.SUCCEEDED)


# ---------------------------------------------------------------------------
# Singleton
# ---------------------------------------------------------------------------

_engine: Optional[LocalEngine] = None
_engine_lock = threading.Lock()


def get_engine(ecfg: Optional[EngineConfig] = None) -> LocalEngine:
    global _engine
    with _engine_lock:
        if _engine is None:
            _engine = LocalEngine(ecfg)
        return _engine


def reset_engine() -> None:
    """Test hook: drop the singleton. The outgoing worker gets a
    bounded stop (idle workers exit immediately; a worker mid-job is
    left to finish on its daemon thread rather than blocking the
    reset)."""
    global _engine
    with _engine_lock:
        old, _engine = _engine, None
    if old is not None:
        old.close(timeout=2.0)
