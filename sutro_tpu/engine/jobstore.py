"""Job store: records, states, durable results, quotas, cost model.

Replaces the remote service's job control plane (reference wire contract
SURVEY §3.6: /jobs/{id}, /job-status/{id}, /list-jobs, /job-results,
/job-cancel, /get-quotas). Layout under ``$SUTRO_HOME/jobs/<job_id>/``:

- ``record.json``   — the job record (status, counters, timestamps, config)
- ``inputs.parquet``  — materialized input rows (row_id, inputs)
- ``partial.parquet`` — completed rows flushed during the run (row-granular
  resume, SURVEY §5.3: a preempted run restarts at row granularity)
- ``results.parquet`` — final ordered results

Invariants (SURVEY §5.2 — replace the reference's results-availability
retry race, sdk.py:384-401, with real guarantees):

- single writer: only the engine worker thread mutates a running job;
- ``results.parquet`` is fully written and flushed *before* the record
  flips to SUCCEEDED, so "status==SUCCEEDED" implies "results readable".
"""

from __future__ import annotations

import dataclasses
import datetime
import json
import threading
import uuid
from pathlib import Path
from typing import Any, Dict, List, Optional

import pandas as pd

from ..interfaces import JobStatus
from ..validation import config_dir

# ---------------------------------------------------------------------------
# Cost model (USD per 1M tokens). The reference surfaces only a server-side
# `cost_estimate` (sdk.py:245-262); this local model prices by param count
# against chip-seconds, tuned so the north-star comparison vs the OpenAI
# Batch API (BASELINE.json) is honest: numbers chosen to approximate
# v5e on-demand $/chip-hour amortized over measured tok/s/chip tiers.
# ---------------------------------------------------------------------------

COST_PER_MTOK: Dict[str, Dict[str, float]] = {
    # engine_key prefix -> {input, output}
    "qwen3-0.6b": {"input": 0.01, "output": 0.02},
    "qwen3-4b": {"input": 0.04, "output": 0.08},
    "qwen3-8b": {"input": 0.07, "output": 0.15},
    "qwen3-14b": {"input": 0.12, "output": 0.25},
    "qwen3-32b": {"input": 0.25, "output": 0.50},
    "qwen3-30b-a3b": {"input": 0.10, "output": 0.20},
    "qwen3-235b-a22b": {"input": 0.50, "output": 1.00},
    "llama-3.2-3b": {"input": 0.03, "output": 0.06},
    "llama-3.1-8b": {"input": 0.07, "output": 0.15},
    "llama-3.3-70b": {"input": 0.45, "output": 0.90},
    "gemma3-4b": {"input": 0.04, "output": 0.08},
    "gemma3-12b": {"input": 0.10, "output": 0.22},
    "gemma3-27b": {"input": 0.22, "output": 0.45},
    "gpt-oss-20b": {"input": 0.06, "output": 0.12},
    "gpt-oss-120b": {"input": 0.25, "output": 0.50},
    "qwen3-emb-0.6b": {"input": 0.01, "output": 0.01},
    "qwen3-emb-6b": {"input": 0.05, "output": 0.05},
    "qwen3-emb-8b": {"input": 0.07, "output": 0.07},
}
_DEFAULT_COST = {"input": 0.10, "output": 0.20}

# Per-priority quotas (rows, tokens) — reference /get-quotas shape: a list
# indexed by priority, each {row_quota, token_quota} (sdk.py:1547-1561,
# cli.py:406-411). NOTE on the BASELINE "priority -> pod-slice size"
# mapping: in this build priority selects quota table + scheduling
# precedence (p0 preempts running p1 jobs, tests/test_priority.py), NOT
# engine/pod sizing. Slice-count selection per priority belongs to the
# pod launcher, which sets SUTRO_DP_WORLD per engine process group
# (engine/dphost.py); a single-host engine has nothing to size. Recorded
# as out of scope in PARITY.md.
DEFAULT_QUOTAS: List[Dict[str, int]] = [
    {"row_quota": 500_000, "token_quota": 500_000_000},
    {"row_quota": 5_000_000, "token_quota": 5_000_000_000},
]


def estimate_cost(
    engine_key: str, input_tokens: int, output_tokens: int
) -> float:
    rates = COST_PER_MTOK.get(engine_key, _DEFAULT_COST)
    return (
        input_tokens * rates["input"] + output_tokens * rates["output"]
    ) / 1e6


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


@dataclasses.dataclass
class JobRecord:
    job_id: str
    status: str = JobStatus.QUEUED.value
    name: Optional[str] = None
    description: Optional[str] = None
    model: str = ""
    engine_key: str = ""
    num_rows: int = 0
    job_priority: int = 0
    datetime_created: str = dataclasses.field(default_factory=_now)
    datetime_started: Optional[str] = None
    datetime_completed: Optional[str] = None
    input_tokens: int = 0
    output_tokens: int = 0
    cost_estimate: Optional[float] = None
    job_cost: Optional[float] = None
    failure_reason: Optional[Dict[str, Any]] = None
    output_schema: Optional[Dict[str, Any]] = None
    system_prompt: Optional[str] = None
    sampling_params: Optional[Dict[str, Any]] = None
    truncate_rows: bool = True
    dry_run: bool = False
    random_seed_per_input: bool = False
    # per-job latency profile (engine/profiling.py StepTimer.summary())
    perf: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class JobStore:
    def __init__(self, root: Optional[Path] = None):
        self.root = Path(root) if root else (config_dir() / "jobs")
        self.root.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()

    # -- paths -----------------------------------------------------------
    def _dir(self, job_id: str) -> Path:
        return self.root / job_id

    def _record_path(self, job_id: str) -> Path:
        return self._dir(job_id) / "record.json"

    # -- record lifecycle ------------------------------------------------
    def create(self, **fields: Any) -> JobRecord:
        job_id = fields.pop("job_id", None) or f"job-{uuid.uuid4().hex[:16]}"
        rec = JobRecord(job_id=job_id, **fields)
        d = self._dir(job_id)
        d.mkdir(parents=True, exist_ok=True)
        self._write_record(rec)
        return rec

    def _write_record(self, rec: JobRecord) -> None:
        path = self._record_path(rec.job_id)
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(rec.to_dict(), indent=2))
        tmp.replace(path)  # atomic on POSIX

    def get(self, job_id: str) -> JobRecord:
        path = self._record_path(job_id)
        if not path.exists():
            raise KeyError(f"Unknown job: {job_id}")
        data = json.loads(path.read_text())
        fields = {f.name for f in dataclasses.fields(JobRecord)}
        return JobRecord(**{k: v for k, v in data.items() if k in fields})

    def update(self, job_id: str, **fields: Any) -> JobRecord:
        with self._lock:
            rec = self.get(job_id)
            for k, v in fields.items():
                setattr(rec, k, v)
            self._write_record(rec)
            return rec

    def set_status(self, job_id: str, status: JobStatus, **extra: Any) -> None:
        fields: Dict[str, Any] = {"status": status.value, **extra}
        if status == JobStatus.RUNNING:
            fields.setdefault("datetime_started", _now())
        if status.is_terminal():
            fields.setdefault("datetime_completed", _now())
        self.update(job_id, **fields)

    def status(self, job_id: str) -> JobStatus:
        return JobStatus(self.get(job_id).status)

    def list_jobs(self) -> List[Dict[str, Any]]:
        """Newest-first job records (reference /list-jobs, cli.py:157-196)."""
        out = []
        for d in self.root.iterdir():
            if (d / "record.json").exists():
                try:
                    out.append(self.get(d.name).to_dict())
                except Exception:
                    continue
        out.sort(key=lambda r: r.get("datetime_created") or "", reverse=True)
        return out

    def delete(self, job_id: str) -> None:
        import shutil

        shutil.rmtree(self._dir(job_id), ignore_errors=True)

    # -- inputs / results -------------------------------------------------
    def write_inputs(self, job_id: str, rows: List[str]) -> None:
        df = pd.DataFrame({"row_id": range(len(rows)), "inputs": rows})
        df.to_parquet(self._dir(job_id) / "inputs.parquet")

    def read_inputs(self, job_id: str) -> List[str]:
        df = pd.read_parquet(self._dir(job_id) / "inputs.parquet")
        return df.sort_values("row_id")["inputs"].tolist()

    def flush_partial(self, job_id: str, rows: List[Dict[str, Any]]) -> None:
        """Append-flush completed rows for row-granular resume (§5.3)."""
        if not rows:
            return
        path = self._dir(job_id) / "partial.parquet"
        df = pd.DataFrame(rows)
        if path.exists():
            df = pd.concat([pd.read_parquet(path), df], ignore_index=True)
        tmp = path.with_suffix(".parquet.tmp")
        df.to_parquet(tmp)
        tmp.replace(path)

    def read_partial(self, job_id: str) -> Dict[int, Dict[str, Any]]:
        path = self._dir(job_id) / "partial.parquet"
        if not path.exists():
            return {}
        df = pd.read_parquet(path)
        return {int(r["row_id"]): dict(r) for _, r in df.iterrows()}

    def finalize_results(
        self, job_id: str, results: Dict[str, List[Any]]
    ) -> None:
        """Write final results THEN flip to SUCCEEDED (ordering invariant)."""
        df = pd.DataFrame(results)
        tmp = self._dir(job_id) / "results.parquet.tmp"
        df.to_parquet(tmp)
        tmp.replace(self._dir(job_id) / "results.parquet")
        self.set_status(job_id, JobStatus.SUCCEEDED)

    def read_results(self, job_id: str) -> pd.DataFrame:
        path = self._dir(job_id) / "results.parquet"
        if not path.exists():
            status = self.status(job_id)
            raise FileNotFoundError(
                f"Results for {job_id} not available (status={status.value})"
            )
        return pd.read_parquet(path)

    # -- quotas ----------------------------------------------------------
    def get_quotas(self) -> List[Dict[str, int]]:
        path = config_dir() / "quotas.json"
        if path.exists():
            try:
                return json.loads(path.read_text())
            except Exception:
                pass
        return [dict(q) for q in DEFAULT_QUOTAS]

    def check_quota(
        self, priority: int, num_rows: int, est_tokens: int
    ) -> Optional[str]:
        quotas = self.get_quotas()
        q = quotas[min(max(priority, 0), len(quotas) - 1)]
        if num_rows > q["row_quota"]:
            return (
                f"Row count {num_rows} exceeds priority-{priority} quota "
                f"{q['row_quota']}"
            )
        if est_tokens > q["token_quota"]:
            return (
                f"Estimated tokens {est_tokens} exceed priority-{priority} "
                f"quota {q['token_quota']}"
            )
        return None
