"""Job store: records, states, durable results, quotas, cost model.

Replaces the remote service's job control plane (reference wire contract
SURVEY §3.6: /jobs/{id}, /job-status/{id}, /list-jobs, /job-results,
/job-cancel, /get-quotas). Layout under ``$SUTRO_HOME/jobs/<job_id>/``:

- ``record.json``   — the job record (status, counters, timestamps, config)
- ``inputs.parquet``  — materialized input rows (row_id, inputs)
- ``partial/``      — completed rows flushed during the run as immutable
  chunk files ``b<bucket>-s<seq>.parquet`` (bucket = row_id //
  chunk_rows, seq = per-flush monotonic counter). Each flush writes
  ONLY its own rows — O(chunk) per flush instead of the old
  read-concat-rewrite of ``partial.parquet`` (O(total), quadratic over
  a job). A legacy ``partial.parquet`` is still read (seq −1) so
  pre-upgrade jobs resume.
- ``results.parquet`` — final ordered results. Generation jobs write it
  with ``write_results_streamed``: a merge-on-read pass over the
  partial buckets, one row-group per bucket, so peak host memory is
  O(chunk_rows), not O(job).

Invariants (SURVEY §5.2 — replace the reference's results-availability
retry race, sdk.py:384-401, with real guarantees):

- single writer: only the engine worker thread mutates a running job;
- ``results.parquet`` is fully written and flushed *before* the record
  flips to SUCCEEDED, so "status==SUCCEEDED" implies "results readable".
"""

from __future__ import annotations

import dataclasses
import datetime
import json
import logging
import threading
import time
import uuid
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

import pandas as pd

logger = logging.getLogger(__name__)

from .. import telemetry
from ..interfaces import JobStatus
from ..validation import config_dir
from . import faults
from .faults import retry_transient

# ---------------------------------------------------------------------------
# Cost model (USD per 1M tokens). The reference surfaces only a server-side
# `cost_estimate` (sdk.py:245-262); this local model prices by param count
# against chip-seconds, tuned so the north-star comparison vs the OpenAI
# Batch API (BASELINE.json) is honest: numbers chosen to approximate
# v5e on-demand $/chip-hour amortized over measured tok/s/chip tiers.
# ---------------------------------------------------------------------------

COST_PER_MTOK: Dict[str, Dict[str, float]] = {
    # engine_key prefix -> {input, output}
    "qwen3-0.6b": {"input": 0.01, "output": 0.02},
    "qwen3-4b": {"input": 0.04, "output": 0.08},
    "qwen3-8b": {"input": 0.07, "output": 0.15},
    "qwen3-14b": {"input": 0.12, "output": 0.25},
    "qwen3-32b": {"input": 0.25, "output": 0.50},
    "qwen3-30b-a3b": {"input": 0.10, "output": 0.20},
    "qwen3-235b-a22b": {"input": 0.50, "output": 1.00},
    "llama-3.2-3b": {"input": 0.03, "output": 0.06},
    "llama-3.1-8b": {"input": 0.07, "output": 0.15},
    "llama-3.3-70b": {"input": 0.45, "output": 0.90},
    "gemma3-4b": {"input": 0.04, "output": 0.08},
    "gemma3-12b": {"input": 0.10, "output": 0.22},
    "gemma3-27b": {"input": 0.22, "output": 0.45},
    "gpt-oss-20b": {"input": 0.06, "output": 0.12},
    "gpt-oss-120b": {"input": 0.25, "output": 0.50},
    "qwen3-emb-0.6b": {"input": 0.01, "output": 0.01},
    "qwen3-emb-6b": {"input": 0.05, "output": 0.05},
    "qwen3-emb-8b": {"input": 0.07, "output": 0.07},
}
_DEFAULT_COST = {"input": 0.10, "output": 0.20}

# Per-priority quotas (rows, tokens) — reference /get-quotas shape: a list
# indexed by priority, each {row_quota, token_quota} (sdk.py:1547-1561,
# cli.py:406-411). NOTE on the BASELINE "priority -> pod-slice size"
# mapping: in this build priority selects quota table + scheduling
# precedence (p0 preempts running p1 jobs, tests/test_priority.py), NOT
# engine/pod sizing. Slice-count selection per priority belongs to the
# pod launcher, which sets SUTRO_DP_WORLD per engine process group
# (engine/dphost.py); a single-host engine has nothing to size. Recorded
# as out of scope in PARITY.md.
DEFAULT_QUOTAS: List[Dict[str, int]] = [
    {"row_quota": 500_000, "token_quota": 500_000_000},
    {"row_quota": 5_000_000, "token_quota": 5_000_000_000},
]


class InvalidPriority(ValueError):
    """Out-of-range ``job_priority`` at submit. Structured (PAPER.md
    quota semantics): priorities index the quota table, so a value
    outside it is a caller error, not something to silently clamp.
    The HTTP layer maps this to 400 with ``code=INVALID_PRIORITY``."""

    code = "INVALID_PRIORITY"
    status = 400

    def __init__(self, priority: Any, n_levels: int) -> None:
        self.priority = priority
        self.n_levels = n_levels
        super().__init__(
            f"job_priority {priority!r} is out of range: the quota "
            f"table defines priorities 0..{n_levels - 1}"
        )


def estimate_cost(
    engine_key: str, input_tokens: int, output_tokens: int
) -> float:
    rates = COST_PER_MTOK.get(engine_key, _DEFAULT_COST)
    return (
        input_tokens * rates["input"] + output_tokens * rates["output"]
    ) / 1e6


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


@dataclasses.dataclass
class JobRecord:
    job_id: str
    status: str = JobStatus.QUEUED.value
    name: Optional[str] = None
    description: Optional[str] = None
    model: str = ""
    engine_key: str = ""
    num_rows: int = 0
    job_priority: int = 0
    datetime_created: str = dataclasses.field(default_factory=_now)
    datetime_started: Optional[str] = None
    datetime_completed: Optional[str] = None
    input_tokens: int = 0
    output_tokens: int = 0
    cost_estimate: Optional[float] = None
    job_cost: Optional[float] = None
    failure_reason: Optional[Dict[str, Any]] = None
    # structured, bounded event trail: every retry / per-row quarantine /
    # terminal failure appends here (reference sessions carry the same
    # ``failure_log[]`` — SURVEY §5.3; schema in FAILURES.md)
    failure_log: Optional[List[Dict[str, Any]]] = None
    output_schema: Optional[Dict[str, Any]] = None
    system_prompt: Optional[str] = None
    sampling_params: Optional[Dict[str, Any]] = None
    truncate_rows: bool = True
    dry_run: bool = False
    random_seed_per_input: bool = False
    # tenant attribution (telemetry/monitor.py): submit-time identity
    # every series and terminal accounting row is keyed by; "default"
    # when the caller names none
    tenant: Optional[str] = None
    # per-job latency profile (engine/profiling.py StepTimer.summary())
    perf: Optional[Dict[str, Any]] = None
    # Stage-graph job (engine/stagegraph.py): the validated stage list
    # exactly as submitted (None for plain jobs — the off switch), plus
    # a durable per-stage rollup {name: {status, rows_done, rows_total,
    # quarantined}} updated as stage chunks finalize. Both ride the
    # record's forward-compatible JSON (get() filters unknown keys), so
    # old records and stage-less jobs round-trip untouched.
    stages: Optional[List[Dict[str, Any]]] = None
    stages_state: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class JobStore:
    # failure_log entries kept per job (oldest dropped first): the log is
    # an incident trail, not a metrics store — bounded so a pathological
    # job can't grow its record without limit
    _FAILURE_LOG_CAP = 200

    def __init__(
        self,
        root: Optional[Path] = None,
        chunk_rows: Optional[int] = None,
        io_retries: Optional[int] = None,
        io_backoff: Optional[float] = None,
        io_backoff_cap: Optional[float] = None,
    ):
        import os

        self.root = Path(root) if root else (config_dir() / "jobs")
        self.root.mkdir(parents=True, exist_ok=True)
        # result/partial chunk granularity: the unit of per-flush I/O
        # AND the peak materialized row count during finalization
        self.chunk_rows = int(
            chunk_rows
            if chunk_rows is not None
            else os.environ.get("SUTRO_RESULT_CHUNK", "1024")
        )
        if self.chunk_rows < 1:
            self.chunk_rows = 1
        # transient-I/O retry policy (exponential backoff + jitter,
        # bounded attempts — engine/faults.retry_transient): a blip in
        # the store must not fail a 20k-row job, a dead disk still must
        self.io_retries = int(
            io_retries
            if io_retries is not None
            else os.environ.get("SUTRO_IO_RETRIES", "4")
        )
        self.io_backoff = float(
            io_backoff
            if io_backoff is not None
            else os.environ.get("SUTRO_IO_BACKOFF", "0.05")
        )
        self.io_backoff_cap = float(
            io_backoff_cap
            if io_backoff_cap is not None
            else os.environ.get("SUTRO_IO_BACKOFF_CAP", "2.0")
        )
        self._lock = threading.Lock()
        self._flush_seq: Dict[str, int] = {}  # job_id -> next chunk seq
        # terminal-transition hook (engine/control.py refunds a job's
        # unused admission reserve here). Called once per terminal
        # transition with the fresh JobRecord; best-effort — a hook
        # error must never corrupt the status funnel.
        self.on_terminal: Optional[Callable[[JobRecord], None]] = None

    # -- paths -----------------------------------------------------------
    def _dir(self, job_id: str) -> Path:
        return self.root / job_id

    def _record_path(self, job_id: str) -> Path:
        return self._dir(job_id) / "record.json"

    # -- record lifecycle ------------------------------------------------
    def create(self, **fields: Any) -> JobRecord:
        job_id = fields.pop("job_id", None) or f"job-{uuid.uuid4().hex[:16]}"
        rec = JobRecord(job_id=job_id, **fields)
        d = self._dir(job_id)
        d.mkdir(parents=True, exist_ok=True)
        self._write_record(rec)
        return rec

    def _write_record(self, rec: JobRecord) -> None:
        path = self._record_path(rec.job_id)
        tmp = path.with_suffix(".json.tmp")
        # small local record write; when reached from ``update`` it runs
        # under the store lock — that read-modify-write IS the lock's
        # critical section
        # graftlint: disable=lock-blocking-call
        tmp.write_text(json.dumps(rec.to_dict(), indent=2))
        tmp.replace(path)  # atomic on POSIX

    def get(self, job_id: str) -> JobRecord:
        path = self._record_path(job_id)
        if not path.exists():
            raise KeyError(f"Unknown job: {job_id}")
        # tiny local JSON; under the lock only via ``update`` (the RMW)
        # graftlint: disable=lock-blocking-call
        data = json.loads(path.read_text())
        fields = {f.name for f in dataclasses.fields(JobRecord)}
        return JobRecord(**{k: v for k, v in data.items() if k in fields})

    def update(self, job_id: str, **fields: Any) -> JobRecord:
        with self._lock:
            rec = self.get(job_id)
            for k, v in fields.items():
                setattr(rec, k, v)
            self._write_record(rec)
            return rec

    def set_status(self, job_id: str, status: JobStatus, **extra: Any) -> None:
        fields: Dict[str, Any] = {"status": status.value, **extra}
        if status == JobStatus.RUNNING:
            fields.setdefault("datetime_started", _now())
        if status.is_terminal():
            fields.setdefault("datetime_completed", _now())
        rec = self.update(job_id, **fields)
        if telemetry.ENABLED and status in (
            JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.CANCELLED
        ):
            # terminal TRANSITIONS (a resumed-then-failed job counts
            # twice — each is a real lifecycle event)
            telemetry.JOBS_TOTAL.inc(1.0, status.value.lower())
            # tenant attribution settles at the same funnel: rows from
            # the job's exact counters, tokens from the record's
            # accounting — every terminal path (generate, embed, dp,
            # resume) passes through here exactly once per transition
            tenant = str(rec.tenant or "default")
            jc = telemetry.JOBS.peek(job_id)
            if jc is not None:
                d = jc.to_dict()
                if d.get("rows_ok"):
                    telemetry.TENANT_ROWS_TOTAL.inc(
                        float(d["rows_ok"]), tenant, "ok"
                    )
                if d.get("rows_quarantined"):
                    telemetry.TENANT_ROWS_TOTAL.inc(
                        float(d["rows_quarantined"]), tenant,
                        "quarantined",
                    )
            if rec.input_tokens:
                telemetry.TENANT_TOKENS_TOTAL.inc(
                    float(rec.input_tokens), tenant, "in"
                )
            if rec.output_tokens:
                telemetry.TENANT_TOKENS_TOTAL.inc(
                    float(rec.output_tokens), tenant, "out"
                )
        if telemetry.ENABLED and status == JobStatus.CANCELLED:
            # CANCELLED dumps the flight recorder like FAILED does
            # (engine/api.py handles FAILED at its failure boundaries):
            # a cancelled 20k-row job is exactly when an operator asks
            # "how far did it get, and why was it slow" — this is the
            # one funnel every cancel path passes through
            telemetry.dump_job(self._dir(job_id), job_id)
        if status.is_terminal() and self.on_terminal is not None:
            try:
                self.on_terminal(rec)
            except Exception:  # noqa: BLE001 — the hook (control-plane
                # refund) is best-effort; the status funnel is not
                logger.warning(
                    "on_terminal hook failed for %s", job_id,
                    exc_info=True,
                )

    def status(self, job_id: str) -> JobStatus:
        return JobStatus(self.get(job_id).status)

    def append_failure_log(
        self, job_id: str, event: Dict[str, Any]
    ) -> None:
        """Append one structured event to the job's bounded
        ``failure_log[]`` (retry / quarantine / terminal failure — the
        reference session schema). Best-effort by design: recording a
        recovery must never itself become a new failure. ``ts`` is
        stamped here so callers only describe the event."""
        ev = {"ts": _now(), **event}
        if telemetry.ENABLED:
            # the single funnel every retry/quarantine/terminal event
            # passes through — one counter covers them all. The label
            # domain is the fixed event-kind vocabulary; a non-string
            # (malformed caller) collapses to one series instead of
            # str()-coercing arbitrary objects into label values.
            kind = event.get("event")
            telemetry.ROW_EVENTS_TOTAL.inc(
                1.0, kind if isinstance(kind, str) else "unknown"
            )
        try:
            # inline RMW (``update`` would re-take the non-reentrant
            # store lock); the record write IS the critical section
            with self._lock:
                rec = self.get(job_id)
                log = list(rec.failure_log or [])
                log.append(ev)
                if len(log) > self._FAILURE_LOG_CAP:
                    log = log[-self._FAILURE_LOG_CAP :]
                rec.failure_log = log
                self._write_record(rec)
        except Exception:
            logger.warning(
                "failure_log append failed for %s (event %r)",
                job_id, event.get("event"), exc_info=True,
            )

    def list_jobs(self) -> List[Dict[str, Any]]:
        """Newest-first job records (reference /list-jobs, cli.py:157-196)."""
        out = []
        for d in self.root.iterdir():
            if (d / "record.json").exists():
                try:
                    out.append(self.get(d.name).to_dict())
                except (KeyError, TypeError, ValueError, OSError) as e:
                    # a torn/foreign record must not break the listing,
                    # but the skip has to be visible
                    logger.warning(
                        "skipping unreadable job record %s: %s", d.name, e
                    )
                    continue
        out.sort(key=lambda r: r.get("datetime_created") or "", reverse=True)
        return out

    def delete(self, job_id: str) -> None:
        import shutil

        shutil.rmtree(self._dir(job_id), ignore_errors=True)

    # -- inputs / results -------------------------------------------------
    def write_inputs(self, job_id: str, rows: List[str]) -> None:
        df = pd.DataFrame({"row_id": range(len(rows)), "inputs": rows})
        df.to_parquet(self._dir(job_id) / "inputs.parquet")

    def read_inputs(self, job_id: str) -> List[str]:
        df = pd.read_parquet(self._dir(job_id) / "inputs.parquet")
        return df.sort_values("row_id")["inputs"].tolist()

    def _partial_dir(self, job_id: str) -> Path:
        return self._dir(job_id) / "partial"

    def _partial_chunks(self, job_id: str) -> List[tuple]:
        """All partial chunk files as ``(bucket, seq, path)``, unsorted.
        Filenames are ``b<bucket>-s<seq>.parquet``; later seq wins on
        duplicate row_ids (a resumed run regenerating a cancelled row
        flushes a fresh entry with a higher seq)."""
        d = self._partial_dir(job_id)
        if not d.is_dir():
            return []
        out = []
        for p in d.iterdir():
            name = p.name
            if not (name.startswith("b") and name.endswith(".parquet")):
                continue
            try:
                b_part, s_part = name[1 : -len(".parquet")].split("-s")
                out.append((int(b_part), int(s_part), p))
            except ValueError:
                continue
        return out

    def _next_flush_seq(self, job_id: str) -> int:
        with self._lock:
            seq = self._flush_seq.get(job_id)
            if seq is None:  # first flush this process: resume the count
                seq = (
                    max(
                        (s for _, s, _ in self._partial_chunks(job_id)),
                        default=-1,
                    )
                    + 1
                )
            self._flush_seq[job_id] = seq + 1
            return seq

    def flush_partial(self, job_id: str, rows: List[Dict[str, Any]]) -> None:
        """Append-flush completed rows for row-granular resume (§5.3).

        O(len(rows)) per call: each flush lands as immutable chunk
        files under ``partial/`` split by row_id bucket (the old
        single-file scheme re-read and re-wrote the WHOLE partial store
        every flush — quadratic over a long job).

        Transient-fault domain: OSError flushes retry with exponential
        backoff + jitter, bounded by ``io_retries``, each retry recorded
        in the job's ``failure_log[]``; chunks are idempotent (a fresh
        seq per attempt, later seq wins on duplicate row_ids), so a
        half-landed attempt is harmless."""
        if not rows:
            return
        t0 = time.monotonic()
        retry_transient(
            lambda: self._flush_partial_once(job_id, rows),
            attempts=self.io_retries,
            base=self.io_backoff,
            cap=self.io_backoff_cap,
            retry_on=(OSError,),
            on_retry=lambda attempt, delay, exc: self.append_failure_log(
                job_id,
                {"event": "io_retry", "site": "jobstore.flush_partial",
                 "attempt": attempt,
                 "error": f"{type(exc).__name__}: {exc}"},
            ),
            what=f"flush_partial[{job_id}]",
        )
        if telemetry.ENABLED:
            dt = time.monotonic() - t0
            telemetry.stage_observe("flush", dt)
            telemetry.RECORDER.record(
                "flush", job_id, t0, dt, {"rows": len(rows)}
            )

    def _flush_partial_once(
        self, job_id: str, rows: List[Dict[str, Any]]
    ) -> None:
        if faults.ACTIVE is not None:
            spec = faults.fire("jobstore.flush_partial", job=job_id)
            if spec is not None:
                if spec.kind == "torn":
                    # simulate a crash mid-flush on a non-durable fs:
                    # a chunk file exists at its FINAL name with only
                    # part of its bytes (readers must skip+quarantine
                    # it; the retry lands a good chunk at a higher seq)
                    self._write_torn_chunk(job_id, rows)
                spec.trigger()
        d = self._partial_dir(job_id)
        d.mkdir(parents=True, exist_ok=True)
        seq = self._next_flush_seq(job_id)
        by_bucket: Dict[int, List[Dict[str, Any]]] = {}
        for r in rows:
            by_bucket.setdefault(
                int(r["row_id"]) // self.chunk_rows, []
            ).append(r)
        for bucket, rs in by_bucket.items():
            df = pd.DataFrame(rs).sort_values("row_id")
            path = d / f"b{bucket:08d}-s{seq:08d}.parquet"
            tmp = path.with_suffix(".parquet.tmp")
            df.to_parquet(tmp)
            tmp.replace(path)  # atomic on POSIX

    def _write_torn_chunk(
        self, job_id: str, rows: List[Dict[str, Any]]
    ) -> None:
        """Fault-plan helper (kind ``torn``): land a truncated chunk
        file at a real chunk name, as a crash between write and fsync
        would on a non-durable filesystem."""
        import io

        d = self._partial_dir(job_id)
        d.mkdir(parents=True, exist_ok=True)
        seq = self._next_flush_seq(job_id)
        bucket = int(rows[0]["row_id"]) // self.chunk_rows
        buf = io.BytesIO()
        pd.DataFrame(rows).to_parquet(buf)
        data = buf.getvalue()
        (d / f"b{bucket:08d}-s{seq:08d}.parquet").write_bytes(
            data[: max(8, len(data) // 2)]
        )

    def _read_chunk(
        self, job_id: str, path: Path, columns: Optional[List[str]] = None
    ) -> Optional[pd.DataFrame]:
        """Read one partial chunk, tolerating a torn/corrupt file (crash
        mid-flush): the bad chunk is quarantined to ``partial/.corrupt/``
        and logged instead of failing the WHOLE store — its rows simply
        regenerate on resume. Returns None for a quarantined chunk."""
        try:
            return pd.read_parquet(path, columns=columns)
        except Exception as e:  # pyarrow raises ArrowInvalid/OSError/...
            logger.warning(
                "quarantining corrupt partial chunk %s: %s", path, e
            )
            try:
                cdir = path.parent / ".corrupt"
                cdir.mkdir(exist_ok=True)
                path.replace(cdir / path.name)
            except OSError:
                logger.warning(
                    "could not quarantine %s", path, exc_info=True
                )
            self.append_failure_log(
                job_id,
                {"event": "torn_chunk_quarantined", "chunk": path.name,
                 "error": f"{type(e).__name__}: {e}"},
            )
            return None

    def _legacy_partial(self, job_id: str) -> Optional[pd.DataFrame]:
        path = self._dir(job_id) / "partial.parquet"
        if not path.exists():
            return None
        return pd.read_parquet(path)

    def read_partial(self, job_id: str) -> Dict[int, Dict[str, Any]]:
        """Full partial rows (legacy file first, then chunks in seq
        order, later writes winning). O(done rows) memory — callers
        that only need row ids/reasons use ``read_partial_meta``."""
        frames: List[pd.DataFrame] = []
        legacy = self._legacy_partial(job_id)
        if legacy is not None:
            frames.append(legacy)
        for _, _, p in sorted(
            self._partial_chunks(job_id), key=lambda t: t[1]
        ):
            df = self._read_chunk(job_id, p)
            if df is not None:
                frames.append(df)
        out: Dict[int, Dict[str, Any]] = {}
        for df in frames:
            for _, r in df.iterrows():
                out[int(r["row_id"])] = dict(r)
        return out

    def read_partial_meta(self, job_id: str) -> Dict[int, str]:
        """row_id -> finish_reason for every flushed row (column
        projection only — the resume filter and done-set bootstrap
        never materialize outputs)."""
        cols = ["row_id", "finish_reason"]
        frames: List[pd.DataFrame] = []
        legacy = self._legacy_partial(job_id)
        if legacy is not None:
            frames.append(legacy[cols])
        for _, _, p in sorted(
            self._partial_chunks(job_id), key=lambda t: t[1]
        ):
            df = self._read_chunk(job_id, p, columns=cols)
            if df is not None:
                frames.append(df)
        out: Dict[int, str] = {}
        for df in frames:
            ids = df["row_id"].to_numpy()
            reasons = df["finish_reason"].tolist()
            for i, reason in zip(ids, reasons):
                out[int(i)] = reason
        return out

    def finalize_results(
        self, job_id: str, results: Dict[str, List[Any]]
    ) -> None:
        """Write final results THEN flip to SUCCEEDED (ordering invariant).
        Materializes the whole frame — kept for the embedding path
        (vector-valued outputs); generation jobs use
        ``write_results_streamed``."""
        t0 = time.monotonic()
        df = pd.DataFrame(results)
        tmp = self._dir(job_id) / "results.parquet.tmp"
        df.to_parquet(tmp)
        tmp.replace(self._dir(job_id) / "results.parquet")
        if telemetry.ENABLED:
            dt = time.monotonic() - t0
            telemetry.stage_observe("finalize", dt)
            telemetry.RECORDER.record(
                "finalize", job_id, t0, dt, {"rows": len(df)}
            )
        self.set_status(job_id, JobStatus.SUCCEEDED)

    # generation result schema: one definition so every row-group of a
    # streamed results.parquet agrees with what finalize_results used
    # to produce via pandas. ``error`` carries a quarantined row's
    # failure message (null for clean rows) — SUCCEEDED with N-k good
    # rows + k error rows, instead of one bad row failing the job.
    _GEN_COLS = (
        "row_id",
        "outputs",
        "cumulative_logprobs",
        "gen_tokens",
        "finish_reason",
        "error",
    )

    # columns absent from pre-upgrade partial rows that backfill with a
    # default instead of raising (anything else missing is a bug)
    _GEN_BACKFILL = ("gen_tokens", "error")

    def write_results_streamed(
        self,
        job_id: str,
        num_rows: int,
        on_chunk=None,
    ) -> None:
        """Merge-on-read finalization: assemble ``results.parquet`` in
        row_id order directly from the partial chunk store, one bucket
        (= one parquet row-group) at a time. Peak memory is
        O(chunk_rows + this bucket's duplicate entries), independent of
        job size. Rows never flushed (cancelled before running) fill as
        ``finish_reason="cancelled"`` with null outputs — same rule as
        the old in-memory assembly. Does NOT flip job status: callers
        update accounting first, then set SUCCEEDED (the
        results-before-status invariant holds either way because the
        final file only appears at the atomic rename below).

        ``on_chunk(df)`` sees each ordered bucket frame — accounting
        hooks (output-token counts) ride the same single pass. On a
        TRANSIENT I/O failure the whole pass retries from scratch
        (bounded, backed off), so ``on_chunk`` observers must reset
        when they see the bucket starting at row 0 again.
        """
        t0 = time.monotonic()
        retry_transient(
            lambda: self._write_results_streamed_once(
                job_id, num_rows, on_chunk
            ),
            attempts=self.io_retries,
            base=self.io_backoff,
            cap=self.io_backoff_cap,
            retry_on=(OSError,),
            on_retry=lambda attempt, delay, exc: self.append_failure_log(
                job_id,
                {"event": "io_retry", "site": "jobstore.finalize",
                 "attempt": attempt,
                 "error": f"{type(exc).__name__}: {exc}"},
            ),
            what=f"finalize[{job_id}]",
        )
        if telemetry.ENABLED:
            dt = time.monotonic() - t0
            telemetry.stage_observe("finalize", dt)
            telemetry.RECORDER.record(
                "finalize", job_id, t0, dt, {"rows": num_rows}
            )

    def _write_results_streamed_once(
        self,
        job_id: str,
        num_rows: int,
        on_chunk=None,
    ) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq

        if faults.ACTIVE is not None:
            faults.inject("jobstore.finalize", job=job_id)
        schema = pa.schema(
            [
                ("row_id", pa.int64()),
                ("outputs", pa.string()),
                ("cumulative_logprobs", pa.float64()),
                ("gen_tokens", pa.int64()),
                ("finish_reason", pa.string()),
                ("error", pa.string()),
            ]
        )
        import numpy as np

        by_bucket: Dict[int, List[tuple]] = {}
        for bucket, seq, p in self._partial_chunks(job_id):
            by_bucket.setdefault(bucket, []).append((seq, p))
        legacy = self._legacy_partial(job_id)  # compat: one old-format
        #                                        file, loaded once
        n_buckets = max(
            1, (num_rows + self.chunk_rows - 1) // self.chunk_rows
        )
        tmp = self._dir(job_id) / "results.parquet.tmp"
        writer = pq.ParquetWriter(tmp, schema)
        try:
            for bucket in range(n_buckets):
                lo = bucket * self.chunk_rows
                hi = min(lo + self.chunk_rows, num_rows)
                frames: List[pd.DataFrame] = []
                if legacy is not None and len(legacy):
                    in_range = legacy[
                        (legacy["row_id"] >= lo) & (legacy["row_id"] < hi)
                    ]
                    if len(in_range):
                        frames.append(in_range)
                for _seq, p in sorted(by_bucket.get(bucket, ())):
                    chunk = self._read_chunk(job_id, p)
                    if chunk is not None:
                        frames.append(chunk)
                if frames:
                    df = pd.concat(frames, ignore_index=True)
                    missing = [
                        c
                        for c in self._GEN_COLS
                        if c not in self._GEN_BACKFILL
                        and c not in df.columns
                    ]
                    if missing:
                        # gen_tokens/error are backfillable (pre-upgrade
                        # partial rows lack them); anything else missing
                        # is a bug and must raise, not record nulls
                        raise ValueError(
                            f"partial rows for {job_id} lack columns "
                            f"{missing}"
                        )
                    if "gen_tokens" not in df.columns:
                        df = df.assign(gen_tokens=0)
                    if "error" not in df.columns:
                        df = df.assign(error=None)
                    sub = df.drop_duplicates(
                        subset="row_id", keep="last"
                    ).set_index("row_id").reindex(range(lo, hi))
                    never_ran = sub["finish_reason"].isna()
                    outputs = [
                        None if pd.isna(v) else v
                        for v in sub["outputs"].tolist()
                    ]
                    reasons = [
                        "cancelled" if m else r
                        for m, r in zip(
                            never_ran.tolist(),
                            sub["finish_reason"].tolist(),
                        )
                    ]
                    errors = [
                        None if (isinstance(v, float) and pd.isna(v))
                        or v is None
                        else str(v)
                        for v in sub["error"].tolist()
                    ]
                    logps = (
                        pd.to_numeric(
                            sub["cumulative_logprobs"], errors="coerce"
                        )
                        .fillna(0.0)
                        .to_numpy(np.float64)
                    )
                    gen_toks = (
                        pd.to_numeric(sub["gen_tokens"], errors="coerce")
                        .fillna(0)
                        .to_numpy(np.int64)
                    )
                else:
                    n = hi - lo
                    outputs = [None] * n
                    reasons = ["cancelled"] * n
                    errors = [None] * n
                    logps = np.zeros((n,), np.float64)
                    gen_toks = np.zeros((n,), np.int64)
                out = pd.DataFrame(
                    {
                        "row_id": np.arange(lo, hi, dtype=np.int64),
                        "outputs": outputs,
                        "cumulative_logprobs": logps,
                        "gen_tokens": gen_toks,
                        "finish_reason": reasons,
                        "error": errors,
                    }
                )
                if on_chunk is not None:
                    on_chunk(out)
                writer.write_table(
                    pa.Table.from_pandas(
                        out, schema=schema, preserve_index=False
                    )
                )
        finally:
            writer.close()
        tmp.replace(self._dir(job_id) / "results.parquet")

    def read_results(self, job_id: str) -> pd.DataFrame:
        path = self._dir(job_id) / "results.parquet"
        # status gate, not just file existence: results.parquet lands
        # (atomic rename) a few ms BEFORE the record flips to SUCCEEDED
        # (accounting updates sit between), and a concurrent reader must
        # not observe results on a still-RUNNING job — the public
        # contract is "SUCCEEDED implies results readable", never the
        # converse (caught by test_races' pre-terminal-results check)
        status = self.status(job_id)
        if status != JobStatus.SUCCEEDED or not path.exists():
            raise FileNotFoundError(
                f"Results for {job_id} not available (status={status.value})"
            )
        return pd.read_parquet(path)

    # -- quotas ----------------------------------------------------------
    def get_quotas(self) -> List[Dict[str, int]]:
        path = config_dir() / "quotas.json"
        if path.exists():
            try:
                return json.loads(path.read_text())
            except (OSError, ValueError) as e:
                logger.warning(
                    "quotas.json unreadable (%s); using default quotas", e
                )
        return [dict(q) for q in DEFAULT_QUOTAS]

    def validate_priority(
        self, priority: Any, quotas: Optional[List[Dict[str, int]]] = None
    ) -> int:
        """The submit-time ``job_priority`` gate: an int indexing the
        quota table, or :class:`InvalidPriority`. No clamping — a
        priority outside the table would otherwise silently inherit
        another level's quota AND queue position."""
        if quotas is None:
            quotas = self.get_quotas()
        try:
            p = int(priority)
        except (TypeError, ValueError):
            raise InvalidPriority(priority, len(quotas)) from None
        if not 0 <= p < len(quotas):
            raise InvalidPriority(priority, len(quotas))
        return p

    def check_quota(
        self, priority: int, num_rows: int, est_tokens: int
    ) -> Optional[str]:
        quotas = self.get_quotas()
        q = quotas[self.validate_priority(priority, quotas)]
        if num_rows > q["row_quota"]:
            return (
                f"Row count {num_rows} exceeds priority-{priority} quota "
                f"{q['row_quota']}"
            )
        if est_tokens > q["token_quota"]:
            return (
                f"Estimated tokens {est_tokens} exceed priority-{priority} "
                f"quota {q['token_quota']}"
            )
        return None
