"""Local dataset store.

TPU-native replacement for the reference's remote dataset CRUD
(/root/reference/sutro/sdk.py:1289-1516; wire contract SURVEY §3.6):
``dataset-<id>`` directories of parquet/csv/txt files under
``$SUTRO_HOME/datasets``, with the same operations the SDK/CLI expose:
create, upload, list datasets (with schema), list files, download.
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import shutil
import threading
import uuid
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

import pandas as pd

from ..validation import config_dir

logger = logging.getLogger(__name__)


def _root() -> Path:
    d = config_dir() / "datasets"
    d.mkdir(parents=True, exist_ok=True)
    return d


class DatasetStore:
    def __init__(self, root: Optional[Path] = None):
        self.root = Path(root) if root else _root()
        # serializes .meta.json read-modify-writes (the daemon's HTTP
        # threads hit the store concurrently, server.py)
        self._lock = threading.Lock()

    @staticmethod
    def _write_meta(d: Path, meta: Dict[str, Any]) -> None:
        """Atomic replace so concurrent readers never see torn JSON.
        Runs under the store lock from ``_touch_meta``: the tiny local
        meta write IS that lock's critical section (serialized RMW)."""
        tmp = d / ".meta.json.tmp"
        # graftlint: disable=lock-blocking-call
        tmp.write_text(json.dumps(meta, indent=2))
        os.replace(tmp, d / ".meta.json")  # graftlint: disable=lock-blocking-call

    def _touch_meta(self, d: Path) -> None:
        # the meta read-modify-write IS the critical section the lock
        # exists for; the file is tiny and local (see _write_meta's
        # graftlint suppressions)
        with self._lock:
            meta = json.loads((d / ".meta.json").read_text())
            meta["updated_at"] = datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat()
            self._write_meta(d, meta)

    def _dir(self, dataset_id: str) -> Path:
        if not dataset_id.startswith("dataset-"):
            raise ValueError(f"Invalid dataset id: {dataset_id!r}")
        d = self.root / dataset_id
        if not d.exists():
            raise FileNotFoundError(f"Unknown dataset: {dataset_id}")
        return d

    def create(self) -> str:
        dataset_id = f"dataset-{uuid.uuid4().hex[:12]}"
        d = self.root / dataset_id
        d.mkdir(parents=True)
        meta = {
            "dataset_id": dataset_id,
            "datetime_added": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(),
            "updated_at": None,
        }
        self._write_meta(d, meta)
        return dataset_id

    def upload(
        self, dataset_id: str, paths: List[Union[str, Path]]
    ) -> List[str]:
        d = self._dir(dataset_id)
        names = []
        for p in paths:
            p = Path(p)
            if p.is_dir():
                for f in sorted(p.iterdir()):
                    if f.is_file():
                        shutil.copy2(f, d / f.name)
                        names.append(f.name)
            else:
                shutil.copy2(p, d / p.name)
                names.append(p.name)
        self._touch_meta(d)
        return names

    def upload_bytes(
        self, dataset_id: str, file_name: str, data: bytes
    ) -> str:
        """Write one uploaded file body (the daemon's multipart endpoint,
        server.py)."""
        d = self._dir(dataset_id)
        name = Path(file_name).name  # strip any client-supplied directories
        if not name or name == ".meta.json":
            raise ValueError(f"Invalid upload file name: {file_name!r}")
        (d / name).write_bytes(data)
        self._touch_meta(d)
        return name

    def list_datasets(self) -> List[Dict[str, Any]]:
        out = []
        for d in sorted(self.root.iterdir()):
            if not d.is_dir() or not d.name.startswith("dataset-"):
                continue
            try:
                meta = json.loads((d / ".meta.json").read_text())
            except (OSError, ValueError) as e:
                # missing/torn meta must not hide the dataset's files —
                # serve id-only metadata, but say why
                logger.warning(
                    "dataset %s: unreadable .meta.json (%s); listing "
                    "with id-only metadata",
                    d.name,
                    e,
                )
                meta = {"dataset_id": d.name}
            meta["schema"] = self._schema(d)
            meta["num_files"] = len(self.list_files(d.name))
            out.append(meta)
        return out

    def _schema(self, d: Path) -> Dict[str, str]:
        for f in sorted(d.iterdir()):
            if f.suffix == ".parquet":
                try:
                    import pyarrow.parquet as pq

                    sch = pq.read_schema(f)
                    return {n: str(t) for n, t in zip(sch.names, sch.types)}
                except (ImportError, OSError, ValueError) as e:
                    # ArrowInvalid/ArrowIOError subclass ValueError/OSError
                    logger.warning(
                        "dataset file %s: cannot read parquet schema "
                        "(%s); reporting none",
                        f,
                        e,
                    )
                    return {}
            if f.suffix == ".csv":
                try:
                    head = pd.read_csv(f, nrows=10)
                    return {c: str(t) for c, t in head.dtypes.items()}
                except (OSError, ValueError) as e:
                    # pandas parser errors subclass ValueError
                    logger.warning(
                        "dataset file %s: cannot infer csv schema "
                        "(%s); reporting none",
                        f,
                        e,
                    )
                    return {}
        return {}

    def list_files(self, dataset_id: str) -> List[str]:
        d = self._dir(dataset_id)
        return sorted(
            f.name
            for f in d.iterdir()
            # dotfiles excluded: .meta.json and its atomic-replace temp
            if f.is_file() and not f.name.startswith(".")
        )

    def file_path(self, dataset_id: str, file_name: str) -> Path:
        d = self._dir(dataset_id)
        p = (d / file_name).resolve()
        # reject traversal: the resolved path must stay inside the dataset
        # dir (file_name is client-controlled via the daemon, server.py)
        if p.parent != d.resolve() or p.name == ".meta.json":
            raise FileNotFoundError(
                f"{dataset_id} has no file {file_name!r}"
            )
        if not p.exists():
            raise FileNotFoundError(f"{dataset_id} has no file {file_name!r}")
        return p

    def download(
        self, dataset_id: str, file_name: str, output_path: Union[str, Path]
    ) -> Path:
        src = self.file_path(dataset_id, file_name)
        out_dir = Path(output_path)
        out_dir.mkdir(parents=True, exist_ok=True)
        dst = out_dir / file_name
        shutil.copy2(src, dst)
        return dst

    def read_rows(
        self, dataset_id: str, column: Optional[Union[str, List[Any]]] = None
    ) -> List[str]:
        """Materialize a dataset's rows for inference input (reference
        behavior: a job may name `dataset-<id>` as its input,
        common.py:111-162)."""
        from ..common import prepare_input_data

        rows: List[str] = []
        for name in self.list_files(dataset_id):
            p = self.file_path(dataset_id, name)
            if p.suffix in (".parquet", ".csv", ".txt"):
                got = prepare_input_data(str(p), column=column)
                assert isinstance(got, list)
                rows.extend(got)
        return rows
