"""Soft in-process deadline for chip-facing benchmark scripts.

Round 4's tunnel wedge: a benchmark subprocess SIGKILLed at its outer
timeout while holding a live axon-tunnel connection left the remote end
wedged, and every case queued behind it aborted rc=3
(CHIP_VALIDATION_HISTORY.jsonl, round-4 records). SIGKILL skips all
teardown, and SIGTERM's *default* disposition also terminates without
running atexit hooks or the PJRT client destructor. The only exit that
reliably closes the tunnel connection is the interpreter unwinding
normally — so the case must stop *itself* before any outer kill fires:

    from sutro_tpu.engine.softdeadline import arm_from_env
    arm_from_env()      # no-op unless SUTRO_SOFT_DEADLINE_S is set

Mechanism, two stages:
  1. At the deadline a daemon watchdog thread calls
     ``_thread.interrupt_main()`` — KeyboardInterrupt is raised in the
     main thread at the next bytecode boundary, the stack unwinds,
     atexit runs, the PJRT client closes its connection, the tunnel
     survives. Exit code 124 (timeout convention) via an installed
     excepthook so supervisors can tell "deadline" from "crash".
  2. If the main thread never reaches a bytecode boundary (stuck in an
     uninterruptible C call — which in practice means the tunnel is
     already dead, so there is nothing left to preserve), a second
     stage ``os._exit(124)``s after ``grace`` more seconds so the
     supervisor never needs SIGKILL.

Additionally installs a SIGTERM handler taking the same clean path, so
a supervisor's TERM (stage 1 of terminate-then-kill) also unwinds
normally instead of dying teardown-less.
"""

from __future__ import annotations

import _thread
import os
import signal
import sys
import threading
import time


_FIRED = threading.Event()
# set the moment the Python-level SIGINT handler actually RUNS (i.e.
# the interrupt was delivered at a bytecode boundary and the
# KeyboardInterrupt is now unwinding): the watchdog must stop
# re-signalling then — a second SIGINT would land inside a finally /
# context-manager teardown frame and abort the very cleanup the clean
# exit exists for. While the main thread is stuck in a C call the
# handler has NOT run yet, so re-signalling remains correct there.
_DELIVERED = threading.Event()
_ARMED = False


def _watchdog(deadline_s: float, grace_s: float) -> None:
    time.sleep(deadline_s)
    _FIRED.set()
    print(
        f"[softdeadline] {deadline_s:.0f}s budget exhausted - "
        "interrupting main thread for a clean (tunnel-preserving) exit",
        file=sys.stderr,
        flush=True,
    )
    # a REAL signal, not _thread.interrupt_main(): interrupt_main only
    # marks a pending exception checked at bytecode boundaries, so a
    # main thread blocked in a syscall (sleep, socket recv) never sees
    # it; pthread_kill(SIGINT) EINTRs the syscall and the default SIGINT
    # handler raises KeyboardInterrupt right there.
    #
    # Stage 2: a main thread inside a long C call (an XLA compile on a
    # LIVE tunnel looks identical to a wedge on a dead one) cannot see
    # the signal until the call returns — so keep re-signalling every
    # 15 s for the whole grace window rather than hard-exiting at the
    # first miss: if the compile finishes anytime within grace, the
    # pending interrupt lands and the exit is still clean. Only after
    # the full grace do we hard-exit — at that point the outer
    # supervisor's SIGKILL is imminent anyway and exiting ourselves at
    # least keeps the rc legible.
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if not _DELIVERED.is_set():
            try:
                signal.pthread_kill(
                    threading.main_thread().ident, signal.SIGINT
                )
            except Exception:
                _thread.interrupt_main()
        time.sleep(min(15.0, max(0.1, deadline - time.monotonic())))
    print(
        "[softdeadline] main thread did not unwind within "
        f"{grace_s:.0f}s grace (stuck in C call) - hard exit 124",
        file=sys.stderr,
        flush=True,
    )
    os._exit(124)


def _excepthook(tp, val, tb):
    if _FIRED.is_set() and issubclass(tp, KeyboardInterrupt):
        print(
            "[softdeadline] clean exit after deadline interrupt (rc=124)",
            file=sys.stderr,
            flush=True,
        )
        # swallow the traceback and let interpreter shutdown proceed
        # normally; the atexit hook registered in arm() sets rc=124
        return
    _orig_excepthook(tp, val, tb)


_orig_excepthook = sys.excepthook


def _sigint(_sig, _frm):
    _DELIVERED.set()
    raise KeyboardInterrupt


def _sigterm(_sig, _frm):
    _FIRED.set()
    _DELIVERED.set()
    print(
        "[softdeadline] SIGTERM - raising for a clean exit",
        file=sys.stderr,
        flush=True,
    )
    raise SystemExit(124)


def arm(deadline_s: float, grace_s: float = 120.0) -> None:
    """Arm the two-stage watchdog. Idempotent (first call wins)."""
    global _ARMED
    if _ARMED or deadline_s <= 0:
        return
    _ARMED = True
    sys.excepthook = _excepthook
    try:
        signal.signal(signal.SIGTERM, _sigterm)
        # our own SIGINT handler, installed unconditionally: (a) a
        # process launched from a non-interactive shell's async list
        # inherits SIGINT=SIG_IGN, which Python preserves — the
        # watchdog's pthread_kill would then be a silent no-op and the
        # deadline would degrade to the teardown-less hard exit; (b)
        # the handler records delivery so the watchdog stops
        # re-signalling once the interrupt is actually unwinding
        signal.signal(signal.SIGINT, _sigint)
    except ValueError:
        pass  # not the main thread; keep default dispositions
    t = threading.Thread(
        target=_watchdog, args=(deadline_s, grace_s), daemon=True
    )
    t.start()

    # make the deadline path exit 124 (not 130/0): atexit hooks run
    # LIFO, and jax registers its backend-teardown hook at first
    # backend touch — AFTER this registration — so jax's hook (tunnel
    # close) runs before this one; by the time we hard-set the exit
    # code the connection is already down cleanly.
    import atexit

    def _exit_code():
        if _FIRED.is_set():
            os._exit(124)

    atexit.register(_exit_code)


def arm_from_env(default_grace_s: float = 120.0) -> None:
    """Arm from SUTRO_SOFT_DEADLINE_S (seconds); no-op if unset/invalid."""
    raw = os.environ.get("SUTRO_SOFT_DEADLINE_S", "")
    try:
        deadline = float(raw)
    except ValueError:
        return
    try:
        grace = float(
            os.environ.get("SUTRO_SOFT_GRACE_S", default_grace_s)
        )
    except ValueError:
        grace = default_grace_s  # a knob typo must not kill the case
    arm(deadline, grace)
