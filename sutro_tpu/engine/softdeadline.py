"""Soft in-process deadline for chip-facing benchmark scripts.

Round 4's tunnel wedge: a benchmark subprocess SIGKILLed at its outer
timeout while holding a live axon-tunnel connection left the remote end
wedged, and every case queued behind it aborted rc=3
(CHIP_VALIDATION_HISTORY.jsonl, round-4 records). SIGKILL skips all
teardown, and SIGTERM's *default* disposition also terminates without
running atexit hooks or the PJRT client destructor. The only exit that
reliably closes the tunnel connection is the interpreter unwinding
normally — so the case must stop *itself* before any outer kill fires:

    from sutro_tpu.engine.softdeadline import arm_from_env
    arm_from_env()      # no-op unless SUTRO_SOFT_DEADLINE_S is set

Mechanism, two stages:
  1. At the deadline a daemon watchdog thread sends the main thread a
     real SIGINT (``pthread_kill`` — unlike ``interrupt_main`` it
     EINTRs blocking syscalls). arm()'s own SIGINT handler raises
     ``SystemExit(124)``: the stack unwinds (finally blocks and
     context managers run), atexit runs, the PJRT client closes its
     connection, and the interpreter exits 124 (timeout convention) —
     no excepthook or exit-code games needed. The handler is installed
     unconditionally because a process launched from a non-interactive
     shell's async list inherits SIGINT=SIG_IGN, which Python
     preserves, making the default-handler path a silent no-op.
  2. A main thread inside a long C call (an XLA compile on a LIVE
     tunnel looks identical to a wedge on a dead one) cannot see the
     signal until the call returns — so the watchdog keeps
     re-signalling every 15 s for the whole ``grace`` window (stopping
     the moment the handler actually runs, so in-flight teardown is
     never re-interrupted). Only after the full grace does it
     ``os._exit(124)`` — at that point the outer supervisor's SIGKILL
     is imminent anyway and self-exiting at least keeps the rc
     legible.

Additionally installs a SIGTERM handler taking the same clean path, so
a supervisor's TERM (stage 1 of terminate-then-kill) also unwinds
normally instead of dying teardown-less.
"""

from __future__ import annotations

import _thread
import os
import signal
import sys
import threading
import time


_FIRED = threading.Event()
# set the moment the Python-level SIGINT/SIGTERM handler actually RUNS
# (the interrupt was delivered at a bytecode boundary and SystemExit is
# now unwinding): the watchdog must stop re-signalling then — another
# SIGINT would land inside a finally / context-manager teardown frame
# and abort the very cleanup the clean exit exists for. While the main
# thread is stuck in a C call the handler has NOT run yet, so
# re-signalling remains correct there.
_DELIVERED = threading.Event()
_ARMED = False
# monotonic timestamp the armed deadline expires at (None when unarmed):
# the control plane reads this via remaining_s() to cap its bounded
# admission waits and to stop preempting when suspended rows could not
# be resumed before the process unwinds
_DEADLINE_AT: float | None = None


def _watchdog(deadline_s: float, grace_s: float) -> None:
    time.sleep(deadline_s)
    _FIRED.set()
    print(
        f"[softdeadline] {deadline_s:.0f}s budget exhausted - "
        "interrupting main thread for a clean (tunnel-preserving) exit",
        file=sys.stderr,
        flush=True,
    )
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if _DELIVERED.is_set():
            # handler ran; the main thread is unwinding — let it finish
            time.sleep(min(15.0, max(0.1, deadline - time.monotonic())))
            continue
        try:
            signal.pthread_kill(
                threading.main_thread().ident, signal.SIGINT
            )
        except Exception:
            _thread.interrupt_main()
        time.sleep(min(15.0, max(0.1, deadline - time.monotonic())))
    if _DELIVERED.is_set():
        # the interrupt landed and teardown is in flight — hard-exiting
        # now would kill the PJRT close mid-way, wedging the tunnel the
        # clean path exists to protect; the outer supervisor's
        # TERM->KILL remains the true backstop for a hung teardown
        print(
            "[softdeadline] grace expired but teardown is unwinding - "
            "leaving it to finish",
            file=sys.stderr,
            flush=True,
        )
        return
    print(
        "[softdeadline] main thread did not unwind within "
        f"{grace_s:.0f}s grace (stuck in C call) - hard exit 124",
        file=sys.stderr,
        flush=True,
    )
    os._exit(124)


def _sigint(_sig, _frm):
    if _FIRED.is_set():
        if _DELIVERED.is_set():
            # already delivered: the stack is unwinding through finally
            # blocks / context managers. A second SystemExit here (a
            # watchdog re-signal racing the delivery, or a stray ^C)
            # would abort the very teardown the clean exit exists to
            # protect — swallow it.
            return
        # only a post-deadline interrupt counts as delivery — marking a
        # genuine pre-deadline ^C would permanently disable the
        # watchdog's re-signalling (the event is never cleared)
        _DELIVERED.set()
        print(
            "[softdeadline] deadline interrupt delivered - clean "
            "unwind to exit 124",
            file=sys.stderr,
            flush=True,
        )
        raise SystemExit(124)
    # a genuine ^C while armed: preserve the usual semantics
    raise KeyboardInterrupt


def _sigterm(_sig, _frm):
    _FIRED.set()
    _DELIVERED.set()
    print(
        "[softdeadline] SIGTERM - raising for a clean exit",
        file=sys.stderr,
        flush=True,
    )
    raise SystemExit(124)


def remaining_s() -> float | None:
    """Seconds left on the armed soft deadline, or None when unarmed.

    Clamped at 0 after expiry. Consumers (engine/control.py) use this
    to bound waits and to refuse work that could not finish before the
    watchdog fires; None means "no deadline pressure"."""
    if _DEADLINE_AT is None:
        return None
    return max(0.0, _DEADLINE_AT - time.monotonic())


def arm(deadline_s: float, grace_s: float = 120.0) -> None:
    """Arm the two-stage watchdog. Idempotent (first call wins)."""
    global _ARMED, _DEADLINE_AT
    if _ARMED or deadline_s <= 0:
        return
    _ARMED = True
    _DEADLINE_AT = time.monotonic() + deadline_s
    try:
        signal.signal(signal.SIGTERM, _sigterm)
        signal.signal(signal.SIGINT, _sigint)
    except ValueError:
        pass  # not the main thread; keep default dispositions
    t = threading.Thread(
        target=_watchdog, args=(deadline_s, grace_s), daemon=True
    )
    t.start()


def arm_from_env(default_grace_s: float = 120.0) -> None:
    """Arm from SUTRO_SOFT_DEADLINE_S (seconds); no-op if unset/invalid."""
    raw = os.environ.get("SUTRO_SOFT_DEADLINE_S", "")
    try:
        deadline = float(raw)
    except ValueError:
        return
    try:
        grace = float(
            os.environ.get("SUTRO_SOFT_GRACE_S", default_grace_s)
        )
    except ValueError:
        grace = default_grace_s  # a knob typo must not kill the case
    arm(deadline, grace)
