"""Continuous-batching scheduler.

Host-side control plane of the engine — the component the reference's
remote service keeps behind ``POST /batch-inference`` (SURVEY §2.3 row 1,
§7.3 "continuous batching under XLA static shapes"). Design:

- A fixed array of ``decode_batch_size`` slots; every decode step runs the
  whole array through one compiled step regardless of occupancy (static
  shapes — no recompiles as rows enter/leave).
- Rows are admitted whenever a slot is free and the page allocator can
  reserve the row's worst-case page count up front (prompt + max_new
  capped to context) — reservation up front makes mid-flight OOM
  impossible and keeps the loop deadlock-free.
- Prefill is BATCHED, shortest-prompt-first: up to ``prefill_batch_size``
  reserved rows share one device dispatch padded to a power-of-two
  (batch x length) bucket (compile-count bounded); each row's
  last-position logits seed its slot's first sampled token. Prompts
  longer than ``prefill_chunk`` prefill alone via the chunked path.
- Order-preserving results: completions are emitted keyed by ``row_id`` and
  re-assembled in input order by the jobstore, while execution order is
  whatever batching dictates (reference contract: README.md:221).
- Constrained decoding: slots carrying a token-FSM contribute a per-slot
  vocab mask assembled host-side each step (SURVEY §7.3 "vectorized
  constrained decoding"); unconstrained slots get all-True rows.
"""

from __future__ import annotations

import dataclasses
import inspect
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Protocol, Tuple

import jax
import numpy as np

logger = logging.getLogger(__name__)

from . import faults
from .kvcache import PageAllocator, pages_needed
from .runner import ModelRunner, next_bucket
from .. import telemetry
from ..ops.sampling import cumulative_logprob, sample as device_sample

# StepTimer phase -> telemetry stage (OBSERVABILITY.md span schema):
# the timer wraps DEVICE dispatches, so its phases map onto the
# device-side stages of the flight-recorder timeline
_TEL_STAGE = {
    "prefill": "prefill",
    "decode": "decode_window",
    "admit_sample": "admit",
}


@jax.jit
def _admit_sample_jit(
    logits, key, temperature, top_p, top_k, allowed, row_seeds
):
    """First-token sampling + logprob for admission, under ONE jit.

    Calling ``sample`` eagerly here cost ~450 ms of host time per
    prefill group (profiled round 5, CPU host): the top-p path's
    ``lax.cond`` re-traces its branches on EVERY eager call. Jitted,
    repeat groups of the same shape hit the pjit cache and the whole
    sample+logprob pair runs as one compiled program."""
    tok = device_sample(
        logits, key,
        temperature=temperature, top_p=top_p, top_k=top_k,
        allowed=allowed, row_seeds=row_seeds,
    )
    return tok, cumulative_logprob(logits, tok)


def _step_seed(row_seed: int, step: int) -> int:
    """Deterministic (row, step) -> int32 seed mix."""
    return ((row_seed * 1_000_003) ^ (step * 2_654_435_761)) & 0x7FFFFFFF


class TokenConstraint(Protocol):
    """Token-level FSM driving schema-constrained decoding
    (engine/constrain/). ``remaining`` (tokens of budget left for the
    row, when known) lets the FSM force closure so schema rows emit
    complete JSON even at the length cap."""

    def allowed_tokens(
        self, remaining: "int | None" = None
    ) -> np.ndarray:  # [V] bool
        ...

    def advance(self, token_id: int) -> None:
        ...

    def is_complete(self) -> bool:
        ...

    # OPTIONAL fast path: implementations may additionally provide
    # ``token_allowed(token_id, remaining=None) -> bool`` (O(1) validity
    # of one token) — the speculative fused-window verifier uses it when
    # present and falls back to ``allowed_tokens`` otherwise.


# per-method cache: does this allowed_tokens accept ``remaining``? Keyed
# by the unbound class function (bounded: one entry per implementing
# class); the value keeps a strong ref so the id can't be reused.
# Instance-attribute callables (no __func__) are probed per object and
# memoized on the instance itself, so the cache cannot grow unboundedly
# in a long-lived daemon.
_TAKES_BUDGET: Dict[int, Tuple[Any, bool]] = {}


def _probe_takes_budget(fn: Any) -> bool:
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        # inspect.signature's documented failure modes for builtins /
        # C callables: no signature means no ``remaining`` kwarg
        return False
    kw_ok = (
        inspect.Parameter.POSITIONAL_OR_KEYWORD,
        inspect.Parameter.KEYWORD_ONLY,
    )
    return any(
        (p.name == "remaining" and p.kind in kw_ok)
        or p.kind == inspect.Parameter.VAR_KEYWORD
        for p in sig.parameters.values()
    )


def _method_takes_budget(obj: Any, bound: Any, attr_cache: str) -> bool:
    """Cached signature probe for a bound constraint method: one probe
    per implementing class (``_TAKES_BUDGET``) or, for instance-attribute
    callables, memoized on the instance. Called on per-token hot paths —
    must not hit ``inspect.signature`` repeatedly."""
    fn = getattr(bound, "__func__", None)
    if fn is not None:
        key = id(fn)
        cached = _TAKES_BUDGET.get(key)
        if cached is not None:
            return cached[1]
        takes = _probe_takes_budget(fn)
        _TAKES_BUDGET[key] = (fn, takes)
        return takes
    takes = getattr(obj, attr_cache, None)
    if takes is None:
        takes = _probe_takes_budget(bound)
        try:
            setattr(obj, attr_cache, takes)
        except (AttributeError, TypeError):
            pass  # __slots__ / frozen instances: re-probe next call
    return takes


@dataclasses.dataclass
class GenRequest:
    row_id: int
    prompt_ids: np.ndarray
    max_new_tokens: int = 256
    temperature: float = 0.7
    top_p: float = 0.95
    top_k: int = 0
    constraint: Optional[TokenConstraint] = None
    # Lazy constraint (double-buffered admission): when set (and
    # ``constraint`` is None), the row's FSM is built at ADMISSION time
    # — by the batcher's background prep thread while the device runs
    # the previous window, falling back to an inline build. A 20k-row
    # job stops instantiating 20k FSMs up front, and steady-state
    # admission host time hides behind device windows.
    constraint_factory: Optional[Callable[[], TokenConstraint]] = None
    # written ONLY by the prep thread, consumed once by the scheduler
    # thread at admission (single-assignment handoff; the scheduler
    # never blocks on it)
    prepped_constraint: Optional[TokenConstraint] = None
    prep_queued: bool = False
    # Reference `truncate_rows` semantics (sdk.py:457,480): True => over-long
    # prompts are truncated to fit the context; False => the row fails.
    allow_truncate: bool = True
    # Per-row sampling seed (`random_seed_per_input`): when set, this row's
    # tokens are drawn from keys folded from (row_seed, step) — reproducible
    # regardless of batch composition.
    row_seed: Optional[int] = None
    # Stop SEQUENCES (byte strings): generation ends once any appears in
    # the decoded output (detection here via a rolling byte tail; exact
    # text truncation happens at the result-rendering layer, which has
    # the full decoded string). Requires the batcher's ``token_bytes``.
    stop_seqs: Optional[List[bytes]] = None
    # vLLM-style sampling penalties over GENERATED tokens (defaults
    # disable). Rows using them decode single-step (the host threads
    # token counts between steps).
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0

    def has_penalties(self) -> bool:
        return (
            self.presence_penalty != 0.0
            or self.frequency_penalty != 0.0
            or self.repetition_penalty != 1.0
        )



@dataclasses.dataclass
class GenResult:
    row_id: int
    token_ids: List[int]
    cumulative_logprob: float
    # "stop" | "length" | "schema_complete" | "cancelled" |
    # "error" | "error_too_long" | "error_capacity"
    finish_reason: str
    input_tokens: int
    # quarantine message for error_* rows (row-level failure domain):
    # the jobstore lands it in the results ``error`` column; None for
    # clean rows
    error: Optional[str] = None


@dataclasses.dataclass
class _SharedPrefix:
    """A job-wide common token prefix prefilled ONCE into shared pages.
    Every slot's page table starts with these pages (read-only — decode
    and suffix prefill write only at positions >= ``tokens``, which land
    in the slot's own pages); the allocator frees them at end of run,
    not per slot. Templates guarantee the headline workload has one
    (reference templates/classification.py builds a single prompt shell
    for all rows)."""

    tokens: int              # shared length, a multiple of kv_page_size
    pages: List[int]
    # cross-job radix store (engine/prefixstore.py): ``handle`` pins the
    # store-owned head of ``pages``; ``own_pages`` is the session-owned
    # tail to free at release (None = the whole list, the storeless
    # per-job path). Release via _release_prefix, never raw frees.
    handle: Optional[Any] = None
    own_pages: Optional[List[int]] = None

    @property
    def n_pages(self) -> int:
        return len(self.pages)


@dataclasses.dataclass
class JobCtx:
    """One job's slice of a (possibly multi-job) batcher session.

    Cross-job co-batching (VERDICT r3 next-step 3): the reference's
    fleet implicitly multiplexes many users' jobs over shared capacity
    (/root/reference/sutro/sdk.py:202-216 — jobs are independent
    submissions against one service); here same-model jobs share the
    decode batch. Admission pulls rows across jobs in (priority, seq)
    order, every slot carries its job, and results/progress/accounting
    stream through the job's own callbacks — a p0 3-row job admitted
    mid-flight of a p1 20k-row job rides free slots to completion
    without preempting p1's active rows."""

    job_id: str
    pending: List[GenRequest]
    on_result: Callable[["GenResult"], None]
    priority: int = 0
    seq: int = 0             # FIFO tiebreak within a priority
    on_progress: Optional[Callable[[Dict[str, Any]], None]] = None
    should_cancel: Optional[Callable[[], bool]] = None
    progress_every: float = 1.0
    # Row-level failure domain: a row whose decode/constrain raises is
    # re-admitted as a FRESH request up to ``row_retries`` times, then
    # quarantined as an error result (the job still completes).
    # ``on_row_event`` is the failure_log sink — every retry/quarantine
    # event streams through it (engine wires it to the jobstore).
    row_retries: int = 0
    on_row_event: Optional[Callable[[Dict[str, Any]], None]] = None
    row_attempts: Dict[int, int] = dataclasses.field(default_factory=dict)
    # Interactive serving tier (serving/gateway.py): ``on_token`` streams
    # every accepted token to the request's channel the moment the single
    # commit point (_accept_token) records it — all decode paths (single
    # step, fused windows, speculative verify, fast-forward) converge
    # there, so per-token streaming needs exactly one hook. ``interactive``
    # marks the ctx as a latency-priority request that may preempt batch
    # rows inside the EngineConfig.interactive_slots budget.
    on_token: Optional[Callable[[int, int, float], None]] = None
    interactive: bool = False
    # Session KV checkpointing (serving/gateway.py chat sessions): when
    # True — set by the gateway only while the tiered pool is on — a
    # finished row's page-aligned KV transfers into the radix prefix
    # store at release instead of being freed, so the session's NEXT
    # turn resumes by prefix hit (and tier promotion once the pages
    # demote) instead of re-prefilling the whole conversation.
    kv_checkpoint: bool = False
    # Stage-graph streaming handoff (engine/stagegraph.py): a downstream
    # stage's ctx starts with an EMPTY pending list and is fed rows as
    # upstream chunks finalize. ``hold_open() -> True`` keeps _sweep_done
    # from declaring the ctx complete while its feeders still run; the
    # executor flips it False once every upstream stage has drained.
    hold_open: Optional[Callable[[], bool]] = None
    # -- internal session state --
    prefix: Optional[_SharedPrefix] = None
    prefix_ready: bool = False  # _setup_prefix attempted (lazily, at
    #                             first admission opportunity — eager
    #                             setup would pin prefix pages for jobs
    #                             whose rows wait behind a full batch)
    # honest roofline attribution (telemetry/doctor.py): prefix tokens
    # this job got warm from the radix store vs prefix tokens it paid
    # to prefill itself — without the split, the first job eats the
    # whole shell cost in its spans and later jobs look faster than
    # the hardware
    prefix_saved: int = 0
    prefix_paid: int = 0
    stats: Dict[str, int] = dataclasses.field(
        default_factory=lambda: {"in": 0, "out": 0, "rows": 0}
    )
    # forensics trace (telemetry/traces.py): the propagated per-request
    # (gateway-assigned) or per-job (scheduler-assigned) trace id;
    # ``trace_enq_mono`` is the submit/park time the queue_wait span
    # measures from; ``trace_preempted`` holds row ids suspended by a
    # preemption so re-admission emits the matching resume event
    trace_id: Optional[str] = None
    trace_enq_mono: float = 0.0
    trace_preempted: set = dataclasses.field(default_factory=set)
    n_slots: int = 0         # live slots carrying this job
    done: bool = False
    started: float = 0.0
    t_last: float = 0.0


@dataclasses.dataclass
class _Slot:
    req: GenRequest
    pages: List[int]         # FULL table pages (shared prefix + own)
    pos: int                 # tokens currently in cache
    last_token: int
    job: Optional[JobCtx] = None
    shared_n: int = 0        # leading entries of ``pages`` owned by the
    #                          job's _SharedPrefix (not freed per slot)
    # Sarathi-style piggybacked prefill (long prompts): the slot is
    # reserved but advances one prefill chunk per scheduler iteration
    # (_prefill_tick) while OTHER slots keep decoding; it joins the
    # decode batch only once the whole prompt is in pages and its first
    # token is sampled. While prefilling, the slot's dense decode state
    # routes to the garbage page (its table row stays zero) so the
    # discarded decode writes can never clobber prefilled positions.
    prefilling: bool = False
    prefill_pos: int = 0     # next global position to prefill
    ptable: Optional[np.ndarray] = None  # real [MP] table for chunks
    # n-gram speculative draft state (built lazily at the first draft
    # lookup, maintained incrementally per accepted token): the full
    # token history and a bigram -> (last, previous) occurrence index,
    # so per-step draft lookups are O(K), not O(seq_len)
    hist: Optional[List[int]] = None
    bigram_idx: Optional[Dict[Tuple[int, int], Tuple[int, Optional[int]]]] = None
    out_ids: List[int] = dataclasses.field(default_factory=list)
    logprob_sum: float = 0.0
    # rolling decoded-byte tail for stop-sequence detection (window =
    # longest stop seq + the current token's bytes)
    tail: bytes = b""
    hit_stop_seq: bool = False
    stop_longest: int = 0  # cached max stop-seq length (set on arm)
    # generated-token counts for sampling penalties (only maintained
    # when the request uses them), plus the packed seen-bitmap for the
    # repetition scope (prompt + generated, vLLM/HF semantics) — built
    # incrementally so the per-step assembly is a memcpy, not an
    # O(vocab) packbits
    counts: Dict[int, int] = dataclasses.field(default_factory=dict)
    seen_bits: Optional[np.ndarray] = None  # uint8 [ceil(V/8)]


@dataclasses.dataclass
class _Hib:
    """A preempted slot's full host state, parked while its page-aligned
    KV sits in the tiered pool under ``key`` (engine/kvtier.py). Resume
    re-reserves pages, uploads the payload, re-prefills ONLY the
    sub-page tail (pos % page_size tokens), and arms the slot exactly
    where it stopped — the request's live constraint object continues
    in place, so nothing rewinds. A tier miss at resume falls back to
    the pre-tier path: the row regenerates from scratch (its constraint
    rebuilt from the factory, which victim selection guarantees
    exists)."""

    key: bytes               # tier-pool entry key (opaque, unique)
    pos: int                 # tokens whose KV was resident at suspend
    last_token: int
    out_ids: List[int]
    logprob_sum: float
    tail: bytes
    hit_stop_seq: bool
    stop_longest: int
    counts: Dict[int, int]
    seen_bits: Optional[np.ndarray]
    shared_tokens: int       # job shared-prefix coverage at suspend —
    #                          resume requires the SAME coverage (the
    #                          stored payload holds own pages only)
    n_pages: int             # own aligned pages stored under ``key``


class ContinuousBatcher:
    def __init__(
        self,
        runner: ModelRunner,
        stop_ids: List[int],
        *,
        seed: int = 0,
        token_bytes=None,  # tokenizer token_bytes(id) -> bytes; enables
        #                    GenRequest.stop_seqs detection
        prefix_store=None,  # engine-lifetime radix prefix store
        #                     (engine/prefixstore.py); None = today's
        #                     per-job prefix path, bit-identical
        kv_tier=None,  # tiered paged-KV pool (engine/kvtier.py):
        #                HBM -> host -> disk page migration + session
        #                hibernation; None = untiered, bit-identical
    ):
        self.runner = runner
        self.ecfg = runner.ecfg
        self.vocab = runner.mcfg.vocab_size
        self.stop_ids = set(int(s) for s in stop_ids)
        self.token_bytes = token_bytes
        self.B = self.ecfg.decode_batch_size
        self.MP = self.ecfg.max_pages_per_seq
        # hot-loop caches: max_context() and the stop-id membership are
        # consulted per accepted token (O(B*K) per window)
        self._max_ctx = self.ecfg.max_context()
        self._stop_arr = np.array(sorted(self.stop_ids), np.int64)
        # Native host runtime (native/runtime.cpp): page allocator +
        # admission + dense step-state arrays as zero-copy views. Falls
        # back to the pure-Python allocator when the toolchain is absent
        # or SUTRO_NATIVE_RUNTIME=0.
        from .native_runtime import maybe_native_runtime

        # allocators see alloc_pages, NOT num_pages: the difference is
        # the chunked-DMA over-read slack at the pool end, which must
        # stay unallocatable (runner._chunk_for_table / pallas_paged)
        alloc_pages = getattr(runner, "alloc_pages", runner.num_pages)
        self.native = maybe_native_runtime(
            alloc_pages, self.B, self.MP, self.ecfg.kv_page_size,
            self.ecfg.max_batch_tokens, self.ecfg.max_context(),
        )
        self.allocator = (
            None if self.native is not None
            else PageAllocator(alloc_pages)
        )
        # Cross-job radix prefix store: its pages live in THIS runner's
        # KV pool but the store outlives the session, so the fresh free
        # list above must give them up before any admission. A store
        # whose pages cannot be re-reserved (pool geometry changed, or
        # a mismatched page size) resets to empty instead of poisoning
        # the run — the ids are already free here, so forgetting the
        # tree is the only consistent move.
        self._prefix_store = None
        if (
            prefix_store is not None
            and prefix_store.page_size == self.ecfg.kv_page_size
        ):
            owned = prefix_store.owned_pages()
            ok = all(0 < p < alloc_pages for p in owned)
            if ok and owned:
                if self.native is not None:
                    ok = self.native.reserve_pages(owned)
                else:
                    try:
                        # the store keeps ownership of these pages (it
                        # frees them via reset()/eviction); reserve only
                        # marks them busy in the allocator's free list
                        self.allocator.reserve(owned)  # graftlint: disable=resource-leak
                    except KeyError:
                        ok = False
            if ok:
                self._prefix_store = prefix_store
            else:
                prefix_store.reset()
                self._prefix_store = prefix_store
        # Tiered paged-KV pool (engine/kvtier.py): page payloads below
        # HBM. Cold prefix-store leaves DEMOTE into it instead of
        # evicting, suspended rows HIBERNATE their pages there and
        # resume by page-upload, and completed session turns checkpoint
        # through the prefix store into it. A geometry mismatch
        # disables tiering for this session (the payloads would not be
        # page-compatible). Hibernation captures the partial tail page
        # too (ceil(pos/PS) own pages), so resume is a PURE page-upload
        # — no suffix prefill — which is why multi-page-group (sp/pp)
        # rows hibernate as well: read_pages/write_pages are
        # sharding-agnostic host copies, unlike runner.prefill(start>0).
        self._kv_tier = None
        if kv_tier is not None and kv_tier.page_size == self.ecfg.kv_page_size:
            self._kv_tier = kv_tier
        self._can_hibernate = self._kv_tier is not None
        # hibernated rows: (id(ctx), row_id) -> _Hib. Entries live only
        # while their ctx is live in THIS session (purged at job finish
        # / session suspend / run_multi exit), so id() reuse is safe.
        self._hibernated: Dict[Tuple[int, int], "_Hib"] = {}
        self._hib_seq = 0
        # session-level tier op counters (api.py stamps them into the
        # job's flight-recorder attrs for the doctor's kv_pressure /
        # resume_bound verdicts)
        self.tier_demotes = 0
        self.tier_promotes = 0
        self.slots: List[Optional[_Slot]] = [None] * self.B
        # per-slot generation counter: bumped on release so a pipelined
        # window dispatched against a slot's OLD occupant fails the
        # (slot, gen) check at processing time after the slot is reused
        self._gen = [0] * self.B
        self._key = jax.random.PRNGKey(seed)
        self._fixed_key = jax.random.PRNGKey(seed)
        self._step = 0
        # slot indices whose speculative window rejected a token: each
        # takes its FSM-masked step inside the NEXT window (allowed0),
        # so one adversarial row doesn't degrade the batch to masked
        # single-steps; only non-greedy constrained batches still fall
        # back to the masked single-step path
        self._needs_mask: set = set()
        # penalty id-buffer growth events already logged (power-of-two K)
        self._pk_grown: set = set()
        # n-gram speculative decoding acceptance counters (greedy
        # prompt-lookup path; rate = accepted / drafted)
        self.spec_drafted = 0
        self.spec_accepted = 0
        # FSM fast-forward ("jump decoding"): forced scaffold tokens
        # committed through parallel verify forwards instead of
        # step-by-step windows. The probe backoff bounds the O(B x V)
        # singleton scan on batches sitting in free-text regions.
        self.ff_forced = 0
        self._ff_probe_step = 0
        self._ff_backoff = 0
        # next step at which the n-gram speculative path may probe;
        # bumped with exponential backoff on failed probes / poor
        # acceptance so the pipelined windows keep RTT hidden between
        # attempts
        self._spec_probe_step = 0
        self._spec_backoff = 0
        # coverage pre-check result, computed ONCE per probe epoch
        # (keyed by _spec_probe_step): while a probe waits for the pipe
        # to drain, recomputing O(B*K) throwaway drafts every drain
        # iteration would repeat up to decode_lookahead times per probe
        self._spec_cov_key = -1
        self._spec_cov_ok = False
        # rolling acceptance window: engagement is decided by draft
        # COVERAGE, but staying engaged requires the accepted tokens to
        # actually beat a plain step (exit when the window's acceptance
        # rate drops below 1/SN, i.e. < ~1 extra token per row-step)
        self._spec_win_drafted = 0
        self._spec_win_accepted = 0
        # shared-prefix KV reuse (one per run; see _setup_prefix)
        self._prefix: Optional[_SharedPrefix] = None
        # preemptive priority ladder (engine/control.py): installed by
        # the engine when the control plane is on; None = the batch
        # path is bit-identical to a ladder-less build
        self.ladder = None
        # tokens actually sent through a prefill program this run —
        # the instrument proving the prefix cache's N-fold prefill
        # saving (input_tokens in progress streams stays the per-row
        # FULL prompt count: user-facing accounting is unchanged)
        self.prefill_tokens = 0
        # Double-buffered admission prep: a background thread builds
        # the NEXT admission group's lazy constraints while the device
        # runs the current window, so FSM instantiation leaves the
        # critical path. prep_overlap_s / prep_inline_s split the prep
        # cost into hidden-behind-device vs paid-inline for the host
        # overhead profile.
        self._prep_thread: Optional[Any] = None
        self._prep_q: Optional[Any] = None
        self.prep_overlap_s = 0.0
        self.prep_inline_s = 0.0
        self.prep_rows_overlapped = 0
        import threading as _threading

        # guards the overlap counters: _prep_stop joins with a timeout,
        # so a wedged worker can briefly coexist with its replacement —
        # two threads may then bump these counters concurrently
        self._prep_lock = _threading.Lock()
        from .profiling import StepTimer

        # telemetry latch (one decision per batcher, zero per-step cost
        # when off): the timer sink feeds every device-dispatch phase
        # into the stage histogram + flight recorder; _tel_jobs carries
        # the live co-batched job ids so batch-wide spans are
        # attributable per job
        self._tel_on = telemetry.enabled()
        self._tel_jobs: Tuple[str, ...] = ()
        # live co-batched trace ids (subset of _tel_jobs' ctxs that
        # carry one): batch-wide spans fan into each request's forensic
        # timeline (telemetry/traces.py)
        self._tel_traces: Tuple[str, ...] = ()
        # per-window device-time attribution (doctor roofline grades):
        # the decode/prefill loops stash {stage: {batch, steps, ...}}
        # here right before dispatch; the sink folds it into the span
        self._tel_attrs: Dict[str, Dict[str, Any]] = {}
        self.timer = StepTimer(
            sink=self._tel_sink if self._tel_on else None
        )

    def _tel_sink(self, phase: str, t0: float, dt: float) -> None:
        stage = _TEL_STAGE.get(phase, phase)
        # stage exemplar: point the aggregate histogram at one live
        # request's trace so a slow-bucket sample is resolvable
        telemetry.stage_observe(
            stage, dt,
            exemplar=self._tel_traces[0] if self._tel_traces else None,
        )
        extra = self._tel_attrs.get(stage)
        attrs = None
        if self._tel_jobs or extra:
            attrs = dict(extra or ())
            if self._tel_jobs:
                attrs["jobs"] = self._tel_jobs
        telemetry.RECORDER.record(stage, None, t0, dt, attrs)
        for tid in self._tel_traces:
            telemetry.TRACES.add(tid, stage, t0, dt, extra)

    # ------------------------------------------------------------------

    @property
    def free_page_count(self) -> int:
        if self.native is not None:
            return self.native.free_count
        return self.allocator.free_count

    def _max_total(self, req: GenRequest) -> int:
        return min(
            len(req.prompt_ids) + req.max_new_tokens,
            self.ecfg.max_context(),
        )

    def _inflight_tokens(self) -> int:
        return sum(
            self._max_total(s.req) for s in self.slots if s is not None
        )

    def _setup_prefix(self, ctx: JobCtx) -> None:
        """Detect the job's longest common PAGE-ALIGNED token prefix and
        prefill it once into shared pages (VERDICT r3 missing #5: the
        single largest chip-independent win for templated jobs — the
        reference's classify template sends one prompt shell for every
        row). Capped at min(len)-1 so every row still prefills >= 1 own
        token (its last-position logits seed the first sample). Skipped
        when: disabled, < 2 rows (1 with the radix store: a lone
        interactive request can hit — and seed — a cross-job shell),
        prefix < 1 page, the pages would starve admission, or under
        sp/pp (suffix prefill rides the chunked paged path, which
        neither wraps).

        With the engine-lifetime radix store attached this becomes
        LOOKUP → EXTEND → INSERT: the warm head of the shell pins
        store pages (prefilled by an EARLIER job — only the novel tail
        is prefilled here, at its offset), and the freshly prefilled
        tail transfers into the tree for the next job. A store crash
        during lookup (fault site ``prefixstore.lookup``) degrades to
        a plain miss — the job pays full prefill but never fails.
        Without a store: per-JOB pages, exactly the pre-store path."""
        ctx.prefix = None
        pending = ctx.pending
        ecfg = self.ecfg
        store = self._prefix_store
        if not getattr(ecfg, "prefix_cache", True):
            return
        if len(pending) < (1 if store is not None else 2):
            return
        if (
            getattr(self.runner, "sp", 1) > 1
            or getattr(self.runner, "pp", 1) > 1
        ):
            return
        PS = ecfg.kv_page_size
        first = pending[0].prompt_ids
        lcp = min(len(r.prompt_ids) for r in pending) - 1
        for r in pending[1:]:
            if lcp <= 0:
                return
            neq = np.nonzero(
                first[:lcp] != r.prompt_ids[:lcp]
            )[0]
            if len(neq):
                lcp = int(neq[0])
        shared = (lcp // PS) * PS
        if shared < PS:
            return
        n_pages = shared // PS
        # warm head from the radix store (pins the matched path);
        # any store raise is a plain miss — never a job failure
        handle = None
        if store is not None:
            try:
                if faults.ACTIVE is not None:
                    faults.inject("prefixstore.lookup", job=ctx.job_id)
                handle = store.lookup_pin(first[:shared])
                if not handle.nodes:
                    handle = None
            except Exception:
                logger.debug(
                    "prefix-store lookup failed; treating as miss",
                    exc_info=True,
                )
                handle = None
        try:
            if (
                store is not None
                and self._kv_tier is not None
                and (len(handle.nodes) if handle else 0) < n_pages
            ):
                # tier promotion: pages past the store hit may be warm
                # in the host/disk tiers (demoted earlier under
                # pressure, or an idle session's checkpoint) — upload
                # them into fresh pages instead of re-prefilling. Net
                # zero page pressure: each promoted page replaces a
                # tail page the prefill below would have allocated.
                handle = self._promote_prefix(ctx, first, n_pages, handle)
            hit_pages = list(handle.pages) if handle is not None else []
            hit = len(hit_pages) * PS
            tail_n = n_pages - len(hit_pages)
            # don't let the prefix starve admission: after taking its
            # NEW pages the WIDEST pending row must still fit. Under
            # pressure, unpinned LRU store pages are evicted back into
            # the free list first — live jobs always win over cached
            # shells.
            worst_own = max(
                pages_needed(self._max_total(r), PS) for r in pending
            ) - n_pages
            need_free = tail_n + max(worst_own, 1)
            if self.free_page_count < need_free:
                self._evict_store_pages(need_free - self.free_page_count)
            if self.free_page_count < need_free:
                if handle is not None:
                    store.release(handle)
                return
            if tail_n == 0:
                # full warm hit: nothing to prefill, nothing to insert
                ctx.prefix = _SharedPrefix(
                    tokens=shared, pages=hit_pages, handle=handle,
                    own_pages=[],
                )
                ctx.prefix_saved += shared
                if self._tel_on and ctx.trace_id is not None:
                    telemetry.TRACES.event(
                        ctx.trace_id, "prefix_hit",
                        {"saved_tokens": int(shared),
                         "paid_tokens": 0},
                    )
                return
            if self.native is not None:
                pages = self.native.alloc_pages(tail_n)
                if pages is None:
                    if handle is not None:
                        store.release(handle)
                    return
            else:
                pages = self.allocator.alloc(tail_n)
        except Exception:
            # eviction/allocation raising must not strand the pin — a
            # handle that never unpins blocks those pages from eviction
            # for the life of the store
            if handle is not None:
                store.release(handle)
            raise
        paid = shared - hit
        try:
            table = np.zeros((self.MP,), np.int32)
            table[: len(hit_pages)] = hit_pages
            table[len(hit_pages) : n_pages] = pages
            if self._tel_on:
                attrs = {"tokens": int(paid)}
                if store is not None:
                    attrs["prefix_saved"] = int(hit)
                    attrs["prefix_paid"] = int(paid)
                self._tel_attrs["prefill"] = attrs
            with self.timer.time("prefill"):
                # last-position logits are discarded: each row derives
                # its first sample from its OWN suffix prefill. Only
                # the novel tail runs, at its global offset — the warm
                # head is already resident in the store's pages.
                self.runner.prefill(
                    np.asarray(first[hit:shared], np.int32), table,
                    start=hit,
                )
        except Exception:
            # pin first (a cheap decref that cannot fail), pages second
            # — if the page free itself raises, the pin must already be
            # returned
            if handle is not None:
                store.release(handle)
            self._free_prefix_pages(pages)
            raise
        self.prefill_tokens += paid
        ctx.prefix_saved += hit
        ctx.prefix_paid += paid
        try:
            own = list(pages)
            if store is not None:
                h = (
                    handle if handle is not None else store.empty_handle()
                )
                if store.extend(h, first[hit:shared], list(pages)):
                    handle, own = h, []  # tail ownership moved to the
                    #                      store
                # extend declined (closed store): the tail stays
                # session-owned; a non-empty original handle still pins
                # the head
        except Exception:
            # a store raise mid-extend must not strand the pin (or the
            # freshly prefilled tail pages, which the store declined);
            # pin first — it cannot fail
            if handle is not None:
                store.release(handle)
            self._free_prefix_pages(pages)
            raise
        if handle is not None and not handle.nodes:
            handle = None
        ctx.prefix = _SharedPrefix(
            tokens=shared, pages=hit_pages + list(pages),
            handle=handle, own_pages=own,
        )
        if self._tel_on and ctx.trace_id is not None:
            if hit:
                telemetry.TRACES.event(
                    ctx.trace_id, "prefix_hit",
                    {"saved_tokens": int(hit),
                     "paid_tokens": int(paid)},
                )
            if store is not None and not own:
                # freshly prefilled tail transferred into the radix
                # tree — the next job's warm head
                telemetry.TRACES.event(
                    ctx.trace_id, "prefix_extend",
                    {"tokens": int(paid)},
                )

    def _free_prefix_pages(self, pages: List[int]) -> None:
        if self.native is not None:
            self.native.free_pages(pages)
        else:
            self.allocator.free(pages)

    def _release_prefix(self, pfx: _SharedPrefix) -> None:
        """The ONLY way a _SharedPrefix goes away: unpin the store-owned
        head (the pages STAY resident — and out of the allocator — for
        the next job; that's the cache) and free the session-owned
        remainder to the pool."""
        if pfx.handle is not None and self._prefix_store is not None:
            self._prefix_store.release(pfx.handle)
        own = pfx.pages if pfx.own_pages is None else pfx.own_pages
        if own:
            self._free_prefix_pages(own)

    def _evict_store_pages(self, n_pages: int) -> int:
        """Allocation-pressure hook: pull up to ``n_pages`` unpinned LRU
        pages out of the radix store and hand them back to THIS
        session's allocator (they were reserved at construction).
        Returns the number actually freed. With the tiered pool on,
        victims DEMOTE — their payloads migrate to host RAM keyed by
        full token prefix — instead of being dropped, so a later job's
        lookup can promote them back instead of re-prefilling."""
        if n_pages <= 0 or self._prefix_store is None:
            return 0
        if self._kv_tier is not None:
            return self._demote_store_pages(n_pages)
        freed = self._prefix_store.evict(n_pages)
        if freed:
            self._free_prefix_pages(freed)
        return len(freed)

    def _demote_store_pages(self, n_pages: int) -> int:
        """Tiered eviction: pull unpinned LRU leaves out of the radix
        store, read their payloads off the device (one batched
        synchronous fetch — the ids go back to the allocator the moment
        it returns), and stage them into the tier pool asynchronously.
        A read/stage failure degrades that page to a plain eviction;
        the freed count is what matters to the caller either way."""
        pairs = self._prefix_store.demote(n_pages)
        if not pairs:
            return 0
        ids = [p for _, p in pairs]
        with self.timer.time("kv_demote"):
            try:
                raw = self.runner.read_pages(ids)
                for j, (path_bytes, _) in enumerate(pairs):
                    per = {
                        k: np.ascontiguousarray(v[:, j : j + 1])
                        for k, v in raw.items()
                    }
                    self._kv_tier.put_page(path_bytes, per)
                self.tier_demotes += len(pairs)
            except Exception:  # noqa: BLE001 — a failed read degrades
                # to a plain eviction; the pages still free below
                logger.warning(
                    "kv tier demotion read failed; evicting plainly",
                    exc_info=True,
                )
        self._free_prefix_pages(ids)
        return len(pairs)

    def _promote_prefix(self, ctx: JobCtx, first, n_pages: int, handle):
        """Probe the tier pool for consecutive prefix pages past the
        radix-store hit, upload them into freshly allocated pages, and
        graft them onto ``handle`` (``store.promote``). Returns the
        possibly-extended handle; every failure path returns the
        original handle and the caller pays plain tail prefill — a
        tier problem never fails a job."""
        store = self._prefix_store
        tier = self._kv_tier
        PS = self.ecfg.kv_page_size
        k = len(handle.nodes) if handle is not None else 0
        hits: List[Tuple[bytes, dict]] = []
        while k + len(hits) < n_pages:
            key = tier.prefix_key(first[: (k + len(hits) + 1) * PS])
            p = tier.get_page(key)
            if p is None:
                break  # consecutive run only: page i is useless
                #        without page i-1 (causal attention)
            hits.append((key, p))
        if not hits:
            return handle
        n = len(hits)
        if self.native is not None:
            pages = self.native.alloc_pages(n)
            if pages is None:
                return handle
            pages = list(pages)
        else:
            if n > self.allocator.free_count:
                return handle
            pages = self.allocator.alloc(n)
        try:
            payload = {
                pk: np.concatenate([p[pk] for _, p in hits], axis=1)
                for pk in hits[0][1]
            }
            with self.timer.time("kv_promote"):
                self.runner.write_pages(pages, payload)
        except Exception:  # noqa: BLE001 — degrade to re-prefill
            self._free_prefix_pages(pages)
            logger.warning(
                "kv tier promotion upload failed; re-prefilling",
                exc_info=True,
            )
            return handle
        h = handle if handle is not None else store.empty_handle()
        if not store.promote(h, first[k * PS : (k + n) * PS], pages):
            # racer re-inserted the run / store closed: keep the tier
            # copy, return our upload, pay the plain tail prefill
            self._free_prefix_pages(pages)
            return handle
        tier.discard([key for key, _ in hits])
        self.tier_promotes += n
        if self._tel_on and ctx.trace_id is not None:
            telemetry.TRACES.event(
                ctx.trace_id, "kv_promote", {"pages": n}
            )
        return h

    def _reserve(
        self, req: GenRequest, ctx: JobCtx, reserved: int = 0,
        exclude=frozenset(),
    ):
        """Reserve a slot + worst-case pages for ``req``. Returns
        ``(slot_idx, own_pages, table)`` or None. No device work happens
        here — prefill/sampling run in ``_admit_batch`` so several
        reserved rows can share one dispatch. Slots are only *armed*
        there, so same-batch state lives in the arguments: ``reserved``
        carries the worst-case tokens of rows reserved but not yet
        armed, ``exclude`` their slot indices (the native runtime tracks
        both internally — its slots go active at try_admit). With the
        job's shared prefix active, the table head carries the prefix
        pages and only the remainder is allocated per slot."""
        n = len(req.prompt_ids)
        pfx = ctx.prefix

        def _admit_native():
            if pfx is not None:
                return self.native.try_admit_pfx(
                    n, req.max_new_tokens, pfx.pages
                )
            return self.native.try_admit(n, req.max_new_tokens)

        if self.native is not None:
            free_idx = _admit_native()
            if free_idx < 0 and self._prefix_store is not None:
                # allocation pressure: a page shortage may be cached
                # shells, not live rows — evict unpinned LRU store
                # pages into the free list and retry once
                need = pages_needed(
                    self._max_total(req), self.ecfg.kv_page_size
                )
                short = need - self.native.free_count
                if short > 0 and self._evict_store_pages(short):
                    free_idx = _admit_native()
            if free_idx < 0:
                return None
            assert self.slots[free_idx] is None
            table = self.native.table[free_idx]
            pages = self.native.slot_pages(free_idx)  # own pages only
        else:
            free_idx = next(
                (
                    i
                    for i, s in enumerate(self.slots)
                    if s is None and i not in exclude
                ),
                None,
            )
            if free_idx is None:
                return None
            total = self._max_total(req)
            need = pages_needed(total, self.ecfg.kv_page_size)
            if need > self.MP:
                return None
            npfx = pfx.n_pages if pfx is not None else 0
            # native-clamp parity (rt_try_admit_pfx): a prefix covering
            # the whole need still allocates 1 own page (every row
            # prefills >= 1 own token) and admits while the table row
            # has room — the old `own < 1 -> reject` starved rows whose
            # shared prefix was bigger than their worst case
            own = max(need - npfx, 1)
            if npfx + own > self.MP:
                return None
            if own > self.allocator.free_count:
                # allocation pressure: evict unpinned LRU store pages
                # back into the free list before refusing the row
                self._evict_store_pages(
                    own - self.allocator.free_count
                )
                if own > self.allocator.free_count:
                    return None
            inflight = self._inflight_tokens() + reserved
            if (
                inflight > 0
                and inflight + total > self.ecfg.max_batch_tokens
            ):
                return None
            pages = self.allocator.alloc(own)
            table = np.zeros((self.MP,), np.int32)
            if pfx is not None:
                table[: pfx.n_pages] = pfx.pages
                table[pfx.n_pages : pfx.n_pages + own] = pages
            else:
                table[: len(pages)] = pages
        return free_idx, pages, table

    # -- double-buffered admission prep --------------------------------

    def _materialize_constraint(self, req: GenRequest) -> None:
        """Resolve a lazy constraint at admission: take the prep
        thread's handoff when ready, else build inline. Runs on the
        scheduler thread only; after this, ``req.constraint`` never
        changes again (slots rely on it)."""
        if req.constraint is not None or req.constraint_factory is None:
            return
        if faults.ACTIVE is not None:
            faults.inject("constrain.compile", row=req.row_id)
        c = req.prepped_constraint
        if c is not None:
            req.constraint = c
            req.prepped_constraint = None
            return
        t0 = time.perf_counter()
        req.constraint = req.constraint_factory()
        dt = time.perf_counter() - t0
        self.prep_inline_s += dt
        if self._tel_on:
            telemetry.stage_observe("constraint_compile", dt)
            telemetry.RECORDER.record(
                "constraint_compile", None, time.monotonic() - dt, dt,
                {"jobs": self._tel_jobs, "row": req.row_id}
                if self._tel_jobs else {"row": req.row_id},
            )

    def _prep_worker(self, q) -> None:
        while True:
            req = q.get()
            if req is None:
                return
            t0 = time.perf_counter()
            try:
                if (
                    req.constraint is None
                    and req.prepped_constraint is None
                    and req.constraint_factory is not None
                ):
                    # single-assignment handoff: only this thread
                    # writes prepped_constraint, only the scheduler
                    # consumes it (worst race: the scheduler admitted
                    # the row mid-build and this FSM is dropped)
                    req.prepped_constraint = req.constraint_factory()
                    with self._prep_lock:
                        self.prep_rows_overlapped += 1
            except Exception:
                logger.exception("admission prep failed; admission "
                                 "will rebuild inline")
            dt = time.perf_counter() - t0
            with self._prep_lock:
                self.prep_overlap_s += dt
            if self._tel_on:
                # overlapped builds hide behind device windows but are
                # still real work on the timeline
                telemetry.stage_observe("constraint_compile", dt)

    def _prep_pump(self, order: List["JobCtx"]) -> None:
        """Queue the NEXT admission group's lazy constraints for the
        background prep thread. Called once per scheduler iteration —
        the builds overlap the device window dispatched below. Admission
        pops from the TAIL of ``ctx.pending``, so the tail is what gets
        prepped; the budget covers two groups (double buffering)."""
        budget = 2 * self.ecfg.prefill_batch_size
        want: List[GenRequest] = []
        for ctx in order:
            if ctx.done:
                continue
            for req in reversed(ctx.pending):
                if budget == 0:
                    break
                budget -= 1
                if (
                    req.constraint is None
                    and req.constraint_factory is not None
                    and not req.prep_queued
                ):
                    want.append(req)
            if budget == 0:
                break
        if not want:
            return
        if self._prep_thread is None or not self._prep_thread.is_alive():
            import queue as _queue
            import threading as _threading

            self._prep_q = _queue.SimpleQueue()
            self._prep_thread = _threading.Thread(
                target=self._prep_worker, args=(self._prep_q,),
                daemon=True, name="sutro-admit-prep",
            )
            self._prep_thread.start()
        for req in want:
            req.prep_queued = True
            self._prep_q.put(req)

    def _prep_stop(self) -> None:
        """End-of-session shutdown: a long-lived engine runs one
        session per job — leaking one thread per job would accumulate."""
        t, self._prep_thread = self._prep_thread, None
        if t is not None and t.is_alive():
            self._prep_q.put(None)
            t.join(timeout=30)
        self._prep_q = None

    def _unreserve(self, slot_idx: int, pages) -> None:
        """Roll back a reservation whose prefill never armed a slot (a
        raised prefill would otherwise leak the slot's pages forever in a
        long-lived daemon)."""
        if self.native is not None:
            self.native.release(slot_idx)
        else:
            self.allocator.free(pages)

    def _admit_batch(self, batch) -> None:
        """``batch`` is a list of ``(req, ctx, slot_idx, pages, table)``
        reservations — possibly spanning JOBS (co-batched admission).
        Runs ONE batched prefill dispatch + ONE batched first-token
        sample for all of them, then arms the slots. Each row prefills
        its own suffix at its job's shared-prefix offset."""
        reqs = [b[0] for b in batch]
        starts = [
            b[1].prefix.tokens if b[1].prefix is not None else 0
            for b in batch
        ]
        try:
            if self._tel_on:
                self._tel_attrs["prefill"] = {
                    "tokens": int(
                        sum(
                            len(r.prompt_ids) - s
                            for r, s in zip(reqs, starts)
                        )
                    ),
                    "batch": len(batch),
                }
            with self.timer.time("prefill"):
                if len(batch) == 1:
                    logits = self.runner.prefill(
                        reqs[0].prompt_ids[starts[0] :].astype(np.int32),
                        batch[0][4], start=starts[0],
                    )[None]
                elif any(starts):
                    logits = self.runner.prefill_batch_at(
                        [
                            r.prompt_ids[s:].astype(np.int32)
                            for r, s in zip(reqs, starts)
                        ],
                        np.stack([b[4] for b in batch]),
                        starts,
                    )
                else:
                    logits = self.runner.prefill_batch(
                        [r.prompt_ids.astype(np.int32) for r in reqs],
                        np.stack([b[4] for b in batch]),
                    )
            self.prefill_tokens += sum(
                len(r.prompt_ids) - s for r, s in zip(reqs, starts)
            )
            toks, logps = self._sample_batch(
                logits, reqs, [b[2] for b in batch]
            )
        except Exception:
            for _, _, slot_idx, pages, _ in batch:
                self._unreserve(slot_idx, pages)
            raise
        for (req, ctx, slot_idx, pages, _), tok, logp in zip(
            batch, toks, logps
        ):
            pfx = ctx.prefix
            first = int(tok)
            slot = _Slot(
                req=req,
                pages=(list(pfx.pages) + list(pages)) if pfx else pages,
                pos=len(req.prompt_ids),
                last_token=first,
                job=ctx,
                shared_n=pfx.n_pages if pfx else 0,
            )
            ctx.n_slots += 1
            ctx.stats["in"] += len(req.prompt_ids)
            ctx.stats["out"] += 1  # the prefill-sampled first token
            self._seed_penalty_bits(slot, req)
            self.slots[slot_idx] = slot
            if self.native is not None:
                self.native.arm_slot(
                    slot_idx, len(req.prompt_ids), first,
                    req.temperature, req.top_p, req.top_k,
                )
            self._record_token(slot, first, float(logp))
            self._deliver_token(slot, first, float(logp))

    def _seed_penalty_bits(self, slot: _Slot, req: GenRequest) -> None:
        if req.has_penalties():
            # repetition scope includes the PROMPT (vLLM/HF)
            bits = np.zeros((self.vocab + 7) // 8, np.uint8)
            ids = np.unique(np.asarray(req.prompt_ids, np.int64))
            ids = ids[(ids >= 0) & (ids < self.vocab)]
            np.bitwise_or.at(
                bits, ids // 8, (0x80 >> (ids % 8)).astype(np.uint8)
            )
            slot.seen_bits = bits

    def _admit_prefilling(
        self, req: GenRequest, ctx: JobCtx, slot_idx: int, pages, table
    ) -> None:
        """Arm a PREFILLING slot: pages reserved, no device work yet.
        ``_prefill_tick`` advances it one chunk per scheduler iteration;
        the decode batch keeps running in between (the Sarathi
        observation: a long admit must degrade active rows' cadence by
        a bounded fraction, not pause them for the whole prefill)."""
        pfx = ctx.prefix
        shared = pfx.tokens if pfx is not None else 0
        full = np.array(table, np.int32, copy=True)
        slot = _Slot(
            req=req,
            pages=(list(pfx.pages) + list(pages)) if pfx else pages,
            pos=shared,
            last_token=0,
            job=ctx,
            shared_n=pfx.n_pages if pfx else 0,
            prefilling=True,
            prefill_pos=shared,
            ptable=full,
        )
        self.slots[slot_idx] = slot
        ctx.n_slots += 1
        if self.native is not None:
            # while prefilling, the slot's DENSE table row routes the
            # (discarded) decode writes to the garbage page — they must
            # never clobber already-prefilled positions. The real table
            # lives on slot.ptable for the chunk dispatches and is
            # restored at activation.
            self.native.table[slot_idx, :] = 0

    def _prefill_tick(self) -> None:
        """Advance the lowest-index prefilling slot by ONE chunk; on the
        final chunk, sample its first token and join the decode batch."""
        i = next(
            (
                j
                for j, s in enumerate(self.slots)
                if s is not None and s.prefilling
            ),
            None,
        )
        if i is None:
            return
        s = self.slots[i]
        req = s.req
        C = self.ecfg.prefill_chunk
        seg = req.prompt_ids[s.prefill_pos : s.prefill_pos + C]
        if self._tel_on:
            self._tel_attrs["prefill"] = {"tokens": int(len(seg))}
        with self.timer.time("prefill"):
            logits = self.runner.prefill_batch_at(
                [np.asarray(seg, np.int32)],
                s.ptable[None, :],
                [s.prefill_pos],
            )
        self.prefill_tokens += len(seg)
        s.prefill_pos += len(seg)
        if s.prefill_pos < len(req.prompt_ids):
            return
        # last chunk: sample the first token and activate
        toks, logps = self._sample_batch(logits, [req], [i])
        first = int(toks[0])
        if self.native is not None:
            row = self.native.table[i]
            row[:] = 0
            row[: len(s.pages)] = s.pages
            self.native.arm_slot(
                i, len(req.prompt_ids), first,
                req.temperature, req.top_p, req.top_k,
            )
        s.prefilling = False
        s.ptable = None
        s.pos = len(req.prompt_ids)
        s.last_token = first
        self._seed_penalty_bits(s, req)
        if s.job is not None:
            s.job.stats["in"] += len(req.prompt_ids)
            s.job.stats["out"] += 1  # the prefill-sampled first token
        self._record_token(s, first, float(logps[0]))
        self._deliver_token(s, first, float(logps[0]))

    @staticmethod
    def _hist_push(s: _Slot, tok: int) -> None:
        """Append one token to the slot's draft history, updating the
        bigram occurrence index — (last, previous) per bigram, so the
        lookup can skip the terminal pair itself. O(1) per token."""
        h = s.hist
        if h:
            key = (h[-1], tok)
            cur = s.bigram_idx.get(key)
            s.bigram_idx[key] = (
                len(h) - 1,
                cur[0] if cur is not None else None,
            )
        h.append(tok)

    def _ngram_draft(self, s: _Slot, K: int) -> Optional[np.ndarray]:
        """Prompt-lookup draft for a greedy row: find the most recent
        PRIOR occurrence of the sequence's last bigram in its own
        prompt+output history and propose the tokens that followed it
        (classify rationales echo prompt text heavily — the VERDICT's
        observation). Capped so the verify dispatch's K/V writes stay
        inside the row's reserved pages. None = no draft this step.
        The history + bigram index build once per row and extend
        incrementally (_record_token), so this is O(K) per step."""
        cap = len(s.pages) * self.ecfg.kv_page_size - s.pos - 1
        K = min(K, cap)
        if K < 1:
            return None
        if s.hist is None:
            s.hist = []
            s.bigram_idx = {}
            for t in list(s.req.prompt_ids) + list(s.out_ids):
                self._hist_push(s, int(t))
        h = s.hist
        if len(h) < 3:
            return None
        cur = s.bigram_idx.get((h[-2], h[-1]))
        if cur is None:
            return None
        j = cur[0]
        if j == len(h) - 2:  # the terminal pair itself: use the prior
            j = cur[1]
            if j is None:
                return None
        d = h[j + 2 : j + 2 + K]
        return np.asarray(d, np.int32) if d else None

    def _fastforward_step(self, active, last, past_len, table) -> bool:
        """FSM fast-forward ("jump decoding") via masked-candidate
        verification: each constrained row PLANS a jump along its
        forced byte path (fsm.plan_fastforward — purely functional, no
        FSM mutation): draft tokens plus the SMALL candidate mask at
        every token boundary. One parallel forward then yields each
        planned position's argmax over its candidates — the EXACT
        masked-path token — so a whole scaffold commits per dispatch
        and every planned position lands a valid token (no rejections;
        a flagged row with a plan gets its masked step as the plan's
        first position). Under byte tokenization candidates are
        singletons; under BPE vocabs they are the path's prefix
        tokenizations, still small. Unplanned rows ride as plain
        greedy steps (constrained ones verified by ``token_allowed``,
        the speculative window's rule).

        Exact vs the every-step-masked path: each accepted token is the
        argmax over the same budget-filtered mask, conditioned on the
        same accepted prefix; acceptance stops at the first draft
        divergence AFTER taking that position's masked token, and
        logprobs come from the candidate-set softmax (== the masked
        distribution). Plans never mutate FSMs, so returning False
        leaves no trace."""
        FF = getattr(self.ecfg, "constrain_fastforward", 0)
        if FF <= 0 or self._step < self._ff_probe_step:
            return False
        PS = self.ecfg.kv_page_size
        MAXC = 32
        flagged = self._needs_mask & set(active)
        plans = {}
        total = 0
        for i in active:
            s = self.slots[i]
            c = s.req.constraint
            plan_fn = getattr(c, "plan_fastforward", None)
            p = None
            if plan_fn is not None:
                rem = self._remaining(s.req, len(s.out_ids), s.pos)
                cap = min(FF, len(s.pages) * PS - s.pos - 1, rem)
                if cap >= 1:
                    p = plan_fn(rem, cap, MAXC)
            if p is None:
                if i in flagged:
                    # ANY flagged row this dispatch cannot plan for
                    # (no plan_fastforward, no capacity, or no
                    # plannable masked step) must get the window's
                    # allowed0 recovery — riding as an unmasked
                    # greedy step would re-flag it forever
                    self._ff_fail_backoff()
                    return False
                continue
            plans[i] = p
            total += len(p[1])
        if total < 2 * len(active):
            self._ff_fail_backoff()
            return False
        # a flagged row WITH a plan takes its masked step as the plan's
        # first position
        self._needs_mask -= set(plans)
        self._ff_backoff = 0
        # static shapes: pad to the configured width regardless of this
        # step's plans — a data-dependent K would retrace the verify
        # program per distinct length (the n-gram path pads the same way)
        K = FF
        C = K + 1
        drafts = np.zeros((self.B, K), np.int32)
        dlens = np.zeros((self.B,), np.int32)
        cand = np.zeros((self.B, C, MAXC), np.int32)
        cand_n = np.zeros((self.B, C), np.int32)
        for i, (draft, cands) in plans.items():
            dlens[i] = len(draft)
            if draft:
                drafts[i, : len(draft)] = draft
            for j, cs in enumerate(cands):
                cand[i, j, : len(cs)] = cs
                cand_n[i, j] = len(cs)
        # unconstrained greedy riders carry their own n-gram drafts
        # when speculation is opted in (spec_ngram_draft > 0): verified
        # against the PLAIN greedy outputs with the spec accept rule,
        # they take up to K+1 tokens from the dispatch instead of 1
        spec_riders = set()
        SN = getattr(self.ecfg, "spec_ngram_draft", 0)
        if SN > 0:
            for i in active:
                if i in plans or self.slots[i].req.constraint is not None:
                    continue
                d = self._ngram_draft(self.slots[i], min(SN, K))
                if d is None:
                    continue
                spec_riders.add(i)
                drafts[i, : len(d)] = d
                dlens[i] = len(d)
        with self.timer.time("decode"):
            ct, cl, pt, pl = self.runner.verify_candidates(
                np.asarray(last, np.int32), drafts, dlens,
                cand, cand_n, np.asarray(past_len, np.int32), table,
            )
        self._step += 1
        for i in active:
            s = self.slots[i]
            ctx = s.job
            if i in plans:
                draft, cands = plans[i]
                jumped = 0  # draft-matching accepts only: the final
                #             free-choice/diverged token is an ordinary
                #             masked step, not a jump — counting it
                #             would overstate ff_forced
                for j in range(len(cands)):
                    tok = int(ct[i, j])
                    matched = j < len(draft) and tok == draft[j]
                    if matched:
                        jumped += 1
                    if self._accept_token(i, tok, float(cl[i, j])):
                        break
                    if not matched:
                        # diverged from the draft (or the plan's final
                        # free position): later positions are
                        # conditioned on the draft, not on this token
                        break
                self.ff_forced += jumped
                if ctx is not None and jumped:
                    ctx.stats["ff_forced"] = (
                        ctx.stats.get("ff_forced", 0) + jumped
                    )
                continue
            if i in spec_riders:
                # n-gram draft verified against the plain greedy
                # outputs (shared spec accept rule)
                self._spec_accept_row(
                    i, int(dlens[i]), drafts[i], pt[i], pl[i]
                )
                continue
            # unplanned rider: plain greedy step at position 0
            tok = int(pt[i, 0])
            c = s.req.constraint
            if c is not None:
                rem = self._remaining(s.req, len(s.out_ids), s.pos)
                if not self._token_ok(c, tok, rem):
                    # next iteration's window opens with this row's
                    # FSM-masked step (allowed0 recovery)
                    self._needs_mask.add(i)
                    continue
            self._accept_token(i, tok, float(pl[i, 0]))
        return True

    def _ff_fail_backoff(self) -> None:
        """Exponential re-probe backoff (2..32 window lengths) after a
        disengaged fast-forward scan: free-text regions (non-singleton
        masks) would otherwise pay the O(rows x V) mask scan before
        every window dispatch."""
        KS = max(self.ecfg.decode_multi_step, 1)
        self._ff_backoff = min(max(self._ff_backoff * 2, 2 * KS), 32 * KS)
        self._ff_probe_step = self._step + self._ff_backoff

    def _spec_accept_row(self, i, L, drafts_row, toks_row, logps_row):
        """THE spec accept rule (one definition shared by the n-gram
        step and fast-forward spec riders so it cannot drift): accept
        the longest matching draft prefix plus the bonus token at the
        first mismatch, maintaining the drafted/accepted counters and
        per-job stats."""
        s = self.slots[i]
        ctx = s.job
        self.spec_drafted += L
        if ctx is not None and L:
            ctx.stats["spec_drafted"] = (
                ctx.stats.get("spec_drafted", 0) + L
            )
        for j in range(L + 1):
            tok = int(toks_row[j])
            matched = j < L and int(drafts_row[j]) == tok
            if matched:
                self.spec_accepted += 1
                if ctx is not None:
                    ctx.stats["spec_accepted"] = (
                        ctx.stats.get("spec_accepted", 0) + 1
                    )
            if (
                self._accept_token(i, tok, float(logps_row[j]))
                or not matched
            ):
                # row finished, or the bonus token at the first
                # mismatch was consumed — later positions are
                # conditioned on a rejected prefix
                break

    def _spec_fail_backoff(self) -> None:
        """Push the next speculative probe out with exponential backoff
        (4..64 window lengths): batches that never draft — or draft but
        never accept — settle into long pipelined stretches with only
        rare, cheap probes instead of paying a recurring drain bubble."""
        KS = max(self.ecfg.decode_multi_step, 1)
        self._spec_backoff = min(
            max(self._spec_backoff * 2, 4 * KS), 64 * KS
        )
        self._spec_probe_step = self._step + self._spec_backoff
        # a disengagement ends the acceptance window: the next
        # engagement's exit decision must not be skewed by stale counts
        self._spec_win_drafted = 0
        self._spec_win_accepted = 0

    def _split_pfx(self, active):
        """Operands for Hydragen-style split decode (Pallas path,
        EngineConfig.prefix_split): a tuple of ``(pfx_pages [Pp_g]
        int32, pfx_len [B] int32)`` groups, one per distinct
        shared-prefix PAGE RUN among the active rows (co-batched
        templated jobs each get their own group UNLESS the prefix
        store gave them the very same pages, in which case they merge
        into one group and the shared pages are read once; member row
        sets are disjoint, so the carries combine exactly —
        ops/attention.py). ``None`` when disabled, on the fallback
        path, or when no active row belongs to a prefix."""
        if not getattr(self.ecfg, "prefix_split", False):
            return None
        if not getattr(self.runner, "use_pallas", False):
            return None
        groups = []
        by_pages = {}  # page-run tuple -> index into groups
        seen = set()
        for i in active:
            ctx = self.slots[i].job
            if ctx is None or ctx.prefix is None or id(ctx) in seen:
                continue
            seen.add(id(ctx))
            pages = ctx.prefix.pages
            key = tuple(pages)
            gi = by_pages.get(key)
            if gi is None:
                by_pages[key] = len(groups)
                # pad the page list to a power-of-two bucket so
                # distinct template lengths don't each retrace the
                # fused decode programs (the pad pages are the garbage
                # page 0, fully masked by pfx_len in the carry; the
                # kernel skips only the REAL pfx_len // PS pages)
                cap = 1
                while cap < len(pages):
                    cap *= 2
                padded = np.zeros((cap,), np.int32)
                padded[: len(pages)] = pages
                groups.append((padded, np.zeros((self.B,), np.int32)))
                gi = len(groups) - 1
            pfx_len = groups[gi][1]
            for j in active:
                if self.slots[j].job is ctx:
                    pfx_len[j] = ctx.prefix.tokens
        if not groups:
            return None
        # the tuple's pytree STRUCTURE is a jit trace key: bound the
        # recompiles from varying group counts by (a) sorting groups by
        # page-bucket size so (4,8) and (8,4) share a structure and
        # (b) padding the count to a power of two with dummy groups
        # (1 garbage page, all-zero pfx_len -> provably cold carry,
        # an exact no-op costing one tiny masked gather+einsum)
        groups.sort(key=lambda g: -len(g[0]))
        n = 1
        while n < len(groups):
            n *= 2
        while len(groups) < n:
            groups.append(
                (
                    np.zeros((1,), np.int32),
                    np.zeros((self.B,), np.int32),
                )
            )
        return tuple(groups)

    def _spec_enough(self, n_draft: int, active) -> bool:
        """THE engagement threshold (one definition so the in-loop
        pre-check and _spec_ngram_step cannot drift): at least half the
        active rows draft."""
        return 2 * n_draft >= len(active)

    def _spec_drafts(self, active) -> dict:
        """All active rows' n-gram drafts for this step ({slot: draft};
        rows with none absent). Computed ONCE per engaged step and
        reused for both the threshold and the verify operands."""
        SN = self.ecfg.spec_ngram_draft
        out = {}
        for i in active:
            d = self._ngram_draft(self.slots[i], SN)
            if d is not None:
                out[i] = d
        return out

    def _spec_coverage_ok(self, active) -> bool:
        """Engagement rule for the in-loop pre-check (drafts here are
        throwaway: positions advance during the pipe drain, so the
        engage-time drafts are recomputed by _spec_ngram_step)."""
        return self._spec_enough(len(self._spec_drafts(active)), active)

    def _spec_ngram_step(self, active, last, past_len, table) -> bool:
        """One prompt-lookup speculative step for an all-greedy batch:
        verify every drafting row's tokens in ONE parallel forward and
        accept each row's longest matching prefix plus the standard
        bonus token at the first mismatch (>= 1 token per row, up to
        K+1 — exact greedy either way). Rows with no draft this step
        ride along as draft_len-0 plain greedy steps (verify_greedy
        supports them natively), so one draftless row cannot disable
        speculation for the rest of the batch. Returns False — caller
        falls back to fused windows — only when fewer than half the
        active rows draft: the verify dispatch is host-synchronous, so
        at low draft coverage the RTT-hiding pipelined windows win."""
        dmap = self._spec_drafts(active)
        if not self._spec_enough(len(dmap), active):
            return False
        SN = self.ecfg.spec_ngram_draft
        drafts = np.zeros((self.B, SN), np.int32)
        dlens = np.zeros((self.B,), np.int32)
        for i, d in dmap.items():
            drafts[i, : len(d)] = d
            dlens[i] = len(d)
        d0, a0 = self.spec_drafted, self.spec_accepted
        with self.timer.time("decode"):
            toks_v, logp_v = self.runner.verify_greedy(
                np.asarray(last, np.int32), drafts, dlens,
                np.asarray(past_len, np.int32), table,
            )
        self._step += 1
        for i in active:
            self._spec_accept_row(
                i, int(dlens[i]), drafts[i], toks_v[i], logp_v[i]
            )
        # acceptance-based exit (coverage got us here; acceptance keeps
        # us here): once the rolling window has seen enough drafts,
        # leave the host-synchronous spec path unless it beats a plain
        # step (>= 1 accepted token per SN drafted, i.e. rate >= 1/SN)
        self._spec_win_drafted += self.spec_drafted - d0
        self._spec_win_accepted += self.spec_accepted - a0
        if self._spec_win_drafted >= 8 * SN:
            if self._spec_win_accepted * SN < self._spec_win_drafted:
                self._spec_fail_backoff()
            else:
                self._spec_backoff = 0
            self._spec_win_drafted = 0
            self._spec_win_accepted = 0
        return True

    def _pad_mask(self, mask: np.ndarray) -> np.ndarray:
        """Constraint masks are sized to the *tokenizer* vocab; pad to the
        (possibly larger, padded) model vocab with False so padding token
        ids are never sampled under a schema constraint."""
        if len(mask) == self.vocab:
            return mask
        out = np.zeros((self.vocab,), bool)
        out[: len(mask)] = mask[: self.vocab]
        return out

    def _constraint_mask(self, c: TokenConstraint, remaining: int) -> np.ndarray:
        # Probe the signature once per implementation: a TypeError raised
        # *inside* a budget-aware allowed_tokens must propagate, not
        # silently disable budget enforcement.
        bound = c.allowed_tokens
        takes_budget = _method_takes_budget(c, bound, "_sutro_takes_budget")
        m = bound(remaining=remaining) if takes_budget else bound()
        return self._pad_mask(m)

    def _fsm_masks(self, rows) -> np.ndarray:
        """[B, V] bool — each listed slot's FSM mask (all-True for
        unconstrained slots). Single assembly path for BOTH the masked
        single-step and the speculative window's allowed0 recovery, so
        the two cannot drift."""
        allowed = np.ones((self.B, self.vocab), bool)
        for i in list(rows):
            s = self.slots[i]
            if s is None:
                continue  # failed earlier in this assembly pass
            c = s.req.constraint
            if c is not None:
                rem = self._remaining(s.req, len(s.out_ids), s.pos)
                try:
                    allowed[i] = self._constraint_mask(c, rem)
                except Exception as e:  # noqa: BLE001 — row isolation
                    # one row's broken FSM must not take the batch down:
                    # release it into the retry/quarantine path; its
                    # all-True mask row samples a token that the (slot,
                    # gen) / None-slot checks then discard
                    self._fail_slot(i, e)
        return allowed

    def _remaining(self, req: GenRequest, emitted: int, pos: int) -> int:
        """Tokens of generation budget left: request cap and context room."""
        return max(
            min(
                req.max_new_tokens - emitted,
                self.ecfg.max_context() - pos - 1,
            ),
            0,
        )

    def _sample_batch(
        self,
        logits: np.ndarray,
        reqs: List[GenRequest],
        slot_idxs: List[int],
    ) -> Tuple[np.ndarray, np.ndarray]:
        """First-token sampling for ``len(reqs)`` fresh rows in one
        device call. ``logits`` is [n, V]."""
        n = len(reqs)
        temps = np.array([r.temperature for r in reqs], np.float32)
        top_p = np.array([r.top_p for r in reqs], np.float32)
        top_k = np.array([r.top_k for r in reqs], np.int32)
        allowed = None
        if any(r.constraint is not None for r in reqs):
            allowed = np.ones((n, self.vocab), bool)
            for i, r in enumerate(reqs):
                if r.constraint is not None:
                    rem = self._remaining(r, 0, len(r.prompt_ids))
                    allowed[i] = self._constraint_mask(r.constraint, rem)
        row_seeds = None
        if any(r.row_seed is not None for r in reqs):
            sub = self._fixed_key  # per-row keys derive from row_seed
            # unseeded rows in a mixed batch key off their SLOT index
            # (unique across same-step admit batches) under a salt
            # distinct from the decode loop's, so no two draws alias
            row_seeds = jax.numpy.asarray(
                [
                    _step_seed(r.row_seed, 0)
                    if r.row_seed is not None
                    else _step_seed(0x0F1E57 ^ (slot_idxs[i] + 1),
                                    self._step)
                    for i, r in enumerate(reqs)
                ],
                dtype=jax.numpy.int32,
            )
        else:
            self._key, sub = jax.random.split(self._key)
        # bucket the group size so _admit_sample_jit compiles once per
        # bucket, not once per distinct admission-group size (profiled
        # round 5: each new size cost a ~1 s XLA:CPU recompile)
        # min(): next_bucket can overshoot hi when B isn't a power of
        # two (doubles past hi before the guard re-checks)
        nb = min(next_bucket(n, lo=1, hi=self.B), self.B)
        if nb > n:
            pad = nb - n
            logits = np.concatenate(
                [logits, np.zeros((pad, logits.shape[1]), logits.dtype)]
            )
            temps = np.concatenate([temps, np.zeros((pad,), np.float32)])
            top_p = np.concatenate([top_p, np.ones((pad,), np.float32)])
            top_k = np.concatenate([top_k, np.zeros((pad,), np.int32)])
            if allowed is not None:
                allowed = np.concatenate(
                    [allowed, np.ones((pad, self.vocab), bool)]
                )
            if row_seeds is not None:
                row_seeds = jax.numpy.concatenate(
                    [
                        row_seeds,
                        jax.numpy.zeros((pad,), jax.numpy.int32),
                    ]
                )
        with self.timer.time("admit_sample"):
            jl = jax.numpy.asarray(logits)
            tok, logp = _admit_sample_jit(
                jl,
                sub,
                temps,
                top_p,
                top_k,
                None if allowed is None else jax.numpy.asarray(allowed),
                row_seeds,
            )
            out = np.asarray(tok[:n]), np.asarray(logp[:n])
        return out

    def _deliver_token(self, slot: _Slot, tok: int, logp: float) -> None:
        """Fan one committed token out to the slot's job ``on_token``
        hook (the interactive streaming channel). Every commit path
        must call this — ``_accept_token``, the two prefill-sampled
        first-token sites, and the vectorized window accept."""
        j = slot.job
        if j is None or j.on_token is None:
            return
        try:
            j.on_token(slot.req.row_id, tok, float(logp))
        except Exception:  # noqa: BLE001 — a broken stream channel
            # must not kill the decode loop; the request's
            # should_cancel path tears it down
            logger.warning(
                "on_token hook failed for %s", j.job_id, exc_info=True
            )

    def _record_token(self, slot: _Slot, tok: int, logp: float) -> None:
        slot.out_ids.append(tok)
        if slot.hist is not None:  # n-gram draft history (incremental)
            self._hist_push(slot, tok)
        slot.logprob_sum += float(logp)
        if slot.req.constraint is not None and tok not in self.stop_ids:
            slot.req.constraint.advance(tok)
        if slot.req.has_penalties() and tok not in self.stop_ids:
            slot.counts[tok] = slot.counts.get(tok, 0) + 1
            if slot.seen_bits is not None and 0 <= tok < self.vocab:
                slot.seen_bits[tok // 8] |= 0x80 >> (tok % 8)
        seqs = slot.req.stop_seqs
        if seqs and self.token_bytes is not None and not slot.hit_stop_seq:
            # match against the FULL tail+token first (a long token must
            # not push a boundary-spanning match out of the window),
            # then keep only what the next boundary match could need
            longest = slot.stop_longest
            if not longest:
                longest = slot.stop_longest = max(len(s) for s in seqs)
            grown = slot.tail + self.token_bytes(tok)
            for s in seqs:
                if s in grown:
                    slot.hit_stop_seq = True
                    break
            slot.tail = grown[-(longest - 1):] if longest > 1 else b""

    def _finish_reason(self, slot: _Slot, tok: int) -> Optional[str]:
        c = slot.req.constraint
        if slot.hit_stop_seq:
            return "stop"
        if tok in self.stop_ids:
            return "stop"
        if c is not None and c.is_complete():
            return "schema_complete"
        if len(slot.out_ids) >= slot.req.max_new_tokens:
            return "length"
        if slot.pos + 1 >= self._max_ctx:
            return "length"
        return None

    def _row_error(
        self, ctx: JobCtx, req: GenRequest, exc: BaseException
    ) -> None:
        """Row-level failure domain (one bad row must not kill the
        job): retry the row as a FRESH request up to ``ctx.row_retries``
        times — only when its constraint can be rebuilt (a directly
        supplied FSM has advanced and cannot be rewound) — then
        quarantine it as an error result the jobstore records in the
        ``error`` column. Every decision streams a failure_log event."""
        rid = req.row_id
        attempt = ctx.row_attempts.get(rid, 0) + 1
        ctx.row_attempts[rid] = attempt
        msg = f"{type(exc).__name__}: {exc}"
        rebuildable = (
            req.constraint is None or req.constraint_factory is not None
        )
        if attempt <= ctx.row_retries and rebuildable:
            logger.warning(
                "row %d failed (attempt %d/%d), retrying: %s",
                rid, attempt, ctx.row_retries, msg,
            )
            if ctx.on_row_event is not None:
                ctx.on_row_event(
                    {"event": "row_retry", "row_id": rid,
                     "attempt": attempt, "error": msg}
                )
            # fresh request: FSM state, prep handoff, and flags reset —
            # appended at the TAIL, which admission pops next
            ctx.pending.append(
                dataclasses.replace(
                    req,
                    constraint=None,
                    prepped_constraint=None,
                    prep_queued=False,
                )
            )
            return
        logger.warning(
            "row %d quarantined after %d attempt(s): %s", rid, attempt, msg
        )
        if ctx.on_row_event is not None:
            ctx.on_row_event(
                {"event": "row_quarantined", "row_id": rid,
                 "attempt": attempt, "error": msg}
            )
        ctx.stats["rows"] += 1
        ctx.on_result(
            GenResult(
                row_id=rid,
                token_ids=[],
                cumulative_logprob=0.0,
                finish_reason="error",
                input_tokens=len(req.prompt_ids),
                error=msg,
            )
        )

    def _fail_slot(self, i: int, exc: BaseException) -> None:
        """Release slot ``i`` after a per-row exception WITHOUT emitting
        its partial output, then route the row through
        :meth:`_row_error` (retry or quarantine). Mirrors ``_release``'s
        bookkeeping; the in-flight-window dead-store argument documented
        there covers the pages freed here too."""
        slot = self.slots[i]
        if self.native is not None:
            self.native.release(i)
        else:
            self.allocator.free(slot.pages[slot.shared_n :])
        ctx = slot.job
        if ctx is not None:
            ctx.n_slots -= 1
        self.slots[i] = None
        self._gen[i] += 1
        self._needs_mask.discard(i)
        if ctx is not None:
            self._row_error(ctx, slot.req, exc)

    def _accept_token(
        self, i: int, tok: int, logp: float, release: bool = True
    ) -> int:
        """Record one sampled token for slot ``i``; release on finish.
        Returns 1 if the row completed, 2 if the row FAILED (slot
        released into the retry/quarantine path — the token was NOT
        recorded), else 0. ``release=False`` defers the release to the
        caller (speculative windows must commit the accepted K/V to
        pages BEFORE freeing them). Results and token accounting route
        through the SLOT'S job (co-batched sessions interleave jobs
        within one decode batch)."""
        s = self.slots[i]
        try:
            if faults.ACTIVE is not None:
                faults.inject(
                    "row.decode", row=s.req.row_id,
                    job=s.job.job_id if s.job is not None else None,
                )
            s.pos += 1  # last_token's KV is now cached
            if self.native is not None:
                self.native.note_token(i, tok)
            self._record_token(s, tok, logp)
        except Exception as e:  # noqa: BLE001 — row isolation boundary
            self._fail_slot(i, e)
            return 2
        s.last_token = tok
        if s.job is not None:
            s.job.stats["out"] += 1
        self._deliver_token(s, tok, float(logp))
        try:
            done = self._finish_reason(s, tok)
        except Exception as e:  # noqa: BLE001 — row isolation (FSM state)
            self._fail_slot(i, e)
            return 2
        if done:
            if release:
                self._emit(i)
            return 1
        return 0

    def _emit(self, i: int, reason: Optional[str] = None) -> None:
        """Release slot ``i`` and stream its result through its job."""
        ctx = self.slots[i].job
        res = self._release(i)
        if reason is not None:
            res.finish_reason = reason
        if ctx is not None:
            ctx.stats["rows"] += 1
            ctx.on_result(res)

    def _token_ok(
        self, c: TokenConstraint, tok: int, remaining: int
    ) -> bool:
        """Single-token FSM validity, used to verify speculative window
        tokens. Prefers the optional O(1) ``token_allowed`` fast path
        when the constraint offers one (signature-probed like
        ``allowed_tokens``, so implementations without a ``remaining``
        parameter still work); otherwise falls back to the full
        (padded) mask."""
        fn = getattr(c, "token_allowed", None)
        if fn is not None:
            if _method_takes_budget(c, fn, "_sutro_tok_takes_budget"):
                return bool(fn(tok, remaining=remaining))
            return bool(fn(tok))
        return bool(self._constraint_mask(c, remaining)[tok])

    def _checkpoint_slot(self, slot: _Slot) -> Optional[set]:
        """Session KV checkpoint (``JobCtx.kv_checkpoint``): transfer
        the finished row's page-aligned OWN pages into the radix prefix
        store keyed by its full (prompt + emitted) token sequence, so
        the session's next turn — whose prompt extends this sequence —
        admits by prefix hit instead of re-prefilling the whole
        conversation. Once store-owned the pages age like any other
        leaves: under pressure they demote down the tiers rather than
        being dropped. Returns the set of page ids now store-owned (the
        caller must keep them out of the allocator), or None."""
        store = self._prefix_store
        PS = self.ecfg.kv_page_size
        try:
            full = np.concatenate(
                [
                    np.asarray(slot.req.prompt_ids, np.int32),
                    np.asarray(slot.out_ids, np.int32),
                ]
            )
            # positions [0, pos) hold KV for full[:pos] — the last
            # sampled token's KV was never written
            aligned = min(slot.pos, len(full)) // PS
            if aligned <= slot.shared_n:
                return None  # nothing beyond the shared head to keep
            handle = store.lookup_pin(full[: aligned * PS])
            try:
                d = len(handle.nodes)
                if d < slot.shared_n or d >= aligned:
                    # the store path stops inside the job-owned prefix
                    # head (pages we cannot transfer) or already covers
                    # everything this row could contribute
                    return None
                pages = [int(p) for p in slot.pages[d:aligned]]
                if not store.extend(
                    handle, full[d * PS : aligned * PS], pages
                ):
                    return None
                if self._tel_on and slot.job.trace_id is not None:
                    telemetry.TRACES.event(
                        slot.job.trace_id, "kv_checkpoint",
                        {"row_id": int(slot.req.row_id),
                         "pages": len(pages)},
                    )
                return set(pages)
            finally:
                store.release(handle)
        except Exception:  # noqa: BLE001 — a checkpoint is an
            # optimization; on any failure the pages free normally and
            # the next turn re-prefills (the pre-tier behavior)
            logger.warning("kv checkpoint failed", exc_info=True)
            return None

    def _release(self, i: int) -> GenResult:
        """Free slot ``i``'s pages and emit its result.

        ORDERING DEPENDENCY: a release can happen while pipelined
        windows referencing this slot are still in flight; those stale
        windows keep writing KV into the freed pages even though the
        (slot, gen) check discards their *tokens*. If the pages are
        reallocated to a newly admitted row, correctness rests on
        per-device in-order execution of dispatched programs: the new
        row's prefill + decode steps are dispatched AFTER the stale
        window and rewrite every KV position they will ever read, so the
        stale writes are dead stores. JAX/TPU executes one program at a
        time per device, which guarantees this today; a multi-stream or
        relaxed-ordering backend would need frees deferred until every
        pipe entry referencing the slot has drained (see
        ``_pipe_capacity_ok`` for the companion invariant)."""
        slot = self.slots[i]
        assert slot is not None
        kept = None
        if (
            slot.job is not None
            and slot.job.kv_checkpoint
            and self._kv_tier is not None
            and self._prefix_store is not None
            and not slot.prefilling
        ):
            kept = self._checkpoint_slot(slot)
        if self.native is not None:
            self.native.release(i)
            if kept and not self.native.reserve_pages(
                sorted(kept)
            ):  # pragma: no cover — release just freed exactly these
                # ids; a failure would mean the store and the allocator
                # both think they own them, so drop the store wholesale
                logger.warning(
                    "kv checkpoint re-reserve failed; resetting store"
                )
                self._prefix_store.reset()
        else:
            # shared-prefix pages at the table head belong to the JOB
            # (freed once at end of run), not this slot; checkpointed
            # pages now belong to the prefix store
            own = slot.pages[slot.shared_n :]
            self.allocator.free(
                [p for p in own if int(p) not in kept] if kept else own
            )
        if slot.job is not None:
            slot.job.n_slots -= 1
        self.slots[i] = None
        self._gen[i] += 1
        self._needs_mask.discard(i)  # flag must not leak to a new occupant
        out = list(slot.out_ids)
        reason = "stop"
        if out and out[-1] in self.stop_ids:
            out = out[:-1]
            reason = "stop"
        elif slot.hit_stop_seq:
            reason = "stop"
        elif slot.req.constraint is not None and slot.req.constraint.is_complete():
            reason = "schema_complete"
        else:
            reason = "length"
        return GenResult(
            row_id=slot.req.row_id,
            token_ids=out,
            cumulative_logprob=slot.logprob_sum,
            finish_reason=reason,
            input_tokens=len(slot.req.prompt_ids),
        )

    # ------------------------------------------------------------------
    # pipelined fused windows (unconstrained decode fast path)
    # ------------------------------------------------------------------

    def _pipe_projection(self, pipe) -> np.ndarray:
        """[B] extra decode steps already dispatched (in-flight windows)
        but not yet processed, per slot — only windows whose (slot, gen)
        snapshot still matches count."""
        proj = np.zeros((self.B,), np.int32)
        for _, _, w_active, w_gens, wK in pipe:
            for idx, i in enumerate(w_active):
                if self._gen[i] == w_gens[idx]:
                    proj[i] += wK
        return proj

    def _pipe_capacity_ok(
        self, active, proj: np.ndarray, K: int
    ) -> bool:
        """True when every active row's up-front page reservation covers
        ``K`` more steps BEYOND everything already in flight — the
        invariant that makes speculative window writes always land in
        the row's own reserved pages.

        Caveat: this invariant covers LIVE slots only. A slot released
        mid-pipeline leaves stale in-flight windows writing into freed
        pages; that case is safe only via the dispatch-order argument
        documented on ``_release``."""
        if not active:
            return False
        PS = self.ecfg.kv_page_size
        for i in active:
            s = self.slots[i]
            if len(s.pages) * PS - s.pos - int(proj[i]) < K:
                return False
        return True

    def _dispatch_pipelined(
        self, pipe, active, last, past, table, temp, top_p, top_k,
        K: int,
    ) -> None:
        """Dispatch one fused window WITHOUT waiting for in-flight ones.

        ``past`` must already include the in-flight projection. The last
        tokens chain from the previous window's device-resident sample
        row; slots admitted (or re-admitted) since that dispatch take
        their host-known token via a device-side merge — no host sync
        anywhere on this path."""
        if pipe:
            prev_toks, _, p_active, p_gens, _ = pipe[-1]
            chained = {
                i
                for idx, i in enumerate(p_active)
                if p_gens[idx] == self._gen[i]
            }
            if all(i in chained for i in active):
                # steady state: every active row chains from the previous
                # window — skip the merge program entirely (tokens at
                # non-active slots are garbage either way)
                last_arg = prev_toks[-1]
            else:
                refresh = np.ones((self.B,), bool)
                for i in chained:
                    refresh[i] = False
                last_arg = self.runner.merge_last(
                    prev_toks[-1], refresh, np.asarray(last, np.int32)
                )
        else:
            last_arg = last
        self._key, sub = jax.random.split(self._key)
        with self.timer.time("decode"):
            toks_dev, logps_dev = self.runner.decode_multi_async(
                last_arg, past, table, sub, temp, top_p, K, top_k=top_k,
                pfx=self._split_pfx(active),
            )
        self._step += K
        pipe.append(
            (
                toks_dev,
                logps_dev,
                list(active),
                [self._gen[i] for i in active],
                K,
            )
        )

    def _process_pipelined(self, entry) -> None:
        """Fetch one in-flight window's results (the only host sync in
        the pipelined path) and accept its tokens. Tokens for slots
        whose generation changed since dispatch (released, possibly
        re-admitted) are discarded. Accounting and results stream
        through each slot's job (_accept_token).

        PLAIN rows — no constraint, no penalties, no stop sequences, no
        n-gram draft history — take a vectorized window-acceptance path
        (round-5 host-overhead profile: the per-token Python loop cost
        ~26 ms per B=128 window, 2× the device window itself); rows with
        any per-token machinery keep the exact per-token loop."""
        toks_dev, logps_dev, w_active, w_gens, wK = entry
        with self.timer.time("decode"):
            toks = np.asarray(toks_dev)
            logps = np.asarray(logps_dev)
        t_acc = time.monotonic() if self._tel_on else 0.0
        plain: List[int] = []
        rest: List[int] = []
        for idx, i in enumerate(w_active):
            if self._gen[i] != w_gens[idx] or self.slots[i] is None:
                continue
            s = self.slots[i]
            r = s.req
            if (
                r.constraint is None
                and s.hist is None
                and not r.stop_seqs
                and not r.has_penalties()
            ):
                plain.append(i)
            else:
                rest.append(i)
        if plain:
            self._accept_plain_window(plain, toks, logps, wK)
        for j in range(wK):
            for i in rest:
                if self.slots[i] is None:
                    continue  # finished earlier in this window
                self._accept_token(
                    i, int(toks[j][i]), float(logps[j][i])
                )
        if self._tel_on:
            self._tel_accept(t_acc)

    def _tel_accept(self, t0: float) -> None:
        """Record the host-side token-acceptance leg of one window as
        an ``accept`` span (the decode span covers only the device
        dispatch/fetch)."""
        dt = time.monotonic() - t0
        telemetry.stage_observe(
            "accept", dt,
            exemplar=self._tel_traces[0] if self._tel_traces else None,
        )
        telemetry.RECORDER.record(
            "accept", None, t0, dt,
            {"jobs": self._tel_jobs} if self._tel_jobs else None,
        )
        for tid in self._tel_traces:
            telemetry.TRACES.add(tid, "accept", t0, dt)

    def _trace_resume(self, ctx: JobCtx, req: GenRequest) -> None:
        """Close a preempt_suspend pair: the row a preemption suspended
        is re-entering the batch (telemetry on, checked by caller)."""
        rid = int(req.row_id)
        if rid in ctx.trace_preempted:
            ctx.trace_preempted.discard(rid)
            if ctx.trace_id is not None:
                telemetry.TRACES.event(
                    ctx.trace_id, "resume", {"row_id": rid}
                )

    def _accept_plain_window(
        self, idxs: List[int], toks: np.ndarray, logps: np.ndarray,
        wK: int,
    ) -> None:
        """Accept a whole window for plain rows with one numpy pass per
        row instead of wK interpreter iterations. Semantics mirror
        _accept_token/_finish_reason exactly: tokens are taken up to and
        including the first trigger among stop-id ("stop"),
        max_new_tokens ("length"), and context limit ("length") — at
        the same position the stop-id check wins, as in the per-token
        order."""
        ii = np.asarray(idxs, np.int64)
        tw = toks[:, ii]                             # [K, n]
        lw = logps[:, ii].astype(np.float64)         # [K, n]
        is_stop = (
            np.isin(tw, self._stop_arr)
            if self._stop_arr.size
            else np.zeros_like(tw, bool)
        )
        INF = wK + 1
        for col, i in enumerate(idxs):
            s = self.slots[i]
            if faults.ACTIVE is not None:
                # the vectorized path skips _accept_token, so the
                # per-row decode fault site fires here instead
                try:
                    faults.inject(
                        "row.decode", row=s.req.row_id,
                        job=s.job.job_id if s.job is not None else None,
                    )
                except Exception as e:  # noqa: BLE001 — row isolation
                    self._fail_slot(i, e)
                    continue
            # first k (tokens accepted) at which the row finishes —
            # mirrors _finish_reason's per-token checks
            stops = np.flatnonzero(is_stop[:, col])
            n_stop = int(stops[0]) + 1 if stops.size else INF
            n_len = s.req.max_new_tokens - len(s.out_ids)
            n_ctx = self._max_ctx - 1 - s.pos
            limit = min(n_stop, n_len, n_ctx)
            if limit <= 0:
                # budget already exhausted at window start (the row
                # should have been emitted earlier; a stale window can
                # still land here): finish NOW with zero tokens taken —
                # the old max(..., 1) silently accepted one token past
                # the cap
                self._emit(i)
                continue
            n_take = min(limit, wK)
            col_t = tw[:n_take, col]
            s.out_ids.extend(col_t.tolist())  # C-speed, yields ints
            s.logprob_sum += float(lw[:n_take, col].sum())
            s.pos += n_take
            s.last_token = int(col_t[-1])
            if self.native is not None:
                self.native.note_bulk(i, s.last_token, n_take)
            if s.job is not None:
                s.job.stats["out"] += n_take
                if s.job.on_token is not None:
                    lcol = lw[:n_take, col]
                    for k in range(n_take):
                        self._deliver_token(
                            s, int(col_t[k]), float(lcol[k])
                        )
            if limit <= wK:
                self._emit(i)

    # ------------------------------------------------------------------

    def run(
        self,
        requests: List[GenRequest],
        *,
        on_result: Callable[[GenResult], None],
        on_progress: Optional[Callable[[Dict[str, Any]], None]] = None,
        should_cancel: Optional[Callable[[], bool]] = None,
        should_yield: Optional[Callable[[], bool]] = None,
        progress_every: float = 1.0,
        row_retries: int = 0,
        on_row_event: Optional[Callable[[Dict[str, Any]], None]] = None,
        job_id: str = "_single",
    ) -> str:
        """Run all requests to completion, streaming results/progress.

        Returns ``"completed"``, ``"cancelled"``, or ``"yielded"``.
        ``should_yield`` is polled between decode steps (same cadence as
        ``should_cancel``): on True the batcher drops its in-flight
        slots WITHOUT emitting results (those rows regenerate when the
        caller re-runs the job; completed rows were already emitted) and
        returns immediately — the preemption primitive behind priority
        scheduling (reference two-priority semantics, README.md:168-171).

        ``row_retries``/``on_row_event`` configure the row-level failure
        domain (see JobCtx) — DP shards get the same retry/quarantine
        semantics as co-batched sessions.

        ``job_id`` tags this run's telemetry spans (dp shard runs pass
        their engine job id so the flight-recorder timeline is
        attributable; the default keeps ad-hoc callers anonymous).

        Single-job convenience over :meth:`run_multi`."""
        outcome: Dict[str, str] = {}
        ctx = JobCtx(
            job_id=job_id,
            pending=list(requests),
            on_result=on_result,
            on_progress=on_progress,
            should_cancel=should_cancel,
            progress_every=progress_every,
            row_retries=row_retries,
            on_row_event=on_row_event,
        )
        state = self.run_multi(
            [ctx],
            on_job_done=lambda c, o: outcome.__setitem__("v", o),
            should_yield=should_yield,
        )
        if state == "yielded":
            return "yielded"
        return outcome.get("v", "completed")

    def _start_job(self, ctx: JobCtx) -> None:
        """Prepare a job for the session: truncation policy pass, the
        shortest-first admission order, and the job's shared-prefix
        prefill."""
        pending = []
        # lazy-constraint jobs share one factory: probe its room ONCE
        # per job instead of instantiating an FSM per row here
        factory_room: Dict[int, int] = {}
        for req in ctx.pending:
            # truncation must leave enough generation room to honor the
            # row's schema: a prompt that fills the context would leave
            # a constrained row 1 token ("{") and silently break the
            # guaranteed-JSON contract. Plain rows keep >=1 token.
            need = 1
            if req.constraint is not None:
                from .constrain.fsm import constraint_room

                need = constraint_room(req.constraint)
            elif req.constraint_factory is not None:
                from .constrain.fsm import constraint_room

                key = id(req.constraint_factory)
                if key not in factory_room:
                    try:
                        factory_room[key] = constraint_room(
                            req.constraint_factory()
                        )
                    except Exception:  # noqa: BLE001 — row isolation
                        # a failing factory surfaces PER ROW at
                        # materialization (retry/quarantine); the probe
                        # only loses the schema-room truncation reserve
                        logger.warning(
                            "constraint probe failed at job start; "
                            "surfacing per-row at admission",
                            exc_info=True,
                        )
                        factory_room[key] = 1
                need = factory_room[key]
            max_prompt = self.ecfg.max_context() - need
            if len(req.prompt_ids) > max_prompt:
                if req.allow_truncate and max_prompt > 0:
                    req = dataclasses.replace(
                        req, prompt_ids=req.prompt_ids[:max_prompt]
                    )
                else:
                    # schema minimum cannot fit the context at all —
                    # an explicit per-row error beats invalid JSON
                    msg = (
                        f"prompt of {len(req.prompt_ids)} tokens leaves "
                        f"no room for generation (max context "
                        f"{self.ecfg.max_context()}, reserve {need}) "
                        "and truncate_rows is off"
                    )
                    if ctx.on_row_event is not None:
                        ctx.on_row_event(
                            {"event": "row_quarantined",
                             "row_id": req.row_id, "attempt": 0,
                             "error": msg}
                        )
                    ctx.stats["rows"] += 1
                    ctx.on_result(
                        GenResult(
                            row_id=req.row_id,
                            token_ids=[],
                            cumulative_logprob=0.0,
                            finish_reason="error_too_long",
                            input_tokens=len(req.prompt_ids),
                            error=msg,
                        )
                    )
                    continue
            pending.append(req)
        # pop() serves the SHORTEST prompts first: batched prefill pads
        # every row in a dispatch to the group's bucket, so grouping
        # similar lengths cuts padding FLOPs on mixed-length jobs (and
        # quick rows finish early for progress). Results are keyed by
        # row_id — output order is unaffected (reference 1:1 contract).
        pending.sort(key=lambda r: len(r.prompt_ids), reverse=True)
        ctx.pending = pending
        # shared-prefix setup is LAZY (_admit_pending): a job attached
        # behind a full batch must not pin prefix pages while it waits
        ctx.started = ctx.t_last = time.monotonic()
        if self._tel_on:
            if ctx.trace_id is None:
                # batch jobs get a per-job trace at adoption (the
                # gateway already assigned one to interactive requests)
                ctx.trace_id = f"tr-{ctx.job_id}"
                telemetry.TRACES.start_trace(
                    ctx.trace_id, "batch",
                    {"job_id": ctx.job_id, "rows": len(ctx.pending)},
                )
            if ctx.trace_enq_mono:
                # admission queue wait: from submit/park to session
                # adoption — the leg the queue_wait_bound verdict grades
                telemetry.TRACES.add(
                    ctx.trace_id, "queue_wait", ctx.trace_enq_mono,
                    ctx.started - ctx.trace_enq_mono,
                )

    def _job_progress(self, ctx: JobCtx, force: bool = False) -> None:
        if ctx.on_progress is None:
            return
        now = time.monotonic()
        if not force and now - ctx.t_last < ctx.progress_every:
            return
        ctx.t_last = now
        elapsed = max(now - ctx.started, 1e-9)
        payload = {
            "rows_completed": ctx.stats["rows"],
            "input_tokens": ctx.stats["in"],
            "output_tokens": ctx.stats["out"],
            "total_tokens_processed_per_second": (
                (ctx.stats["in"] + ctx.stats["out"]) / elapsed
            ),
        }
        if ctx.stats.get("spec_drafted"):
            payload["spec_drafted"] = ctx.stats["spec_drafted"]
            payload["spec_accepted"] = ctx.stats.get("spec_accepted", 0)
        ctx.on_progress(payload)

    def _finish_job(
        self, ctx: JobCtx, outcome: str, on_job_done,
        emit_cancel: bool = False,
    ) -> None:
        """Terminal transition for one job of the session. With
        ``emit_cancel`` the job's live slots are released as
        ``cancelled`` results and its pending rows dropped (the
        jobstore layer records never-run rows)."""
        if emit_cancel:
            for i, s in enumerate(self.slots):
                if s is not None and s.job is ctx:
                    self._emit(i, reason="cancelled")
            ctx.pending.clear()
        if ctx.prefix is not None:
            self._release_prefix(ctx.prefix)
            ctx.prefix = None
        if self._hibernated:
            self._purge_hibernated(ctx)
        ctx.done = True
        if self.ladder is not None:
            self.ladder.forget(ctx)  # drop the aging-clock entry
        self._job_progress(ctx, force=True)
        on_job_done(ctx, outcome)

    def _suspend_job(self, ctx: JobCtx) -> None:
        """Yield path: drop the job's live slots WITHOUT emitting
        results (those rows regenerate on resume; completed rows were
        already streamed) and return its shared-prefix pages."""
        for i, s in enumerate(self.slots):
            if s is not None and s.job is ctx:
                self._unreserve(i, s.pages[s.shared_n :])
                if s.job is not None:
                    s.job.n_slots -= 1
                self.slots[i] = None
                self._gen[i] += 1
        if ctx.prefix is not None:
            self._release_prefix(ctx.prefix)
            ctx.prefix = None
        if self._hibernated:
            # the session layer rebuilds pending on resume; a stale
            # hibernation entry must not shadow those fresh requests
            self._purge_hibernated(ctx)
        ctx.prefix_ready = False  # a resumed ctx re-detects its prefix

    def _sweep_done(self, live: List[JobCtx], on_job_done) -> None:
        for ctx in live:
            if not ctx.done and not ctx.pending and ctx.n_slots == 0:
                if ctx.hold_open is not None and ctx.hold_open():
                    # stage-graph downstream ctx: drained for NOW, but
                    # upstream feeders are still producing rows
                    continue
                self._finish_job(ctx, "completed", on_job_done)

    def _interactive_slots_used(self) -> int:
        return sum(
            1
            for s in self.slots
            if s is not None and s.job is not None and s.job.interactive
        )

    def _hibernate_slot(self, i: int) -> bool:
        """Suspend slot ``i`` by demoting its own KV — INCLUDING the
        partial tail page (``ceil(pos/PS)`` own pages) — into the
        tiered pool instead of discarding it, so the preempted row
        resumes by pure page-upload with zero re-prefilled tokens.
        (Positions >= pos inside the tail page are garbage, but
        attention masks to pos and the resumed decode overwrites them
        in place through the page table.) The demote is SYNCHRONOUS
        and pinned: the device pages free only after the pool owns the
        payload, so a torn demotion (fault site ``kvtier.demote``)
        degrades to the caller's plain regenerate suspend — never a
        corrupt row. Returns True when the slot was hibernated and its
        ORIGINAL request (live constraint and all) re-queued."""
        if not self._can_hibernate:
            return False
        s = self.slots[i]
        if s is None or s.prefilling or s.job is None:
            return False
        ctx = s.job
        PS = self.ecfg.kv_page_size
        end = -(-s.pos // PS)  # ceil: the partial tail page rides along
        own_aligned = [
            int(p) for p in s.pages[s.shared_n : max(s.shared_n, end)]
        ]
        key = b""
        if own_aligned:
            self._hib_seq += 1
            key = b"hib:%d:%d:%d" % (
                id(ctx), int(s.req.row_id), self._hib_seq,
            )
            try:
                with self.timer.time("kv_demote"):
                    raw = self.runner.read_pages(own_aligned)
                    self._kv_tier.put_row(key, raw)
                self.tier_demotes += len(own_aligned)
            except Exception:  # noqa: BLE001 — HBM copy stays
                # authoritative: fall back to the plain suspend
                logger.warning(
                    "hibernation demote failed; row %d regenerates",
                    s.req.row_id, exc_info=True,
                )
                return False
        self._hibernated[(id(ctx), int(s.req.row_id))] = _Hib(
            key=key,
            pos=s.pos,
            last_token=s.last_token,
            out_ids=list(s.out_ids),
            logprob_sum=s.logprob_sum,
            tail=s.tail,
            hit_stop_seq=s.hit_stop_seq,
            stop_longest=s.stop_longest,
            counts=dict(s.counts),
            seen_bits=s.seen_bits,
            shared_tokens=s.shared_n * PS,
            n_pages=len(own_aligned),
        )
        self._unreserve(i, s.pages[s.shared_n :])
        ctx.n_slots -= 1
        self.slots[i] = None
        self._gen[i] += 1
        self._needs_mask.discard(i)
        # the ORIGINAL request re-queues — its live constraint object
        # continues in place at resume (the stripped retry-style copy
        # is built only if the tier loses the payload)
        ctx.pending.insert(0, s.req)
        return True

    def _resume_hibernated(
        self, req: GenRequest, ctx: JobCtx, r, hib: _Hib
    ) -> Optional[GenRequest]:
        """Re-admit a hibernated row into reservation ``r``: upload its
        tier payload into the fresh pages and arm the slot exactly
        where it stopped — a pure upload, since hibernation captures
        the partial tail page (the legacy sub-page re-prefill branch
        survives only for aligned-capture entries, and is refused under
        sp/pp where suffix prefill is unsupported). Returns None on
        success (the slot is live); on a tier miss — torn demotion,
        host-LRU drop without a disk tier, or a shared-prefix coverage
        change across a session suspend — returns a FRESH request for
        the caller to admit through the normal path (the pre-tier
        full-regenerate behavior)."""
        slot_idx, own_pages, table = r
        PS = self.ecfg.kv_page_size
        shared = ctx.prefix.tokens if ctx.prefix is not None else 0
        payload = None
        ok = shared == hib.shared_tokens
        if ok and hib.n_pages:
            payload = self._kv_tier.take_row(hib.key)
            ok = (
                payload is not None
                and int(payload["k"].shape[1]) == hib.n_pages
            )
        start = shared + hib.n_pages * PS
        if ok and hib.pos > start and (
            getattr(self.runner, "sp", 1) != 1
            or getattr(self.runner, "pp", 1) != 1
        ):
            # aligned-capture entry on a sharded runner: the sub-page
            # tail would need prefill(start>0), which sp/pp forbids —
            # treat as a miss and regenerate rather than assert
            ok = False
        if ok:
            try:
                with self.timer.time("kv_promote"):
                    if payload is not None:
                        self.runner.write_pages(
                            [int(p) for p in own_pages[: hib.n_pages]],
                            payload,
                        )
                    if hib.pos > start:
                        full = np.concatenate(
                            [
                                np.asarray(req.prompt_ids, np.int32),
                                np.asarray(hib.out_ids, np.int32),
                            ]
                        )
                        # the truly novel tail: KV for the sub-page
                        # positions the aligned payload cannot carry
                        self.runner.prefill(
                            full[start : hib.pos],
                            np.asarray(table, np.int32),
                            start=start,
                        )
            except Exception:  # noqa: BLE001 — the reservation stays;
                # normal admission below overwrites every position
                logger.warning(
                    "hibernation resume failed; row %d regenerates",
                    req.row_id, exc_info=True,
                )
                ok = False
        if not ok:
            ctx.stats["resumes_reprefill"] = (
                ctx.stats.get("resumes_reprefill", 0) + 1
            )
            if self._tel_on:
                telemetry.KV_RESUMES_TOTAL.inc(1.0, "reprefill")
            # victim selection guaranteed the constraint is rebuildable
            return dataclasses.replace(
                req,
                constraint=None,
                prepped_constraint=None,
                prep_queued=False,
            )
        pfx = ctx.prefix
        slot = _Slot(
            req=req,
            pages=(
                (list(pfx.pages) + list(own_pages))
                if pfx is not None
                else list(own_pages)
            ),
            pos=hib.pos,
            last_token=hib.last_token,
            job=ctx,
            shared_n=pfx.n_pages if pfx is not None else 0,
            out_ids=list(hib.out_ids),
            logprob_sum=hib.logprob_sum,
            tail=hib.tail,
            hit_stop_seq=hib.hit_stop_seq,
            stop_longest=hib.stop_longest,
            counts=dict(hib.counts),
            seen_bits=hib.seen_bits,
        )
        self.slots[slot_idx] = slot
        ctx.n_slots += 1
        if self.native is not None:
            self.native.arm_slot(
                slot_idx, hib.pos, hib.last_token,
                req.temperature, req.top_p, req.top_k,
            )
        self.tier_promotes += hib.n_pages
        ctx.stats["resumes_upload"] = (
            ctx.stats.get("resumes_upload", 0) + 1
        )
        if self._tel_on:
            telemetry.KV_RESUMES_TOTAL.inc(1.0, "upload")
            if ctx.trace_id is not None:
                telemetry.TRACES.event(
                    ctx.trace_id, "hibernate_resume",
                    {"row_id": int(req.row_id),
                     "pages": int(hib.n_pages),
                     "reprefilled_tokens": max(0, int(hib.pos - start))},
                )
        return None

    def _purge_hibernated(self, ctx: JobCtx) -> None:
        """Drop every hibernated entry of ``ctx`` (job finished, or the
        whole session is suspending). Pending requests for those rows
        carry LIVE advanced constraints that only a resume could have
        continued — with the host state gone they must re-admit as
        fresh requests, exactly the retry-path rebuild."""
        stale = [k for k in self._hibernated if k[0] == id(ctx)]
        if not stale:
            return
        rows = set()
        keys: List[bytes] = []
        for k in stale:
            h = self._hibernated.pop(k)
            rows.add(k[1])
            if h.key:
                keys.append(h.key)
        if self._kv_tier is not None and keys:
            self._kv_tier.discard(keys)
        for j, r in enumerate(ctx.pending):
            if int(r.row_id) in rows and (
                r.constraint is not None or r.prep_queued
            ):
                ctx.pending[j] = dataclasses.replace(
                    r,
                    constraint=None,
                    prepped_constraint=None,
                    prep_queued=False,
                )

    def _evict_for_interactive(self, ctx: JobCtx) -> bool:
        """Latency-priority admission (Sarathi-style mixed windows): when
        an INTERACTIVE row finds the batch full, suspend one batch row —
        inside the ``EngineConfig.interactive_slots`` budget — so the
        request enters the live decode window now instead of waiting for
        a batch row to finish. The victim re-admits row-granularly (same
        rebuild rule as the retry path: a directly supplied FSM cannot
        be rewound); its partial output regenerates, exactly like a
        session-yield suspend. Returns True when a victim was freed."""
        budget = getattr(self.ecfg, "interactive_slots", 0)
        if not ctx.interactive or budget <= 0:
            return False
        if self._interactive_slots_used() >= budget:
            return False  # the tier already holds its reserved share
        best: Optional[int] = None
        best_cost = -1
        for i, s in enumerate(self.slots):
            if s is None or s.job is None or s.job.interactive:
                continue
            if s.req.constraint is not None and (
                s.req.constraint_factory is None
            ):
                continue  # not rebuildable — cannot re-admit from scratch
            cost = len(s.out_ids) + (s.prefill_pos if s.prefilling else 0)
            if best is None or cost < best_cost:
                best, best_cost = i, cost
        if best is None:
            return False
        s = self.slots[best]
        victim = s.job
        hibernated = self._hibernate_slot(best)
        if not hibernated:
            self._unreserve(best, s.pages[s.shared_n:])
            victim.n_slots -= 1
            self.slots[best] = None
            self._gen[best] += 1
            self._needs_mask.discard(best)
            # fresh request at the HEAD of pending (admission pops the
            # tail), so the victim's other rows keep their order and
            # this one re-admits once the batch has room again
            victim.pending.insert(
                0,
                dataclasses.replace(
                    s.req,
                    constraint=None,
                    prepped_constraint=None,
                    prep_queued=False,
                ),
            )
        victim.stats["preempted"] = victim.stats.get("preempted", 0) + 1
        if self._tel_on:
            telemetry.INTERACTIVE_PREEMPTIONS_TOTAL.inc(1.0)
            if victim.trace_id is not None:
                victim.trace_preempted.add(int(s.req.row_id))
                telemetry.TRACES.event(
                    victim.trace_id, "preempt_suspend",
                    {"row_id": int(s.req.row_id), "by": ctx.job_id,
                     "lost_tokens": 0 if hibernated else int(best_cost),
                     "hibernated": bool(hibernated)},
                )
        logger.debug(
            "interactive admit: suspended batch row %d of %s (%s)",
            s.req.row_id, victim.job_id,
            "hibernated" if hibernated
            else "%d tokens regenerate" % best_cost,
        )
        return True

    def _evict_for_priority(self, ctx: JobCtx) -> bool:
        """Priority-ladder admission (engine/control.py): when a
        higher-priority BATCH job finds the batch full, suspend one
        decode row of a lower-priority job — the same row-granular
        suspend/re-admit recipe as ``_evict_for_interactive`` (pages
        free, the row re-enters its job's pending queue and
        regenerates). Who outranks whom — including anti-starvation
        aging and the soft-deadline veto — is the ladder's call;
        this method only does the slot mechanics. A ladder error
        disables the ladder, never admission."""
        lad = self.ladder
        if lad is None or ctx.interactive:
            return False
        try:
            if not lad.active():
                return False
            now = time.monotonic()
            best: Optional[int] = None
            best_cost = -1
            for i, s in enumerate(self.slots):
                if s is None or s.job is None or s.job.interactive:
                    continue
                if s.job is ctx:
                    continue  # never cannibalize the preemptor itself
                if s.req.constraint is not None and (
                    s.req.constraint_factory is None
                ):
                    continue  # not rebuildable — cannot re-admit
                if not lad.may_preempt(ctx, s.job, now):
                    continue
                cost = len(s.out_ids) + (
                    s.prefill_pos if s.prefilling else 0
                )
                if best is None or cost < best_cost:
                    best, best_cost = i, cost
            if best is None:
                return False
            s = self.slots[best]
            victim = s.job
            hibernated = self._hibernate_slot(best)
            if not hibernated:
                self._unreserve(best, s.pages[s.shared_n:])
                victim.n_slots -= 1
                self.slots[best] = None
                self._gen[best] += 1
                self._needs_mask.discard(best)
                victim.pending.insert(
                    0,
                    dataclasses.replace(
                        s.req,
                        constraint=None,
                        prepped_constraint=None,
                        prep_queued=False,
                    ),
                )
            victim.stats["preempted"] = (
                victim.stats.get("preempted", 0) + 1
            )
            if self._tel_on and victim.trace_id is not None:
                victim.trace_preempted.add(int(s.req.row_id))
                telemetry.TRACES.event(
                    victim.trace_id, "preempt_suspend",
                    {"row_id": int(s.req.row_id), "by": ctx.job_id,
                     "lost_tokens": 0 if hibernated else int(best_cost),
                     "hibernated": bool(hibernated)},
                )
            lad.record(ctx, victim)
            logger.debug(
                "priority ladder: P%d %s suspended row %d of P%d %s (%s)",
                ctx.priority, ctx.job_id, s.req.row_id,
                victim.priority, victim.job_id,
                "hibernated" if hibernated
                else "%d tokens regenerate" % best_cost,
            )
            return True
        except Exception:  # noqa: BLE001 — policy errors must never
            # break admission; the control plane degrades itself on
            # its own sites, this is the scheduler-side backstop
            logger.warning(
                "priority ladder failed — disabling it", exc_info=True
            )
            self.ladder = None
            return False

    def _admit_pending(self, order: List[JobCtx]) -> bool:
        """Admit as many pending rows as slots/pages allow, pulling from
        jobs in (priority, seq) order; rows prefill in batches of up to
        ``prefill_batch_size`` per device dispatch (long rows chunk one
        at a time — see runner.prefill), and one batch may span jobs
        (per-row suffix offsets)."""
        admitted = False
        while True:
            batch = []
            reserved_tokens = 0
            reserved_idxs = set()
            while len(batch) < self.ecfg.prefill_batch_size:
                ctx = next(
                    (c for c in order if not c.done and c.pending), None
                )
                if ctx is None:
                    break
                if not ctx.prefix_ready:
                    if not any(s is None for s in self.slots) and not (
                        # a freshly attached latency/priority job must
                        # not wait for natural churn when it outranks a
                        # running row — evict here or the reserve loop's
                        # eviction path below is never reached
                        self._evict_for_interactive(ctx)
                        or self._evict_for_priority(ctx)
                    ):
                        break  # no slot anyway — defer prefix setup
                    # shared-prefix KV: prefill this job's common prefix
                    # once, right when its rows first stand a chance of
                    # admission
                    self._setup_prefix(ctx)
                    ctx.prefix_ready = True
                req = ctx.pending[-1]
                shared = ctx.prefix.tokens if ctx.prefix else 0
                # "long" is what actually rides the chunked path: the
                # row's OWN suffix (the shared prefix, if any, was
                # prefilled once at job start)
                is_long = (
                    len(req.prompt_ids) - shared
                    > self.ecfg.prefill_chunk
                )
                if (
                    is_long
                    and getattr(self.ecfg, "prefill_piggyback", True)
                    # the chunked paged-prefill program has no ring/
                    # pipeline wrapper (same gate as _setup_prefix and
                    # runner.prefill's start>0 assert) — under sp/pp,
                    # long rows keep the stop-the-world full-sequence
                    # path below
                    and getattr(self.runner, "sp", 1) == 1
                    and getattr(self.runner, "pp", 1) == 1
                ):
                    if batch:
                        break  # flush the short-row batch first
                    r = self._reserve(
                        req, ctx, reserved=reserved_tokens,
                        exclude=reserved_idxs,
                    )
                    while r is None and (
                        self._evict_for_interactive(ctx)
                        or self._evict_for_priority(ctx)
                    ):
                        r = self._reserve(
                            req, ctx, reserved=reserved_tokens,
                            exclude=reserved_idxs,
                        )
                    if r is None:
                        break
                    ctx.pending.pop()
                    if self._tel_on and ctx.trace_preempted:
                        self._trace_resume(ctx, req)
                    if self._hibernated:
                        hib = self._hibernated.pop(
                            (id(ctx), int(req.row_id)), None
                        )
                        if hib is not None:
                            req2 = self._resume_hibernated(
                                req, ctx, r, hib
                            )
                            if req2 is None:
                                admitted = True
                                continue  # armed in place — no prefill
                            req = req2  # tier miss: admit from scratch
                    try:
                        self._materialize_constraint(req)
                    except Exception as e:  # noqa: BLE001 — row isolation
                        # a row whose FSM won't compile fails ALONE:
                        # roll the reservation back and retry/quarantine
                        self._unreserve(r[0], r[1])
                        self._row_error(ctx, req, e)
                        continue
                    # Sarathi-style: reserve now, prefill ONE chunk per
                    # scheduler iteration (_prefill_tick) so active rows
                    # keep decoding instead of stalling for the whole
                    # multi-chunk prefill
                    self._admit_prefilling(req, ctx, *r)
                    admitted = True
                    continue
                if is_long and batch:
                    break  # flush the short-row batch first
                r = self._reserve(
                    req, ctx, reserved=reserved_tokens,
                    exclude=reserved_idxs,
                )
                while r is None and (
                    self._evict_for_interactive(ctx)
                    or self._evict_for_priority(ctx)
                ):
                    r = self._reserve(
                        req, ctx, reserved=reserved_tokens,
                        exclude=reserved_idxs,
                    )
                if r is None:
                    break
                ctx.pending.pop()
                if self._tel_on and ctx.trace_preempted:
                    self._trace_resume(ctx, req)
                if self._hibernated:
                    hib = self._hibernated.pop(
                        (id(ctx), int(req.row_id)), None
                    )
                    if hib is not None:
                        req2 = self._resume_hibernated(req, ctx, r, hib)
                        if req2 is None:
                            admitted = True
                            continue  # armed in place — no prefill
                        req = req2  # tier miss: admit from scratch
                try:
                    self._materialize_constraint(req)
                except Exception as e:  # noqa: BLE001 — row isolation
                    self._unreserve(r[0], r[1])
                    self._row_error(ctx, req, e)
                    continue
                batch.append((req, ctx) + r)
                reserved_tokens += self._max_total(req)
                reserved_idxs.add(r[0])
                if is_long:
                    break  # long rows prefill alone (chunked path)
            if not batch:
                return admitted
            self._admit_batch(batch)
            admitted = True

    def run_multi(
        self,
        jobs: List[JobCtx],
        *,
        on_job_done: Callable[[JobCtx, str], None],
        poll_new: Optional[Callable[[], Optional[JobCtx]]] = None,
        should_yield: Optional[Callable[[], bool]] = None,
    ) -> str:
        """Drive a multi-job co-batching session to completion.

        Jobs share the decode batch; admission pulls rows across jobs
        in (priority, seq) order; each job's results/progress stream
        through its own callbacks, and ``on_job_done(ctx, outcome)``
        fires the moment a job reaches a terminal outcome ("completed"
        or "cancelled") — other jobs keep running. ``poll_new`` is
        polled every loop iteration so the caller can ATTACH
        newly-submitted same-model jobs mid-session. ``should_yield``
        preempts the WHOLE session (returns "yielded"; non-done jobs'
        slots are dropped for row-granular resume)."""
        # fresh session: a coverage verdict cached by a previous
        # run()/run_multi() on this batcher must not gate this one's
        # first spec probe
        self._spec_cov_key = -1
        live: List[JobCtx] = []
        try:
            for ctx in jobs:
                self._start_job(ctx)
                live.append(ctx)
            # in-flight fused windows (pipelined unconstrained decode):
            # entries are (toks_dev, logps_dev, active, gens, K)
            pipe: List[Any] = []
            while True:
                if poll_new is not None:
                    while True:
                        nctx = poll_new()
                        if nctx is None:
                            break
                        self._start_job(nctx)
                        live.append(nctx)
                for ctx in live:
                    if (
                        not ctx.done
                        and ctx.should_cancel
                        and ctx.should_cancel()
                    ):
                        self._finish_job(
                            ctx, "cancelled", on_job_done,
                            emit_cancel=True,
                        )
                if should_yield and should_yield():
                    for ctx in live:
                        if not ctx.done:
                            self._suspend_job(ctx)
                    return "yielded"
                if self._kv_tier is not None:
                    # serving-side idle-session checkpoints: demote the
                    # coldest unpinned store leaves host-ward so a long
                    # think-time session stops holding HBM pages
                    for toks in self._kv_tier.pop_demote_requests():
                        self._demote_store_pages(
                            max(len(toks) // self.ecfg.kv_page_size, 1)
                        )
                ajobs = [c for c in live if not c.done]
                if self._tel_on:
                    # batch-wide spans (prefill/decode/accept) carry the
                    # live job ids; a tuple rebuild per iteration is a
                    # few hundred ns against a multi-ms device window
                    self._tel_jobs = tuple(c.job_id for c in ajobs)
                    self._tel_traces = tuple(
                        c.trace_id
                        for c in ajobs
                        if c.trace_id is not None
                    )
                if not ajobs:
                    break
                order = sorted(
                    ajobs, key=lambda c: (c.priority, c.seq)
                )
                admitted = self._admit_pending(order)
                # double-buffered admission: hand the NEXT group's lazy
                # constraint builds to the prep thread now — they
                # overlap the device window dispatched below
                self._prep_pump(order)
                # one chunk of piggybacked prefill per iteration: long
                # admits advance while the decode batch below keeps its
                # cadence (bounded degradation, never a pause)
                self._prefill_tick()
                # Immediately-finished rows (e.g. first token was stop).
                for i, s in enumerate(self.slots):
                    if (
                        s is not None
                        and not s.prefilling
                        and self._finish_reason(s, s.last_token)
                    ):
                        self._emit(i)
                self._sweep_done(live, on_job_done)
                active = [
                    i
                    for i, s in enumerate(self.slots)
                    if s is not None and not s.prefilling
                ]
                if not active:
                    ajobs = [c for c in live if not c.done]
                    if not ajobs:
                        break
                    if not admitted and not any(
                        s is not None for s in self.slots
                    ):
                        # The head row can never fit an EMPTY machine
                        # (prompt+max_new exceeds total KV capacity).
                        # Fail that one row and keep the session going —
                        # one bad row must not fail its whole job. (A
                        # PREFILLING slot means the machine is NOT empty
                        # — the row may fit once it completes.)
                        ctx = next(
                            (c for c in order if not c.done and c.pending),
                            None,
                        )
                        if ctx is not None:
                            req = ctx.pending.pop()
                            msg = (
                                "row cannot fit an empty machine: "
                                f"prompt + max_new_tokens need more KV "
                                "than the engine's total page pool"
                            )
                            if ctx.on_row_event is not None:
                                ctx.on_row_event(
                                    {"event": "row_quarantined",
                                     "row_id": req.row_id, "attempt": 0,
                                     "error": msg}
                                )
                            ctx.stats["rows"] += 1
                            ctx.on_result(
                                GenResult(
                                    row_id=req.row_id,
                                    token_ids=[],
                                    cumulative_logprob=0.0,
                                    finish_reason="error_capacity",
                                    input_tokens=len(req.prompt_ids),
                                    error=msg,
                                )
                            )
                            self._sweep_done(live, on_job_done)
                    for ctx in live:
                        if not ctx.done:
                            self._job_progress(ctx)
                    if not admitted and not any(
                        s is not None for s in self.slots
                    ) and all(not c.pending for c in live if not c.done):
                        # Only held-open stage-graph ctxs remain and no
                        # feeder can run on THIS thread until poll_new /
                        # cancel checks fire — doze instead of spinning.
                        time.sleep(0.0005)
                    continue
                if self.native is not None:
                    # dense arrays live in the C++ core, always current
                    nat = self.native
                    last, past_len, table = (
                        nat.last, nat.past_len, nat.table
                    )
                    temp, top_p, top_k = nat.temp, nat.top_p, nat.top_k
                else:
                    last = np.zeros((self.B,), np.int32)
                    past_len = np.zeros((self.B,), np.int32)
                    table = np.zeros((self.B, self.MP), np.int32)
                    temp = np.zeros((self.B,), np.float32)
                    top_p = np.ones((self.B,), np.float32)
                    top_k = np.zeros((self.B,), np.int32)
                has_constraint = False
                has_row_seed = False
                has_penalty = False
                row_seeds = np.zeros((self.B,), np.int32)
                for i in active:
                    s = self.slots[i]
                    if s.req.has_penalties():
                        has_penalty = True
                    if self.native is None:
                        last[i] = s.last_token
                        past_len[i] = s.pos
                        table[i, : len(s.pages)] = s.pages
                        temp[i] = s.req.temperature
                        top_p[i] = s.req.top_p
                        top_k[i] = s.req.top_k
                    if s.req.row_seed is not None:
                        has_row_seed = True
                        row_seeds[i] = _step_seed(
                            s.req.row_seed, len(s.out_ids)
                        )
                    else:
                        # mixed batch: unseeded rows still need fresh
                        # per-step keys (the batch-wide rng is pinned to
                        # _fixed_key when any row is seeded)
                        row_seeds[i] = _step_seed(
                            0x5EED0000 ^ (i + 1), self._step
                        )
                    if s.req.constraint is not None:
                        has_constraint = True

                # Prompt-lookup speculative decoding (opt-in,
                # spec_ngram_draft > 0): when the whole batch is plain
                # greedy and no windows are in flight, verify rows'
                # n-gram drafts in one parallel forward — up to K+1
                # tokens per row per dispatch vs the fused window's K
                # sequential steps. Host-synchronous, so the pipelined
                # windows below win under a high-RTT tunnel unless
                # draft coverage is decent (chip A/B: bench_e2e
                # SUTRO_E2E_SPEC). While a probe is pending the
                # pipeline refill below is suspended so the pipe can
                # DRAIN — a standing `not pipe` requirement against an
                # always-refilled pipe would lock speculation out
                # permanently after its first miss; a failed probe
                # backs off a few window lengths and pipelining
                # resumes at full lookahead in the meantime.
                spec_probe = (
                    getattr(self.ecfg, "spec_ngram_draft", 0) > 0
                    and self._step >= self._spec_probe_step
                    and not has_constraint
                    and not has_row_seed
                    and not has_penalty
                    # the verify forward has no ring/pipeline wrapper
                    # (same gate as the prefix cache and piggyback)
                    and getattr(self.runner, "sp", 1) == 1
                    and getattr(self.runner, "pp", 1) == 1
                    and all(
                        self.slots[i].req.temperature <= 0.0
                        for i in active
                    )
                )
                if spec_probe and pipe:
                    # host-only coverage pre-check BEFORE paying the
                    # pipeline drain: if the engagement rule fails right
                    # now, fail the probe in place and keep the pipe
                    # full — no drain bubble for batches that never
                    # draft. Computed once per probe epoch and cached
                    # across the drain iterations (drafts advance
                    # during the drain, but they are throwaway here —
                    # _spec_ngram_step recomputes real ones at engage)
                    if self._spec_cov_key != self._spec_probe_step:
                        self._spec_cov_key = self._spec_probe_step
                        self._spec_cov_ok = self._spec_coverage_ok(
                            active
                        )
                    if not self._spec_cov_ok:
                        self._spec_fail_backoff()
                        spec_probe = False
                if spec_probe and not pipe:
                    if self._spec_ngram_step(
                        active, last, past_len, table
                    ):
                        self._sweep_done(live, on_job_done)
                        for ctx in live:
                            if not ctx.done:
                                self._job_progress(ctx)
                        continue
                    self._spec_fail_backoff()
                    spec_probe = False

                # Pipelined fused windows: when no row needs host work
                # between steps, window k+1 is dispatched chained off
                # window k's device-resident tokens BEFORE window k's
                # results cross the host link, hiding the host<->device
                # round trip behind device compute (PERF.md: the RTT
                # dominates when the chip sits behind a network tunnel).
                # Page-capacity at dispatch covers every in-flight
                # window, and (slot, generation) snapshots make stale
                # windows' tokens discardable after a slot is
                # released/reused mid-pipeline.
                KS = self.ecfg.decode_multi_step
                pipe_ok = (
                    KS > 1
                    and self.ecfg.decode_lookahead > 1
                    and not has_constraint
                    and not has_row_seed
                    and not has_penalty
                    and not self._needs_mask
                )
                if pipe_ok or pipe:
                    if self._tel_on:
                        self._tel_attrs["decode_window"] = {
                            "batch": len(active),
                            "steps": KS,
                            "avg_ctx": round(
                                sum(int(past_len[i]) for i in active)
                                / max(len(active), 1), 1,
                            ),
                        }
                    # a pending spec probe suspends refill so the pipe
                    # drains (one window per iteration) and the probe
                    # above gets its `not pipe` opening
                    if pipe_ok and not spec_probe:
                        while len(pipe) < self.ecfg.decode_lookahead:
                            proj = self._pipe_projection(pipe)
                            if not self._pipe_capacity_ok(
                                active, proj, KS
                            ):
                                break
                            self._dispatch_pipelined(
                                pipe, active, last, past_len + proj,
                                table, temp, top_p, top_k, KS,
                            )
                    if pipe:
                        # drain-one: also covers pipe_ok going false
                        # (e.g. a constrained row admitted mid-pipeline)
                        # — windows drain one per iteration, then other
                        # paths resume
                        self._process_pipelined(pipe.pop(0))
                        self._sweep_done(live, on_job_done)
                        for ctx in live:
                            if not ctx.done:
                                self._job_progress(ctx)
                        continue
                    # pipe empty and nothing dispatchable (capacity
                    # below one window): fall through to single-step

                # Fuse K decode steps into one device program when no
                # row needs host work between steps: one dispatch + one
                # fetch per window instead of per token. Constrained
                # rows fuse too when they are GREEDY (classify-style
                # jobs): the window samples unmasked, the host verifies
                # tokens against each row's FSM, and only the longest
                # valid prefix is committed to pages — exact for greedy
                # (masked argmax == unmasked argmax when the unmasked
                # argmax is valid). A rejecting row takes its FSM-masked
                # step as the FIRST step of its next window (allowed0)
                # — per-row recovery; other rows keep full window
                # cadence.
                K = 1
                if (
                    self.ecfg.decode_multi_step > 1
                    and not has_row_seed
                    and not has_penalty  # counts update host-side
                    # flagged rows are fine here: the speculative window
                    # FSM-masks their first step (allowed0); only the
                    # non-greedy constrained fallback needs the masked
                    # single-step, and it clears the flags itself
                    and (not self._needs_mask or has_constraint)
                    and (
                        not has_constraint
                        or all(
                            self.slots[i].req.temperature <= 0.0
                            for i in active
                            if self.slots[i].req.constraint is not None
                        )
                    )
                ):
                    cap = min(
                        len(self.slots[i].pages) * self.ecfg.kv_page_size
                        - self.slots[i].pos
                        for i in active
                    )
                    # all-or-nothing: every distinct K is a separate XLA
                    # compilation of the fused window (steps is static),
                    # so near-capacity tails run single-step instead of
                    # walking through K-1 recompiles
                    if cap >= self.ecfg.decode_multi_step:
                        K = self.ecfg.decode_multi_step

                if self._tel_on:
                    # window attribution for the doctor's roofline
                    # grade: occupancy x fused steps over the span's
                    # duration is the window's attempted token rate
                    self._tel_attrs["decode_window"] = {
                        "batch": len(active),
                        "steps": K,
                        "avg_ctx": round(
                            sum(int(past_len[i]) for i in active)
                            / max(len(active), 1), 1,
                        ),
                    }
                self._key, sub = jax.random.split(self._key)
                # row-seeded sampling needs a batch-independent base key
                # so a row's stream reproduces regardless of batch
                # composition
                rng = self._fixed_key if has_row_seed else sub
                if K > 1 and has_constraint:
                    # FSM fast-forward first: when enough rows sit in a
                    # forced scaffold run, one parallel verify commits
                    # the whole run per row — the speculative window
                    # below would reject its unmasked samples there.
                    # Flagged SINGLETON rows are candidates too (the
                    # peel is their masked step); a flagged row in a
                    # non-singleton state sends the batch to the
                    # window's allowed0 recovery instead. The verify
                    # forward has no ring/pipeline wrapper.
                    if (
                        getattr(self.runner, "sp", 1) == 1
                        and getattr(self.runner, "pp", 1) == 1
                        and all(
                            self.slots[i].req.temperature <= 0.0
                            for i in active
                        )
                        and self._fastforward_step(
                            active, last, past_len, table
                        )
                    ):
                        self._sweep_done(live, on_job_done)
                        for ctx in live:
                            if not ctx.done:
                                self._job_progress(ctx)
                        continue
                    # speculative window: sample unmasked, verify
                    # host-side, commit only each row's FSM-valid
                    # prefix. Rows whose previous window rejected take
                    # their FSM-masked step as the window's FIRST step
                    # (allowed0) — per-row recovery, full cadence for
                    # everyone else.
                    allowed0 = None
                    flagged: set = self._needs_mask & set(active)
                    if flagged:
                        allowed0 = self._fsm_masks(flagged)
                        self._needs_mask -= flagged
                    with self.timer.time("decode"):
                        toks_w, logps_w, handle = (
                            self.runner.decode_window(
                                last, past_len, table, sub, temp, top_p,
                                K, top_k=top_k, allowed0=allowed0,
                                pfx=self._split_pfx(active),
                            )
                        )
                    self._step += K
                    t_acc = time.monotonic() if self._tel_on else 0.0
                    accepted = np.zeros((self.B,), np.int32)
                    finished: List[int] = []
                    for i in active:
                        s = self.slots[i]
                        if s is None:
                            continue  # failed during mask assembly
                        c = s.req.constraint
                        for j in range(K):
                            tok = int(toks_w[j][i])
                            # a flagged row's step-0 token was chosen
                            # UNDER its FSM mask — accept without
                            # re-verifying, exactly like the masked
                            # single-step this replaces. Re-checking
                            # would livelock in the budget-infeasible
                            # corner where allowed_tokens degrades to
                            # unfiltered but token_allowed still returns
                            # False (fsm.py degrade semantics).
                            if c is not None and not (
                                j == 0 and i in flagged
                            ):
                                rem = self._remaining(
                                    s.req, len(s.out_ids), s.pos
                                )
                                try:
                                    tok_ok = self._token_ok(c, tok, rem)
                                except Exception as e:  # noqa: BLE001 — row isolation
                                    self._fail_slot(i, e)
                                    break
                                if not tok_ok:
                                    # this row's NEXT window opens with
                                    # its FSM-masked step (allowed0) so
                                    # it crosses the scaffold token;
                                    # other rows keep full window
                                    # cadence
                                    self._needs_mask.add(i)
                                    break
                            rc = self._accept_token(
                                i, tok, float(logps_w[j][i]),
                                release=False,
                            )
                            if rc == 2:
                                break  # row failed: token NOT committed
                            accepted[i] += 1
                            if rc:
                                finished.append(i)
                                break
                    if self._tel_on:
                        self._tel_accept(t_acc)
                    # pages are still reserved for every row (releases
                    # were deferred), so the accepted K/V lands safely
                    with self.timer.time("decode"):
                        self.runner.commit_window(handle, accepted)
                    for i in finished:
                        self._emit(i)
                elif K > 1:
                    with self.timer.time("decode"):
                        toks_w, logps_w = self.runner.decode_multi(
                            last, past_len, table, sub, temp, top_p, K,
                            top_k=top_k, pfx=self._split_pfx(active),
                        )
                    self._step += K
                    for j in range(K):
                        for i in active:
                            if self.slots[i] is None:
                                continue  # finished earlier this window
                            self._accept_token(
                                i, int(toks_w[j][i]),
                                float(logps_w[j][i]),
                            )
                        active = [
                            i for i in active
                            if self.slots[i] is not None
                        ]
                        if not active:
                            break
                else:
                    allowed = None
                    if has_constraint:
                        # masked step: per-row FSM vocab masks (fused
                        # windows verify tokens instead; their allowed0
                        # recovery masks come from the same helper)
                        allowed = self._fsm_masks(active)
                    penalties = None
                    if has_penalty:
                        # Distinct generated ids carried per row. K is a
                        # jit shape, so grow it in power-of-two buckets:
                        # exact presence/frequency semantics at any
                        # generation length, with at most log2 extra
                        # compiles.
                        PK = 256
                        max_distinct = max(
                            (
                                len(self.slots[i].counts)
                                for i in active
                                if self.slots[i].req.has_penalties()
                            ),
                            default=0,
                        )
                        while PK < max_distinct:
                            PK *= 2
                        if PK > 256 and PK not in self._pk_grown:
                            self._pk_grown.add(PK)
                            logger.info(
                                "penalty id buffer grown to K=%d (a row "
                                "has %d distinct generated ids)",
                                PK, max_distinct,
                            )
                        nb = (self.vocab + 7) // 8
                        seen_packed = np.zeros((self.B, nb), np.uint8)
                        ids_p = np.full((self.B, PK), -1, np.int32)
                        cnt_p = np.zeros((self.B, PK), np.float32)
                        pres = np.zeros((self.B,), np.float32)
                        freq = np.zeros((self.B,), np.float32)
                        rep = np.ones((self.B,), np.float32)
                        for i in active:
                            s = self.slots[i]
                            if not s.req.has_penalties():
                                continue
                            pres[i] = s.req.presence_penalty
                            freq[i] = s.req.frequency_penalty
                            rep[i] = s.req.repetition_penalty
                            if s.seen_bits is not None:
                                seen_packed[i] = s.seen_bits  # memcpy
                            assert len(s.counts) <= PK  # growth above
                            for j, t in enumerate(s.counts):
                                ids_p[i, j] = t
                                cnt_p[i, j] = s.counts[t]
                        penalties = (
                            seen_packed, ids_p, cnt_p, pres, freq, rep
                        )
                    with self.timer.time("decode"):
                        toks, logps = self.runner.decode_step(
                            last, past_len, table, rng, temp, top_p,
                            top_k=top_k, allowed=allowed,
                            row_seeds=(
                                row_seeds if has_row_seed else None
                            ),
                            penalties=penalties,
                            pfx=self._split_pfx(active),
                        )
                    self._step += 1
                    # masked single-step crossed every flagged row's
                    # rejected scaffold token
                    self._needs_mask.clear()
                    for i in active:
                        if self.slots[i] is None:
                            continue  # failed during mask assembly
                        self._accept_token(
                            i, int(toks[i]), float(logps[i])
                        )
                self._sweep_done(live, on_job_done)
                for ctx in live:
                    if not ctx.done:
                        self._job_progress(ctx)
            return "completed"
        finally:
            # every exit path (completed / yielded / raise) returns any
            # live job's shared-prefix pages to the pool (_finish_job
            # and _suspend_job already None the refs they freed) and
            # parks the admission-prep thread
            self._prep_stop()
            for ctx in live:
                if ctx.prefix is not None:
                    self._release_prefix(ctx.prefix)
                    ctx.prefix = None
                if self._hibernated:
                    self._purge_hibernated(ctx)
            if self._hibernated and self._kv_tier is not None:
                # entries of jobs no longer in ``live`` (defensive —
                # purge runs on every terminal transition above)
                self._kv_tier.discard(
                    [h.key for h in self._hibernated.values() if h.key]
                )
                self._hibernated.clear()
