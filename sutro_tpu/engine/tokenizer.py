"""Tokenizer layer.

The reference has no tokenizer (token counting happens server-side; SURVEY
§2.3) — the TPU build needs one for prefill, honest ``truncate_rows``
(reference sdk.py:457,480), dry-run cost estimation (sdk.py:245-262), and
constrained decoding (token-level FSM needs per-token byte strings).

Two implementations behind one interface:

- ``HFTokenizer`` — loads a local HuggingFace ``tokenizer.json`` via the
  ``tokenizers`` library (works for the whole Qwen3/Llama/Gemma/gpt-oss
  catalog when a checkpoint dir is available).
- ``ByteTokenizer`` — dependency-free byte-level tokenizer (vocab = 256
  bytes + specials) used for tests and random-weight tiny models; also the
  worst-case-honest token counter when no checkpoint is present.

Both expose ``token_bytes(id)`` so the constrained-decoding FSM
(engine/constrain/) can walk token strings without tokenizer-specific code.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .. import telemetry


def _gpt2_byte_decoder() -> Dict[str, int]:
    """Inverse of the GPT-2 byte-level BPE unicode mapping: printable stand-in
    char -> original byte. Covers Qwen/Llama/gpt-oss vocabs."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(0xA1, 0xAC + 1))
        + list(range(0xAE, 0xFF + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


_GPT2_BYTE_DECODER = _gpt2_byte_decoder()


class BaseTokenizer:
    vocab_size: int
    eos_id: int
    pad_id: int
    bos_id: Optional[int] = None

    def encode(self, text: str) -> List[int]:
        raise NotImplementedError

    def encode_batch(self, texts: Sequence[str]) -> List[List[int]]:
        """Encode many texts in one call. Subclasses with a native
        batched path (HF's rust ``encode_batch``) override; the default
        loops — still one call site, so the engine never hand-rolls the
        per-row loop again."""
        return [self.encode(t) for t in texts]

    def concat_safe(self, left: str) -> bool:
        """True when ``encode(left + right) == encode(left) +
        encode(right)`` for EVERY right — i.e. no token can span the
        boundary after ``left``. Enables the shared-shell tokenization
        fast path (encode the chat-template shell once, per-row
        suffixes in batch). Default False: BPE merges can cross any
        boundary, so only tokenizers that can prove safety opt in."""
        return False

    def decode(self, ids: Sequence[int]) -> str:
        raise NotImplementedError

    def token_bytes(self, token_id: int) -> bytes:
        """Raw bytes this token contributes to the output stream (empty for
        special/control tokens)."""
        raise NotImplementedError

    def count(self, text: str) -> int:
        return len(self.encode(text))

    # -- chat templating ----------------------------------------------------
    def render_chat(
        self,
        user: str,
        system: Optional[str] = None,
        template: str = "chatml",
        add_generation_prompt: bool = True,
    ) -> str:
        """Render a single-turn prompt. ``chatml`` covers the Qwen/gpt-oss
        style; ``plain`` concatenates (tiny-model/tests); ``gemma``/``llama3``
        cover those families."""
        if template == "plain":
            return (system + "\n\n" if system else "") + user
        if template == "gemma":
            sys_part = (system + "\n\n") if system else ""
            out = f"<start_of_turn>user\n{sys_part}{user}<end_of_turn>\n"
            if add_generation_prompt:
                out += "<start_of_turn>model\n"
            return out
        if template == "llama3":
            out = "<|begin_of_text|>"
            if system:
                out += (
                    "<|start_header_id|>system<|end_header_id|>\n\n"
                    f"{system}<|eot_id|>"
                )
            out += (
                "<|start_header_id|>user<|end_header_id|>\n\n"
                f"{user}<|eot_id|>"
            )
            if add_generation_prompt:
                out += "<|start_header_id|>assistant<|end_header_id|>\n\n"
            return out
        # chatml (default)
        out = ""
        if system:
            out += f"<|im_start|>system\n{system}<|im_end|>\n"
        out += f"<|im_start|>user\n{user}<|im_end|>\n"
        if add_generation_prompt:
            out += "<|im_start|>assistant\n"
        return out

    def render_chat_continuation(
        self, user: str, template: str = "chatml"
    ) -> str:
        """Render the NEXT user turn of a running conversation — the
        text appended after an assistant reply whose terminal stop
        token was stripped from the stream (serving sessions store
        ``prompt_ids + token_ids``, which end mid-assistant-turn). The
        scaffold therefore re-supplies the assistant-end marker, then
        the user turn, then the generation prompt, so that
        ``stored_text + continuation`` is exactly the multi-turn render
        and the stored ids stay a strict prefix of the next prompt
        (the property session KV checkpointing rides on)."""
        if template == "plain":
            return "\n\n" + user
        if template == "gemma":
            return (
                "<end_of_turn>\n"
                f"<start_of_turn>user\n{user}<end_of_turn>\n"
                "<start_of_turn>model\n"
            )
        if template == "llama3":
            return (
                "<|eot_id|>"
                "<|start_header_id|>user<|end_header_id|>\n\n"
                f"{user}<|eot_id|>"
                "<|start_header_id|>assistant<|end_header_id|>\n\n"
            )
        # chatml (default)
        return (
            f"<|im_end|>\n<|im_start|>user\n{user}<|im_end|>\n"
            "<|im_start|>assistant\n"
        )


class ByteTokenizer(BaseTokenizer):
    """Byte-level tokenizer: ids 0..255 are raw bytes; specials follow.

    Special strings are tokenized atomically so chat templates round-trip.
    """

    SPECIALS = [
        "<pad>",
        "<eos>",
        "<bos>",
        "<|im_start|>",
        "<|im_end|>",
        "<start_of_turn>",
        "<end_of_turn>",
        "<|eot_id|>",
        "<|begin_of_text|>",
        "<|start_header_id|>",
        "<|end_header_id|>",
    ]

    def __init__(self, vocab_size: Optional[int] = None):
        self._special_to_id: Dict[str, int] = {
            s: 256 + i for i, s in enumerate(self.SPECIALS)
        }
        self.vocab_size = vocab_size or (256 + len(self.SPECIALS))
        if self.vocab_size < 256 + len(self.SPECIALS):
            raise ValueError("vocab_size too small for byte tokenizer")
        self.pad_id = self._special_to_id["<pad>"]
        self.eos_id = self._special_to_id["<eos>"]
        self.bos_id = self._special_to_id["<bos>"]
        self.im_end_id = self._special_to_id["<|im_end|>"]

    def encode(self, text: str) -> List[int]:
        ids: List[int] = []
        i = 0
        while i < len(text):
            matched = False
            if text[i] == "<":
                for s, sid in self._special_to_id.items():
                    if text.startswith(s, i):
                        ids.append(sid)
                        i += len(s)
                        matched = True
                        break
            if not matched:
                ids.extend(text[i].encode("utf-8"))
                i += 1
        return ids

    def decode(self, ids: Sequence[int]) -> str:
        out = bytearray()
        for t in ids:
            t = int(t)
            if t < 256:
                out.append(t)
        return out.decode("utf-8", errors="replace")

    def token_bytes(self, token_id: int) -> bytes:
        token_id = int(token_id)
        if token_id < 256:
            return bytes([token_id])
        return b""

    def concat_safe(self, left: str) -> bool:
        """The byte encoder scans left-to-right with no cross-char
        state, so the ONLY way a boundary changes tokenization is a
        special token starting inside ``left`` and ending after it.
        Safe iff ``left`` does not end with a proper prefix of any
        special."""
        for s in self._special_to_id:
            for k in range(1, len(s)):
                if left.endswith(s[:k]):
                    return False
        return True

    def stop_ids(self) -> List[int]:
        return [self.eos_id, self.im_end_id]


class HFTokenizer(BaseTokenizer):
    """Wraps a local HuggingFace ``tokenizer.json`` (no network)."""

    def __init__(self, path: str):
        from tokenizers import Tokenizer as _Tok

        if os.path.isdir(path):
            path = os.path.join(path, "tokenizer.json")
        self._tok = _Tok.from_file(path)
        self.vocab_size = self._tok.get_vocab_size()
        self._vocab = self._tok.get_vocab()
        self._token_bytes_cache: Dict[int, bytes] = {}
        # Byte-level BPE detection: every char of a known word-ish token maps
        # through the GPT-2 byte decoder.
        probe = self._tok.id_to_token(min(1000, self.vocab_size - 1)) or ""
        self._byte_level = bool(probe) and all(
            c in _GPT2_BYTE_DECODER for c in probe
        )
        ids = {}
        for cand in ["<|im_end|>", "<|endoftext|>", "</s>", "<eos>", "<end_of_turn>", "<|eot_id|>", "<|return|>"]:
            if cand in self._vocab:
                ids[cand] = self._vocab[cand]
        # eos preference order per family
        self.eos_id = next(iter(ids.values())) if ids else self.vocab_size - 1
        self._stop = list(dict.fromkeys(ids.values()))
        self.pad_id = self._vocab.get("<|endoftext|>", self.eos_id)
        self.bos_id = self._vocab.get("<|begin_of_text|>", self._vocab.get("<bos>"))

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def encode_batch(self, texts: Sequence[str]) -> List[List[int]]:
        """Rust-side batched encode: releases the GIL and parallelizes
        internally — the per-row Python call overhead (the dominant host
        cost of tokenizing a 20k-row job) disappears."""
        if not texts:
            return []
        encs = self._tok.encode_batch(
            list(texts), add_special_tokens=False
        )
        return [e.ids for e in encs]

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(map(int, ids)), skip_special_tokens=True)

    def token_bytes(self, token_id: int) -> bytes:
        """Raw bytes of the token piece. Handles GPT-2 byte-level vocabs
        (per-char byte decoder — a lone token of a multi-byte UTF-8 char
        yields its true bytes, not U+FFFD) and SentencePiece vocabs
        ('▁' = space, '<0xNN>' byte tokens). Specials yield b""."""
        token_id = int(token_id)
        cached = self._token_bytes_cache.get(token_id)
        if cached is not None:
            return cached
        piece = self._tok.id_to_token(token_id)
        if piece is None:
            out = b""
        elif piece.startswith("<") and piece.endswith(">"):
            if len(piece) == 6 and piece[1:3].lower() == "0x":
                try:
                    out = bytes([int(piece[1:5], 16)])
                except ValueError:
                    out = b""
            else:
                out = b""  # special/control token
        elif self._byte_level:
            try:
                out = bytes(_GPT2_BYTE_DECODER[c] for c in piece)
            except KeyError:
                out = piece.encode("utf-8")
        else:
            out = piece.replace("▁", " ").encode("utf-8")
        self._token_bytes_cache[token_id] = out
        return out

    def stop_ids(self) -> List[int]:
        return self._stop or [self.eos_id]


def render_shell(
    tok: BaseTokenizer,
    system: Optional[str],
    template: str,
) -> Tuple[str, str]:
    """Split the chat template into the (prefix, suffix) shell around
    the user row: every row's prompt is ``pre + row + post``. Templates
    embed the user text verbatim (pure concatenation), so rendering via
    the shell is string-identical to per-row ``render_chat``."""
    mark = "\x00\x01sutro-row\x01\x00"
    shell = tok.render_chat(mark, system=system, template=template)
    pre, sep, post = shell.partition(mark)
    if not sep:  # a template that transforms user text: no shell
        return "", ""
    return pre, post


def encode_chat_batch(
    tok: BaseTokenizer,
    rows: Sequence[str],
    system: Optional[str],
    template: str,
    threads: int = 0,
) -> List[List[int]]:
    """Tokenize every row's full chat prompt in one batched pass.

    Prefix-aware: when the tokenizer proves the shell boundary is
    concat-safe (ByteTokenizer), the shared shell prefix — chat
    scaffold plus the whole system prompt — is encoded ONCE and each
    row encodes only ``row + suffix``; a 20k-row job stops re-encoding
    20k copies of its system prompt. Unsafe tokenizers (BPE merges span
    boundaries) encode full prompts through ``encode_batch``, which is
    the rust-parallel path for HF vocabs. Either way the ids are
    bit-identical to per-row ``encode(render_chat(row))`` — verified on
    the first row, with a full-prompt fallback if the proof ever fails.

    ``threads`` > 1 splits the batch across a thread pool — only useful
    for tokenizers whose ``encode_batch`` releases the GIL.
    """
    rows = list(rows)
    if not rows:
        return []
    if telemetry.ENABLED:
        # batch-granular (never per row): row volume through the batched
        # tokenize path, plus its latency histogram below
        telemetry.TOKENIZE_ROWS_TOTAL.inc(float(len(rows)))
        t0 = time.monotonic()
        try:
            return _encode_chat_batch(tok, rows, system, template, threads)
        finally:
            telemetry.stage_observe(
                "tokenize", time.monotonic() - t0
            )
    return _encode_chat_batch(tok, rows, system, template, threads)


def _encode_chat_batch(
    tok: BaseTokenizer,
    rows: List[str],
    system: Optional[str],
    template: str,
    threads: int = 0,
) -> List[List[int]]:
    def _batched(texts: List[str]) -> List[List[int]]:
        if threads > 1 and len(texts) >= 2 * threads:
            from concurrent.futures import ThreadPoolExecutor

            step = (len(texts) + threads - 1) // threads
            chunks = [
                texts[o : o + step] for o in range(0, len(texts), step)
            ]
            with ThreadPoolExecutor(max_workers=threads) as ex:
                parts = list(ex.map(tok.encode_batch, chunks))
            return [ids for part in parts for ids in part]
        return tok.encode_batch(texts)

    pre, post = render_shell(tok, system, template)
    if not pre and not post:
        # no recoverable shell: render per row (templates that
        # transform user text), still one batched encode
        return _batched(
            [
                tok.render_chat(r, system=system, template=template)
                for r in rows
            ]
        )
    if pre and tok.concat_safe(pre):
        head = tok.encode(pre)
        out = [head + ids for ids in _batched([r + post for r in rows])]
        # boundary proof spot-check: one direct encode per job
        if out[0] != tok.encode(pre + rows[0] + post):
            out = _batched([pre + r + post for r in rows])
        return out
    return _batched([pre + r + post for r in rows])


def load_tokenizer(
    weights_dir: Optional[str], vocab_size: Optional[int] = None
) -> BaseTokenizer:
    """HF tokenizer if a checkpoint dir with tokenizer.json exists, else the
    byte tokenizer sized to the model's vocab."""
    if weights_dir:
        tj = os.path.join(weights_dir, "tokenizer.json")
        if os.path.exists(tj):
            return HFTokenizer(tj)
    return ByteTokenizer(vocab_size=vocab_size)
