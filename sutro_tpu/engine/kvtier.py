"""Tiered paged-KV pool: HBM -> pinned host RAM -> disk.

ROADMAP "KV tiering + session hibernation": every byte of warm state
used to live in one device's HBM, so resident capacity — and resume
latency for anything that fell out — was hard-capped by device KV.
This module owns the two tiers BELOW the device pool and the bounded
migration worker that moves page payloads between them:

- **host tier** — an LRU dict of page payloads, always stored int8
  (quantize-on-demote via the same math as ``kvcache._quantize_tokens``,
  regardless of the HBM pool dtype) so a host-RAM byte holds 2x the
  bf16 tokens. Budgeted in pages (``host_pages``).
- **disk tier** — one ``.npz`` bundle per entry under ``disk_dir``,
  written with the jobstore partial-store idiom (tmp + atomic rename;
  torn files quarantined to ``.corrupt/`` on read, never crashing the
  reader). Host-tier overflow spills here; entries survive the process.

The pool stores PAYLOADS, not device pages: entries are keyed by the
raw bytes of the FULL token prefix whose KV they hold (prefix pages) or
by an opaque hibernation key (suspended rows), so promotion is exact —
KV depends on (tokens, positions) only, and a byte-equal key guarantees
a bit-identical (up to int8 round-trip) page. Device-side ownership
never enters this module: the scheduler reads pages out of the runner
BEFORE freeing them and uploads into freshly allocated pages on
promote.

Migration worker: demotions are staged synchronously (the raw payload
is already a host copy) and quantized/spilled asynchronously on one
bounded daemon thread — the scheduler hot path never waits on a disk
write. ``drain()`` flushes the queue for deterministic tests.

Torn-migration contract (chaos suite, FAILURES.md):

- a torn DEMOTION (fault site ``kvtier.demote``) drops the entry — the
  HBM copy (or the request itself) stays authoritative, degrading to a
  plain eviction / full regenerate, never to corruption;
- a torn PROMOTION (``kvtier.promote``) retries once, then returns
  None — the caller re-prefills the tokens it asked for;
- a torn DISK WRITE (``kvtier.disk_write``) leaves the host copy in
  place (durability is best-effort; the host tier stays authoritative
  until the rename lands), and a torn file on disk is quarantined at
  read time.

Kill switch: the pool only exists when ``EngineConfig.kv_tiers`` is on
and ``SUTRO_KV_TIERS`` is not ``0``/``off`` — the scheduler holds None
otherwise and runs the untiered path bit-identically with zero tier
ops (asserted by tests/test_kv_tiers.py).
"""

from __future__ import annotations

import hashlib
import logging
import queue
import threading
from collections import OrderedDict
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import telemetry
from . import faults

logger = logging.getLogger("sutro.kvtier")

# payload dict keys: int8 values + f32 per-token scales, [L, n, PS, KD]
# and [L, n, PS] — the canonical below-HBM page format
_PAYLOAD_KEYS = ("k", "v", "ks", "vs")


def quantize_payload(raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Raw page payload (any float dtype, or already int8+scales) ->
    canonical int8 payload. The math is kvcache._quantize_tokens
    verbatim (f32 amax / 127, 1e-8 floor, symmetric clip) so a
    demote->promote round trip through a bf16 pool drifts no more than
    the round-4 ``kv_quantize="int8"`` bound."""
    if raw["k"].dtype == np.int8:
        return raw  # int8 pool: already values+scales, bit-exact
    out: Dict[str, np.ndarray] = {}
    for vk, sk in (("k", "ks"), ("v", "vs")):
        xf = np.asarray(raw[vk], np.float32)
        amax = np.max(np.abs(xf), axis=-1)
        scale = np.maximum(amax / 127.0, 1e-8)
        q = np.clip(np.rint(xf / scale[..., None]), -127, 127)
        out[vk] = q.astype(np.int8)
        out[sk] = scale.astype(np.float32)
    return out


def dequantize_payload(
    payload: Dict[str, np.ndarray], dtype
) -> Dict[str, np.ndarray]:
    """Canonical int8 payload -> float values in ``dtype`` (promotion
    into an unquantized HBM pool)."""
    return {
        "k": (
            payload["k"].astype(np.float32) * payload["ks"][..., None]
        ).astype(dtype),
        "v": (
            payload["v"].astype(np.float32) * payload["vs"][..., None]
        ).astype(dtype),
    }


class _Entry:
    __slots__ = ("payload", "n_pages", "pin")

    def __init__(self, payload: Dict[str, np.ndarray], pin: bool):
        self.payload = payload
        self.n_pages = int(payload["k"].shape[1])
        self.pin = pin  # pinned entries (hibernated rows) never DROP —
        #                 they may spill to disk, but only durably


class KVTierPool:
    """Host + disk tiers for paged-KV payloads, engine-lifetime."""

    def __init__(
        self,
        page_size: int,
        *,
        host_pages: int = 4096,
        disk_dir: Optional[Path] = None,
        queue_depth: int = 256,
    ):
        self.page_size = int(page_size)
        self.host_pages = int(host_pages)
        self.disk_dir = Path(disk_dir) if disk_dir is not None else None
        self._lock = threading.RLock()
        self._host: "OrderedDict[bytes, _Entry]" = OrderedDict()
        self._staging: Dict[bytes, Tuple[Dict[str, np.ndarray], bool]] = {}
        self._disk: Dict[bytes, int] = {}  # key -> n_pages on disk
        self._host_used = 0
        self._closed = False
        # demote requests posted by the gateway's idle-session
        # checkpointer; drained by the live batcher at a safe point
        # (it owns the allocator the freed pages return to)
        self._demote_req: "queue.SimpleQueue[np.ndarray]" = (
            queue.SimpleQueue()
        )
        # exact op census (tests + profile_host_overhead assert ZERO of
        # everything with the kill switch off)
        self.demotes = 0
        self.promotes = 0
        self.disk_writes = 0
        self.disk_reads = 0
        self.dropped = 0  # torn/overflowed migrations (never pinned)
        # bounded migration worker: the scheduler never blocks on
        # quantization or a disk write
        self._q: "queue.Queue[Optional[bytes]]" = queue.Queue(
            maxsize=max(8, int(queue_depth))
        )
        self._inflight = 0
        self._idle = threading.Condition(self._lock)
        if self.disk_dir is not None:
            try:
                self.disk_dir.mkdir(parents=True, exist_ok=True)
                self._scan_disk()
            except OSError:
                logger.warning(
                    "kv tier disk dir unusable; disk tier off",
                    exc_info=True,
                )
                self.disk_dir = None
        # the worker starts only once the disk tier is decided: it
        # reads ``disk_dir``/``_disk`` without the lock, so both must
        # be fully published before the thread exists (the old order
        # let the OSError fallback above race the first migration)
        self._worker = threading.Thread(
            target=self._run_worker, daemon=True, name="sutro-kv-migrate"
        )
        self._worker.start()

    # -- key helpers ----------------------------------------------------

    @staticmethod
    def prefix_key(tokens: np.ndarray) -> bytes:
        """Content key for a prefix page: the raw bytes of the FULL
        token prefix through that page (causal attention: a page's KV
        is only valid joined with every ancestor token)."""
        return np.ascontiguousarray(
            np.asarray(tokens, np.int32)
        ).tobytes()

    # -- demotion (device -> host) --------------------------------------

    def put_page(self, key: bytes, raw: Dict[str, np.ndarray]) -> None:
        """Stage one demoted PREFIX page asynchronously. ``raw`` is the
        runner's host copy (any pool dtype); the worker quantizes and
        inserts. Lossy by design: a full queue or a torn demotion drops
        the entry (plain eviction), never blocks the scheduler."""
        with self._lock:
            if self._closed or key in self._host or key in self._staging:
                return
            self._staging[key] = (raw, False)
            self._inflight += 1
        try:
            self._q.put_nowait(key)
        except queue.Full:
            with self._lock:
                self._staging.pop(key, None)
                self._inflight -= 1
                self.dropped += 1
                self._idle.notify_all()

    def put_row(self, key: bytes, raw: Dict[str, np.ndarray]) -> None:
        """Demote a HIBERNATED row's pages synchronously and pinned.
        Raises on a torn demotion (fault site ``kvtier.demote``) so the
        caller can fall back to the regenerate path BEFORE freeing the
        row's device pages — the HBM copy stays authoritative until
        this returns."""
        if faults.ACTIVE is not None:
            faults.inject("kvtier.demote")
        payload = quantize_payload(raw)
        with self._lock:
            if self._closed:
                raise RuntimeError("kv tier pool is closed")
            self._insert_host(key, _Entry(payload, pin=True))
        self._count("demote")

    # -- promotion (host/disk -> device) --------------------------------

    def get_page(self, key: bytes) -> Optional[Dict[str, np.ndarray]]:
        """Payload for ``key`` or None. Checks host, then staging (a
        demotion still in the worker queue), then disk. A torn
        promotion (fault site ``kvtier.promote``) retries once, then
        degrades to a miss — the caller re-prefills."""
        for attempt in (0, 1):
            try:
                if faults.ACTIVE is not None:
                    faults.inject("kvtier.promote")
                return self._get_once(key)
            except Exception:
                if attempt:
                    logger.warning(
                        "kv tier promote failed twice; degrading to "
                        "re-prefill", exc_info=True,
                    )
                    return None
        return None

    def take_row(self, key: bytes) -> Optional[Dict[str, np.ndarray]]:
        """Promote-and-remove a hibernated row's payload (a resumed row
        re-demotes on its next suspension; keeping the stale copy would
        serve an outdated sequence)."""
        payload = self.get_page(key)
        if payload is not None:
            self.discard([key])
        return payload

    def _get_once(self, key: bytes) -> Optional[Dict[str, np.ndarray]]:
        with self._lock:
            ent = self._host.get(key)
            if ent is not None:
                self._host.move_to_end(key)
                self._count("promote")
                return ent.payload
            staged = self._staging.get(key)
            if staged is not None:
                self._count("promote")
                return quantize_payload(staged[0])
            on_disk = key in self._disk
        if not on_disk or self.disk_dir is None:
            return None
        payload = self._disk_read(key)
        if payload is None:
            return None
        with self._lock:
            # cache the disk hit back in the host tier (it is warm now)
            if key not in self._host and not self._closed:
                self._insert_host(key, _Entry(payload, pin=False))
            self._count("promote")
        return payload

    def discard(self, keys: List[bytes]) -> None:
        """Drop entries in every tier (promoted into HBM, or a session
        reset). Missing keys are fine."""
        with self._lock:
            for key in keys:
                ent = self._host.pop(key, None)
                if ent is not None:
                    self._host_used -= ent.n_pages
                self._staging.pop(key, None)
                self._disk.pop(key, None)
            self._set_gauges()
        if self.disk_dir is not None:
            for key in keys:
                try:
                    self._disk_path(key).unlink(missing_ok=True)
                except OSError:
                    pass

    # -- gateway-side idle checkpointing --------------------------------

    def request_demote(self, tokens: np.ndarray) -> None:
        """Post a demote request for the prefix-store pages covering
        ``tokens`` (an idle session's conversation). The LIVE batcher
        drains these at its loop top — it owns the allocator that the
        freed device pages return to; with no batcher running the pages
        simply stay warm in HBM."""
        self._demote_req.put(np.asarray(tokens, np.int32))

    def pop_demote_requests(self) -> List[np.ndarray]:
        out: List[np.ndarray] = []
        while True:
            try:
                out.append(self._demote_req.get_nowait())
            except queue.Empty:
                return out

    # -- accounting -----------------------------------------------------

    def pages(self, tier: str) -> int:
        with self._lock:
            if tier == "host":
                return self._host_used + sum(
                    int(np.asarray(r["k"]).shape[1])
                    for r, _ in self._staging.values()
                )
            if tier == "disk":
                return sum(self._disk.values())
            raise ValueError(f"unknown tier {tier!r}")

    def op_census(self) -> Dict[str, int]:
        with self._lock:
            return {
                "demotes": self.demotes,
                "promotes": self.promotes,
                "disk_writes": self.disk_writes,
                "disk_reads": self.disk_reads,
                "dropped": self.dropped,
            }

    def set_host_budget(self, pages: int) -> int:
        """Re-budget the pinned-host tier live (the control plane's
        ``kv_tier_host_pages`` knob actuates through here). Shrinking
        evicts LRU entries immediately — spilled to disk when a disk
        tier exists, else unpinned entries drop; pinned entries without
        a disk tier stay resident over budget (a hibernated row is
        never lost). Returns the applied budget."""
        pages = max(1, int(pages))
        with self._lock:
            if self._closed:
                return self.host_pages
            self.host_pages = pages
            self._evict_host_locked()
            self._set_gauges()
        return pages

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until the migration worker has consumed every staged
        demotion/spill (deterministic tests; engine drain)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        with self._idle:
            while self._inflight > 0:
                left = deadline - _time.monotonic()
                if left <= 0:
                    return False
                self._idle.wait(min(left, 0.25))
            return True

    def close(self, timeout: float = 10.0) -> None:
        """Stop the worker with a bounded join; the host tier drops
        (its payloads die with the process anyway), disk entries stay
        for the next process."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass
        self._worker.join(timeout=timeout)
        with self._lock:
            self._host.clear()
            self._staging.clear()
            self._host_used = 0
            self._set_gauges()

    # -- internals ------------------------------------------------------

    def _count(self, direction: str) -> None:
        with self._lock:
            if direction == "demote":
                self.demotes += 1
            elif direction == "promote":
                self.promotes += 1
            elif direction == "disk_write":
                self.disk_writes += 1
            elif direction == "disk_read":
                self.disk_reads += 1
        if telemetry.ENABLED:
            telemetry.KV_MIGRATIONS_TOTAL.inc(1.0, direction)

    def _set_gauges(self) -> None:
        # caller holds the lock
        if telemetry.ENABLED:
            telemetry.KV_TIER_PAGES.set(float(self._host_used), "host")
            telemetry.KV_TIER_PAGES.set(
                float(sum(self._disk.values())), "disk"
            )

    def _insert_host(self, key: bytes, ent: _Entry) -> None:
        # caller holds the lock
        old = self._host.pop(key, None)
        if old is not None:
            self._host_used -= old.n_pages
        self._host[key] = ent
        self._host_used += ent.n_pages
        self._evict_host_locked()
        self._set_gauges()

    def _evict_host_locked(self) -> None:
        """Shed LRU host entries over budget: spill to disk when a disk
        tier exists (durable-before-drop for pinned entries), else drop
        unpinned ones. Pinned entries without a disk tier stay resident
        over budget — a hibernated row must never be lost."""
        if self._host_used <= self.host_pages:
            return
        for key in list(self._host.keys()):
            if self._host_used <= self.host_pages:
                return
            ent = self._host[key]
            if self.disk_dir is not None:
                # durable first: the entry leaves the host tier from
                # the worker only after the rename lands
                if key not in self._disk:
                    self._staging.setdefault(
                        key, (ent.payload, ent.pin)
                    )
                    self._inflight += 1
                    try:
                        self._q.put_nowait(key)
                    except queue.Full:
                        self._staging.pop(key, None)
                        self._inflight -= 1
                        if not ent.pin:
                            del self._host[key]
                            self._host_used -= ent.n_pages
                            self.dropped += 1
                        continue
                    # optimistic: the worker completes the spill and
                    # removes the host copy; keep it until then
                    continue
                del self._host[key]
                self._host_used -= ent.n_pages
            elif not ent.pin:
                del self._host[key]
                self._host_used -= ent.n_pages
                self.dropped += 1
            # pinned + no disk: keep (bounded by live hibernated rows)

    def _run_worker(self) -> None:
        while True:
            key = self._q.get()
            if key is None:
                return
            try:
                self._migrate_one(key)
            except Exception:  # noqa: BLE001 — a torn migration drops
                # one cache entry; the worker itself must survive
                logger.warning(
                    "kv tier migration failed; entry dropped",
                    exc_info=True,
                )
                with self._lock:
                    self._staging.pop(key, None)
                    self.dropped += 1
            finally:
                with self._idle:
                    self._inflight -= 1
                    self._idle.notify_all()

    def _migrate_one(self, key: bytes) -> None:
        with self._lock:
            staged = self._staging.get(key)
            already_host = key in self._host
        if staged is None:
            return  # raced with discard()
        raw, pin = staged
        if not already_host:
            # async prefix-page demotion: quantize + insert
            if faults.ACTIVE is not None:
                faults.inject("kvtier.demote")
            payload = quantize_payload(raw)
            with self._lock:
                if self._closed:
                    return
                self._staging.pop(key, None)
                self._insert_host(key, _Entry(payload, pin))
            self._count("demote")
            return
        # spill: host copy stays authoritative until the rename lands
        payload = quantize_payload(raw)
        if self.disk_dir is not None and self._disk_write(key, payload):
            with self._lock:
                ent = self._host.pop(key, None)
                if ent is not None:
                    self._host_used -= ent.n_pages
                self._staging.pop(key, None)
                self._set_gauges()
        else:
            with self._lock:
                self._staging.pop(key, None)

    # -- disk tier (jobstore partial-store idiom) -----------------------

    def _disk_path(self, key: bytes) -> Path:
        return self.disk_dir / (
            hashlib.blake2b(key, digest_size=16).hexdigest() + ".npz"
        )

    def _scan_disk(self) -> None:
        for p in self.disk_dir.glob("*.npz"):
            try:
                with np.load(p) as z:
                    self._disk[bytes(z["key"].tobytes())] = int(
                        z["k"].shape[1]
                    )
            except Exception:  # noqa: BLE001 — torn leftovers quarantine
                self._quarantine(p)

    def _disk_write(
        self, key: bytes, payload: Dict[str, np.ndarray]
    ) -> bool:
        path = self._disk_path(key)
        tmp = path.with_suffix(".npz.tmp")
        try:
            if faults.ACTIVE is not None:
                spec = faults.fire("kvtier.disk_write")
                if spec is not None:
                    if spec.kind == "torn":
                        # crash between write and fsync on a non-durable
                        # fs: a truncated bundle at the FINAL name (the
                        # reader quarantines it; the host copy stays)
                        import io

                        buf = io.BytesIO()
                        np.savez(
                            buf, key=np.frombuffer(key, np.uint8),
                            **payload,
                        )
                        data = buf.getvalue()
                        path.write_bytes(data[: max(8, len(data) // 2)])
                    spec.trigger()
            with open(tmp, "wb") as f:
                np.savez(f, key=np.frombuffer(key, np.uint8), **payload)
            tmp.replace(path)  # atomic on POSIX
        except Exception:  # noqa: BLE001 — durability is best-effort;
            # the host copy stays authoritative
            logger.warning("kv tier disk write failed", exc_info=True)
            try:
                tmp.unlink(missing_ok=True)
            except OSError:
                pass
            return False
        with self._lock:
            self._disk[key] = int(payload["k"].shape[1])
            self._set_gauges()
        self._count("disk_write")
        return True

    def _disk_read(
        self, key: bytes
    ) -> Optional[Dict[str, np.ndarray]]:
        path = self._disk_path(key)
        try:
            with np.load(path) as z:
                if bytes(z["key"].tobytes()) != key:
                    raise ValueError("key mismatch (hash collision?)")
                payload = {
                    k: np.array(z[k])
                    for k in _PAYLOAD_KEYS
                    if k in z.files
                }
        except FileNotFoundError:
            with self._lock:
                self._disk.pop(key, None)
            return None
        except Exception as e:  # noqa: BLE001 — torn bundle: quarantine
            logger.warning(
                "quarantining corrupt kv tier bundle %s: %s", path, e
            )
            self._quarantine(path)
            with self._lock:
                self._disk.pop(key, None)
                self._set_gauges()
            return None
        self._count("disk_read")
        return payload

    def _quarantine(self, path: Path) -> None:
        try:
            cdir = path.parent / ".corrupt"
            cdir.mkdir(exist_ok=True)
            path.replace(cdir / path.name)
        except OSError:
            try:
                path.unlink(missing_ok=True)
            except OSError:
                pass
