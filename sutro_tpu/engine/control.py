"""SLO enforcement control plane: the actuator half of the monitor.

Rounds 10/13 built the *measurement* half — windowed SLO rules with
hysteresis, streamed doctor verdicts, per-tenant attribution. This
module closes the loop with three actuators:

1. **Per-tenant token-bucket admission.** Batch submits and
   interactive requests draw rows/tokens from per-``(tenant,
   priority)`` buckets sized off the jobstore quota tables. An empty
   bucket means 429/``QUOTA_EXCEEDED`` for interactive traffic and a
   *bounded* wait (then the same rejection) for batch submits.
   Terminal accounting refunds the unused part of a batch reserve, so
   a job that fails early does not burn its tenant's budget.

2. **Preemptive priority ladder** (``PriorityLadder``), generalizing
   the scheduler's ``_evict_for_interactive``: when a higher-priority
   job cannot admit, the scheduler may suspend a *lower*-priority
   job's decode rows through the paged-KV suspend/resume path.
   Anti-starvation aging promotes a waiting job one level per
   ``aging`` seconds, and a near soft-deadline (softdeadline.py)
   vetoes new preemptions — a suspended row that cannot resume before
   the watchdog fires would be lost work.

3. **Closed-loop autotuner.** Consumes each monitor tick (stats,
   alert transitions, doctor verdicts) and adjusts
   ``interactive_slots`` (live — the batcher reads it per admission)
   and ``decode_batch_size`` (next engine session — the batcher
   snapshots it at construction) in bounded steps with the same
   sustain/cooldown hysteresis shape as the SLO rules. Every move
   lands in a bounded audit trail and the
   ``sutro_autotune_adjustments_total`` counter.

Contract (mirrors faults.py / monitor.py):
- **Zero cost when off.** ``SUTRO_CONTROL=0`` / ``EngineConfig.control
  = None`` means the engine never constructs a ControlPlane; every
  hot-path hook is a ``None`` check. Batch results are bit-identical.
- **Degrades, never fails a job.** Any controller exception —
  including the injected fault sites ``control.admit`` and
  ``control.actuate`` — flips the plane to pass-through (buckets and
  ladder disabled), records a ``control_degraded`` event in the
  failure logs of in-flight jobs, and lets all traffic through.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import faults, softdeadline

logger = logging.getLogger("sutro.control")

# submit rejections carry this marker (PAPER.md quota semantics); the
# HTTP layers map it to 429
QUOTA_EXCEEDED = "QUOTA_EXCEEDED"

# seconds of soft-deadline headroom below which the ladder stops
# preempting: a suspended row needs the preemptor to finish before it
# can resume, and a process about to unwind cannot promise that
DEADLINE_GUARD_S = 30.0


def resolve_spec(config_control: Optional[str]) -> Optional[str]:
    """THE enablement rule: $SUTRO_CONTROL overrides when set (empty /
    "0" / "off" / "false" force OFF), else EngineConfig.control; None
    means the engine never constructs a ControlPlane."""
    import os

    env = os.environ.get("SUTRO_CONTROL")
    if env is not None:
        if env.strip().lower() in ("", "0", "off", "false", "none"):
            return None
        return env
    spec = config_control
    if spec is None or not str(spec).strip():
        return None
    if str(spec).strip().lower() in ("0", "off", "false", "none"):
        return None
    return str(spec)


@dataclasses.dataclass
class ControlConfig:
    """Parsed ``k=v,...`` control spec ("1"/"on"/"all" = defaults)."""

    window_s: float = 60.0      # bucket refill window: capacity/window
                                # is the sustained per-tenant rate
    quota_divisor: float = 1000.0  # default bucket capacity per window
                                # = per-job quota / this (quota tables
                                # are per-SUBMIT caps; the bucket is a
                                # sustained-rate limit)
    rows: Optional[float] = None    # absolute row capacity per window
                                    # (overrides the quota derivation)
    tokens: Optional[float] = None  # absolute token capacity per window
    wait_s: float = 2.0         # bounded-wait backpressure budget for
                                # batch submits (interactive never waits)
    itokens: float = 2048.0     # token reserve drawn per interactive
                                # request (coarse: prompt+completion
                                # are unknown at admission)
    aging_s: float = 30.0       # anti-starvation: a waiting job gains
                                # one priority level per this many
                                # seconds
    sustain: int = 2            # autotuner: ticks a signal must persist
                                # before acting (mirrors rule for_ticks)
    cooldown: int = 3           # autotuner: quiet ticks after a move
    settle: int = 5             # autotuner: signal-free ticks before
                                # stepping a knob back toward baseline
    slots_boost: int = 4        # max interactive_slots above baseline

    _KEYS = {
        "window": "window_s",
        "divisor": "quota_divisor",
        "rows": "rows",
        "tokens": "tokens",
        "wait": "wait_s",
        "itokens": "itokens",
        "aging": "aging_s",
        "sustain": "sustain",
        "cooldown": "cooldown",
        "settle": "settle",
        "slots_boost": "slots_boost",
    }

    @classmethod
    def parse(cls, spec: str) -> "ControlConfig":
        cfg = cls()
        body = spec.strip().lower()
        if body in ("1", "on", "true", "all", "default"):
            return cfg
        for part in body.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"control spec clause {part!r} is not k=v "
                    f"(known keys: {sorted(cls._KEYS)})"
                )
            k, v = part.split("=", 1)
            field = cls._KEYS.get(k.strip())
            if field is None:
                raise ValueError(
                    f"unknown control spec key {k.strip()!r} "
                    f"(known: {sorted(cls._KEYS)})"
                )
            cur = getattr(cfg, field)
            if isinstance(cur, int) and not isinstance(cur, bool):
                setattr(cfg, field, int(float(v)))
            else:
                setattr(cfg, field, float(v))
        return cfg


class TokenBucket:
    """Continuous-refill token bucket (monotonic clock, caller locks)."""

    __slots__ = ("capacity", "rate", "level", "_t")

    def __init__(self, capacity: float, window_s: float) -> None:
        self.capacity = max(1.0, float(capacity))
        self.rate = self.capacity / max(1e-6, float(window_s))
        self.level = self.capacity
        self._t = time.monotonic()

    def _refill(self, now: float) -> None:
        if now > self._t:
            self.level = min(
                self.capacity, self.level + (now - self._t) * self.rate
            )
        self._t = now

    def try_take(self, n: float, now: float) -> bool:
        self._refill(now)
        if self.level >= n:
            self.level -= n
            return True
        return False

    def time_until(self, n: float, now: float) -> float:
        """Seconds until ``n`` tokens could be taken (inf if n exceeds
        capacity — no wait will ever satisfy it)."""
        self._refill(now)
        if self.level >= n:
            return 0.0
        if n > self.capacity:
            return float("inf")
        return (n - self.level) / self.rate

    def put(self, n: float) -> None:
        self.level = min(self.capacity, self.level + n)


class PriorityLadder:
    """Scheduler-facing view of the preemption policy.

    The scheduler owns slot mechanics (it reuses the exact
    ``_evict_for_interactive`` unreserve/re-admit recipe); this class
    owns the *policy*: who may preempt whom, with anti-starvation
    aging and the soft-deadline veto."""

    def __init__(self, plane: "ControlPlane") -> None:
        self._plane = plane
        self._cfg = plane.cfg
        # first time each JobCtx.seq asked for admission — the aging
        # clock. Bounded: entries die with the batcher.
        self._first_seen: Dict[int, float] = {}

    def active(self) -> bool:
        return self._plane.enabled

    def effective_priority(self, ctx: Any, now: float) -> int:
        """Nominal priority minus one level per ``aging_s`` waited —
        an old P2 job eventually outranks a fresh P0 flood."""
        first = self._first_seen.setdefault(ctx.seq, now)
        aged = int((now - first) / max(1e-6, self._cfg.aging_s))
        return int(ctx.priority) - aged

    def may_preempt(self, preemptor: Any, victim: Any, now: float) -> bool:
        """True when ``preemptor`` (a JobCtx needing a slot) outranks
        ``victim`` (a JobCtx holding decode rows). Interactive ctxs
        (priority < 0) are handled by ``_evict_for_interactive`` and
        excluded on both sides here."""
        if not self._plane.enabled:
            return False
        if preemptor.priority < 0 or victim.priority < 0:
            return False
        rem = softdeadline.remaining_s()
        if rem is not None and rem < DEADLINE_GUARD_S:
            return False
        return self.effective_priority(
            preemptor, now
        ) < self.effective_priority(victim, now)

    def record(self, preemptor: Any, victim: Any) -> None:
        """Count one suspended row (telemetry + audit)."""
        self._plane.note_preemption(
            int(preemptor.priority), int(victim.priority)
        )

    def forget(self, ctx: Any) -> None:
        """Drop the aging entry once a job fully drains."""
        self._first_seen.pop(ctx.seq, None)


def _prio_label(p: int) -> str:
    """Bounded label domain for priority metrics: the ladder lives in a
    small integer window; anything outside collapses to one series so a
    caller passing arbitrary ints can't mint unbounded label values."""
    return str(int(p)) if -1 <= int(p) <= 8 else "other"


class ControlPlane:
    """Admission buckets + ladder policy + autotuner, one per engine."""

    def __init__(
        self,
        spec: str,
        *,
        ecfg: Any,
        jobs: Any = None,
        jobs_provider: Optional[
            Callable[[], List[Tuple[str, str]]]
        ] = None,
        tier_pools: Optional[Callable[[], List[Any]]] = None,
    ) -> None:
        self.cfg = ControlConfig.parse(spec)
        self.ecfg = ecfg
        self.jobs = jobs
        self._jobs_provider = jobs_provider
        self.enabled = True
        self.degraded_reason: Optional[str] = None
        self._lock = threading.Lock()
        # (tenant, priority_index) -> {"rows": bucket, "tokens": bucket}
        self._buckets: Dict[Tuple[str, int], Dict[str, TokenBucket]] = {}
        # job_id -> (tenant, prio_idx, rows_drawn, tokens_drawn): the
        # outstanding reserve, settled (refunded) at terminal status
        self._drawn: Dict[str, Tuple[str, int, float, float]] = {}
        self.ladder = PriorityLadder(self)
        # -- autotuner state ------------------------------------------
        self._base_slots = int(getattr(ecfg, "interactive_slots", 0))
        self._base_batch = int(getattr(ecfg, "decode_batch_size", 64))
        self._batch_step = max(8, self._base_batch // 4)
        # kv_tier_host_pages: bounded-notch growth off the doctor's
        # kv_pressure verdict. New pools read the knob from ecfg at
        # construction; live pools are pushed through ``tier_pools``.
        self._tier_pools = tier_pools
        self._base_kv_pages = int(
            getattr(ecfg, "kv_tier_host_pages", 4096)
        )
        self._kv_step = max(256, self._base_kv_pages // 4)
        self._sustain: Dict[str, int] = {}
        self._quiet = 0
        self._cooldown = 0
        self._audit: deque = deque(maxlen=128)
        self._audit_seq = 0
        self._rejections = 0
        self._preemptions = 0

    # -- degradation ---------------------------------------------------

    def _degrade(
        self, site: str, exc: BaseException,
        job_id: Optional[str] = None,
    ) -> None:
        """Pass-through, never fail a job: disable every actuator and
        leave a trail in the failure logs of the triggering job (when
        there is one) and every in-flight job."""
        self.enabled = False
        self.degraded_reason = f"{site}: {type(exc).__name__}: {exc}"
        logger.warning(
            "control plane degraded to pass-through at %s: %s",
            site, exc, exc_info=True,
        )
        if self.jobs is None:
            return
        targets = [] if job_id is None else [job_id]
        if self._jobs_provider is not None:
            try:
                targets.extend(
                    jid for jid, _status in self._jobs_provider()
                    if jid != job_id
                )
            except Exception as list_exc:  # noqa: BLE001
                logger.debug(
                    "control degradation trail: job listing failed: %s",
                    list_exc,
                )
        for jid in targets:
            try:
                self.jobs.append_failure_log(
                    jid,
                    {
                        "event": "control_degraded",
                        "site": site,
                        "error": f"{type(exc).__name__}: {exc}",
                    },
                )
            except Exception as log_exc:  # noqa: BLE001
                logger.debug(
                    "control degradation trail: %s: %s", jid, log_exc,
                )

    # -- admission -----------------------------------------------------

    def _bucket(
        self, tenant: str, prio_idx: int
    ) -> Dict[str, TokenBucket]:
        key = (tenant, prio_idx)
        b = self._buckets.get(key)
        if b is None:
            from .jobstore import DEFAULT_QUOTAS

            quotas = (
                self.jobs.get_quotas()
                if self.jobs is not None
                else [dict(q) for q in DEFAULT_QUOTAS]
            )
            q = quotas[min(max(prio_idx, 0), len(quotas) - 1)]
            cfg = self.cfg
            rows_cap = (
                cfg.rows
                if cfg.rows is not None
                else max(
                    1.0, float(q["row_quota"]) / cfg.quota_divisor
                )
            )
            tok_cap = (
                cfg.tokens
                if cfg.tokens is not None
                else max(
                    1.0, float(q["token_quota"]) / cfg.quota_divisor
                )
            )
            b = {
                "rows": TokenBucket(rows_cap, cfg.window_s),
                "tokens": TokenBucket(tok_cap, cfg.window_s),
            }
            self._buckets[key] = b
        return b

    def _reject_msg(
        self, tenant: str, what: str, need: float, wait_s: float
    ) -> str:
        return (
            f"{QUOTA_EXCEEDED}: tenant {tenant!r} {what} bucket empty "
            f"(need {need:g}, sustained rate exhausted; retry after "
            f"~{max(0.1, wait_s):.1f}s)"
        )

    def admit_batch(
        self,
        tenant: str,
        priority: int,
        rows: int,
        tokens: float,
        job_id: Optional[str] = None,
    ) -> Optional[str]:
        """Draw a batch submit's reserve from the tenant's buckets.

        Returns None on admit, else a ``QUOTA_EXCEEDED`` message. A
        draw that would succeed after a short refill waits for it —
        bounded by ``wait_s`` and the armed soft deadline — so bursty
        batch traffic sees backpressure before rejection."""
        if not self.enabled:
            return None
        try:
            faults.inject("control.admit", job=job_id)
            now = time.monotonic()
            need_rows = float(max(1, rows))
            need_tok = float(max(0, tokens))
            deadline = now + self._wait_budget()
            while True:
                with self._lock:
                    b = self._bucket(tenant, max(0, int(priority)))
                    row_wait = b["rows"].time_until(need_rows, now)
                    tok_wait = b["tokens"].time_until(need_tok, now)
                    wait = max(row_wait, tok_wait)
                    if wait <= 0.0:
                        b["rows"].try_take(need_rows, now)
                        b["tokens"].try_take(need_tok, now)
                        if job_id is not None:
                            self._drawn[job_id] = (
                                tenant, max(0, int(priority)),
                                need_rows, need_tok,
                            )
                        return None
                if now + wait > deadline:
                    self._count_rejection(tenant)
                    short = "row" if row_wait >= tok_wait else "token"
                    return self._reject_msg(
                        tenant, short,
                        need_rows if short == "row" else need_tok,
                        wait,
                    )
                time.sleep(min(wait, 0.05))
                now = time.monotonic()
        except Exception as e:  # noqa: BLE001 — pass-through contract
            self._degrade("control.admit", e, job_id=job_id)
            return None

    def admit_interactive(self, tenant: str) -> Optional[str]:
        """Latency-sensitive admission: one row + a coarse token
        reserve, no waiting — an empty bucket is an immediate 429."""
        if not self.enabled:
            return None
        try:
            faults.inject("control.admit", job=f"interactive:{tenant}")
            now = time.monotonic()
            with self._lock:
                b = self._bucket(tenant, 0)
                wait = max(
                    b["rows"].time_until(1.0, now),
                    b["tokens"].time_until(self.cfg.itokens, now),
                )
                if wait <= 0.0:
                    b["rows"].try_take(1.0, now)
                    b["tokens"].try_take(self.cfg.itokens, now)
                    return None
            self._count_rejection(tenant)
            return self._reject_msg(tenant, "interactive", 1.0, wait)
        except Exception as e:  # noqa: BLE001 — pass-through contract
            self._degrade("control.admit", e)
            return None

    def _wait_budget(self) -> float:
        budget = max(0.0, self.cfg.wait_s)
        rem = softdeadline.remaining_s()
        if rem is not None:
            # leave the guard window intact: waiting into the deadline
            # would trade a quota rejection for a dead process
            budget = min(budget, max(0.0, rem - DEADLINE_GUARD_S))
        return budget

    def on_terminal(self, rec: Any) -> None:
        """Terminal-accounting refill (called from JobStore.set_status
        via the ``on_terminal`` hook): give back the unused part of
        the reserve — all of it for a job that never ran, the
        token overage for one that finished under its estimate."""
        if not self.enabled:
            return
        try:
            drawn = self._drawn.pop(rec.job_id, None)
            if drawn is None:
                return
            tenant, prio_idx, rows, tokens = drawn
            status = getattr(rec, "status", "")
            used_tok = float(
                (getattr(rec, "input_tokens", 0) or 0)
                + (getattr(rec, "output_tokens", 0) or 0)
            )
            with self._lock:
                b = self._bucket(tenant, prio_idx)
                if status in ("FAILED", "CANCELLED") and used_tok <= 0:
                    # never ran: full refund, rows included
                    b["rows"].put(rows)
                    b["tokens"].put(tokens)
                elif used_tok < tokens:
                    b["tokens"].put(tokens - used_tok)
        except Exception as e:  # noqa: BLE001 — the terminal funnel
            # must never see a control error
            self._degrade("control.admit", e)

    def _count_rejection(self, tenant: str) -> None:
        self._rejections += 1
        from .. import telemetry

        if telemetry.ENABLED:
            telemetry.ADMISSION_REJECTIONS_TOTAL.inc(1.0, tenant)

    def note_preemption(self, from_prio: int, to_prio: int) -> None:
        self._preemptions += 1
        from .. import telemetry

        if telemetry.ENABLED:
            telemetry.PREEMPTIONS_TOTAL.inc(
                1.0, _prio_label(from_prio), _prio_label(to_prio)
            )

    # -- autotuner -----------------------------------------------------

    def on_monitor_tick(
        self,
        stats: Dict[str, Any],
        transitions: List[Dict[str, Any]],
        verdicts: Optional[Dict[str, Dict[str, Any]]],
        firing: List[str],
    ) -> None:
        """One closed-loop step, driven by the monitor's sampler.

        Inputs are the monitor's own artifacts: windowed stats, alert
        transitions, live doctor verdicts, and the currently-firing
        rule names. Hysteresis mirrors the SLO rules — act only on a
        signal sustained ``sustain`` ticks, then hold ``cooldown``
        ticks; after ``settle`` quiet ticks, step back toward the
        baseline config."""
        if not self.enabled:
            return
        try:
            faults.inject("control.actuate")
            names = set()
            for doc in (verdicts or {}).values():
                v = doc.get("verdict")
                if v:
                    names.add(str(v))
            signals = {
                "starved": (
                    "interactive_starved" in names
                    or "interactive_ttft_p99" in firing
                ),
                "roofline": "decode_below_roofline" in names,
                "hostbound": "host_bound_admit" in names,
                "kvpressure": "kv_pressure" in names,
            }
            any_signal = any(signals.values())
            for k, on in signals.items():
                self._sustain[k] = self._sustain.get(k, 0) + 1 if on else 0
            if self._cooldown > 0:
                self._cooldown -= 1
                self._quiet = 0 if any_signal else self._quiet + 1
                return
            acted = False
            if self._sustain.get("starved", 0) >= self.cfg.sustain:
                cur = int(self.ecfg.interactive_slots)
                new = min(self._base_slots + self.cfg.slots_boost, cur + 1)
                acted = self._apply(
                    "interactive_slots", cur, new, "interactive_starved"
                )
            elif self._sustain.get("kvpressure", 0) >= self.cfg.sustain:
                # tier thrash: widen the host tier so demoted pages
                # stay promotable instead of falling through to disk
                cur = int(
                    getattr(
                        self.ecfg, "kv_tier_host_pages",
                        self._base_kv_pages,
                    )
                )
                new = min(4 * self._base_kv_pages, cur + self._kv_step)
                acted = self._apply(
                    "kv_tier_host_pages", cur, new, "kv_pressure"
                )
                if acted:
                    self._push_kv_budget(new)
            elif self._sustain.get("hostbound", 0) >= self.cfg.sustain:
                # host-bound admit outranks roofline: shrinking the
                # batch relieves the host, growing it makes it worse
                cur = int(self.ecfg.decode_batch_size)
                new = max(8, cur - self._batch_step)
                acted = self._apply(
                    "decode_batch_size", cur, new, "host_bound_admit"
                )
            elif self._sustain.get("roofline", 0) >= self.cfg.sustain:
                cur = int(self.ecfg.decode_batch_size)
                new = min(2 * self._base_batch, cur + self._batch_step)
                acted = self._apply(
                    "decode_batch_size", cur, new, "decode_below_roofline"
                )
            if acted:
                self._cooldown = self.cfg.cooldown
                self._sustain.clear()
                self._quiet = 0
                return
            # settle: walk each knob one step back toward baseline
            # after a sustained quiet spell
            self._quiet = 0 if any_signal else self._quiet + 1
            if self._quiet >= self.cfg.settle:
                self._quiet = 0
                cur = int(self.ecfg.interactive_slots)
                if cur > self._base_slots:
                    self._apply(
                        "interactive_slots", cur, cur - 1, "settle"
                    )
                cur = int(self.ecfg.decode_batch_size)
                if cur != self._base_batch:
                    step = min(self._batch_step, abs(cur - self._base_batch))
                    new = cur - step if cur > self._base_batch else cur + step
                    self._apply("decode_batch_size", cur, new, "settle")
                cur = int(
                    getattr(
                        self.ecfg, "kv_tier_host_pages",
                        self._base_kv_pages,
                    )
                )
                if cur != self._base_kv_pages:
                    step = min(
                        self._kv_step, abs(cur - self._base_kv_pages)
                    )
                    new = (
                        cur - step
                        if cur > self._base_kv_pages
                        else cur + step
                    )
                    if self._apply(
                        "kv_tier_host_pages", cur, new, "settle"
                    ):
                        self._push_kv_budget(new)
        except Exception as e:  # noqa: BLE001 — pass-through contract
            self._degrade("control.actuate", e)

    def _push_kv_budget(self, pages: int) -> None:
        """Propagate a ``kv_tier_host_pages`` move to every live tier
        pool; pools constructed later read the knob off ecfg. Raises
        propagate to the actuate degrade path — a broken pool must not
        keep absorbing autotuner moves."""
        if self._tier_pools is None:
            return
        for pool in self._tier_pools():
            pool.set_host_budget(pages)

    def _apply(self, knob: str, cur: int, new: int, reason: str) -> bool:
        if new == cur:
            return False
        setattr(self.ecfg, knob, int(new))
        self._audit_seq += 1
        self._audit.append(
            {
                "seq": self._audit_seq,
                "unix": round(time.time(), 3),
                "knob": knob,
                "from": int(cur),
                "to": int(new),
                "reason": reason,
            }
        )
        from .. import telemetry

        if telemetry.ENABLED:
            telemetry.AUTOTUNE_ADJUSTMENTS_TOTAL.inc(1.0, knob)
        logger.info(
            "autotune: %s %d -> %d (%s)", knob, cur, new, reason
        )
        return True

    # -- introspection -------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /monitor`` enforcement sub-document."""
        with self._lock:
            buckets = {
                f"{tenant}/p{p}": {
                    "rows": round(b["rows"].level, 1),
                    "rows_capacity": b["rows"].capacity,
                    "tokens": round(b["tokens"].level, 1),
                    "tokens_capacity": b["tokens"].capacity,
                }
                for (tenant, p), b in self._buckets.items()
            }
        return {
            "enabled": self.enabled,
            "degraded_reason": self.degraded_reason,
            "window_s": self.cfg.window_s,
            "rejections": self._rejections,
            "preemptions": self._preemptions,
            "buckets": buckets,
            "autotune": {
                "baseline": {
                    "interactive_slots": self._base_slots,
                    "decode_batch_size": self._base_batch,
                    "kv_tier_host_pages": self._base_kv_pages,
                },
                "current": {
                    "interactive_slots": int(
                        getattr(self.ecfg, "interactive_slots", 0)
                    ),
                    "decode_batch_size": int(
                        getattr(self.ecfg, "decode_batch_size", 0)
                    ),
                    "kv_tier_host_pages": int(
                        getattr(
                            self.ecfg, "kv_tier_host_pages",
                            self._base_kv_pages,
                        )
                    ),
                },
                "audit": list(self._audit),
            },
        }
