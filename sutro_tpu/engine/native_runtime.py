"""ctypes binding to the native scheduler runtime (native/runtime.cpp).

Wraps the C++ page allocator + admission + dense step-state core behind
the same semantics as the pure-Python PageAllocator/slot bookkeeping in
engine/scheduler.py. The dense per-step arrays (last tokens, past
lengths, page tables, sampling params) are exposed as zero-copy numpy
views over the C++ buffers, so the scheduler's per-step slot-assembly
loop does no Python work.

Builds ``native/libsutro_runtime.so`` on demand (``make -C native``);
``is_available()`` is False when the toolchain is absent and the
scheduler falls back to pure Python. Set ``SUTRO_NATIVE_RUNTIME=0`` to
force the fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libsutro_runtime.so")
_lib = None
_lib_failed = False


def _load_lib():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    if os.environ.get("SUTRO_NATIVE_RUNTIME", "1") == "0":
        _lib_failed = True
        return None
    try:
        if not os.path.exists(os.path.join(_NATIVE_DIR, "runtime.cpp")):
            raise FileNotFoundError("native/runtime.cpp not present")
        # always run make: a no-op when the .so is fresh, a rebuild when
        # runtime.cpp changed (the artifact is not checked in)
        subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        lib = ctypes.CDLL(_LIB_PATH)
    except Exception:
        _lib_failed = True
        return None

    c_rt = ctypes.c_void_p
    i32, i64, f32 = ctypes.c_int32, ctypes.c_int64, ctypes.c_float
    p_i32 = ctypes.POINTER(i32)
    p_f32 = ctypes.POINTER(f32)

    lib.rt_create.restype = c_rt
    lib.rt_create.argtypes = [i32, i32, i32, i32, i64, i32]
    lib.rt_destroy.argtypes = [c_rt]
    lib.rt_free_page_count.restype = i32
    lib.rt_free_page_count.argtypes = [c_rt]
    lib.rt_inflight_tokens.restype = i64
    lib.rt_inflight_tokens.argtypes = [c_rt]
    lib.rt_active_count.restype = i32
    lib.rt_active_count.argtypes = [c_rt]
    lib.rt_try_admit.restype = i32
    lib.rt_try_admit.argtypes = [c_rt, i32, i32]
    lib.rt_try_admit_pfx.restype = i32
    lib.rt_try_admit_pfx.argtypes = [c_rt, i32, i32, i32, p_i32]
    lib.rt_alloc_pages.restype = i32
    lib.rt_alloc_pages.argtypes = [c_rt, i32, p_i32]
    lib.rt_free_pages.argtypes = [c_rt, i32, p_i32]
    lib.rt_reserve_pages.restype = i32
    lib.rt_reserve_pages.argtypes = [c_rt, i32, p_i32]
    lib.rt_arm_slot.argtypes = [c_rt, i32, i32, i32, f32, f32, i32]
    lib.rt_note_token.argtypes = [c_rt, i32, i32]
    lib.rt_note_bulk.argtypes = [c_rt, i32, i32, i32]
    lib.rt_release.argtypes = [c_rt, i32]
    lib.rt_emitted.restype = i32
    lib.rt_emitted.argtypes = [c_rt, i32]
    lib.rt_slot_npfx.restype = i32
    lib.rt_slot_npfx.argtypes = [c_rt, i32]
    lib.rt_pos.restype = i32
    lib.rt_pos.argtypes = [c_rt, i32]
    lib.rt_is_active.restype = i32
    lib.rt_is_active.argtypes = [c_rt, i32]
    for name, ptype in [
        ("rt_view_last", p_i32),
        ("rt_view_past_len", p_i32),
        ("rt_view_table", p_i32),
        ("rt_view_top_k", p_i32),
        ("rt_view_temp", p_f32),
        ("rt_view_top_p", p_f32),
    ]:
        fn = getattr(lib, name)
        fn.restype = ptype
        fn.argtypes = [c_rt]
    _lib = lib
    return _lib


def is_available() -> bool:
    return _load_lib() is not None


def _view(ptr, shape, dtype) -> np.ndarray:
    n = int(np.prod(shape))
    arr = np.ctypeslib.as_array(ptr, shape=(n,))
    out = arr.view(dtype).reshape(shape)
    return out


class NativeRuntime:
    """Slot/page/step-state manager backed by native/runtime.cpp.

    The ``last``/``past_len``/``table``/``temp``/``top_p``/``top_k``
    attributes are zero-copy views into C++ memory — always current, no
    per-step assembly."""

    def __init__(
        self,
        num_pages: int,
        num_slots: int,
        max_pages_per_seq: int,
        page_size: int,
        max_batch_tokens: int,
        max_context: int,
    ):
        lib = _load_lib()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._rt = lib.rt_create(
            num_pages, num_slots, max_pages_per_seq, page_size,
            max_batch_tokens, max_context,
        )
        self.num_slots = num_slots
        self.max_pages_per_seq = max_pages_per_seq
        self.last = _view(
            lib.rt_view_last(self._rt), (num_slots,), np.int32
        )
        self.past_len = _view(
            lib.rt_view_past_len(self._rt), (num_slots,), np.int32
        )
        self.table = _view(
            lib.rt_view_table(self._rt),
            (num_slots, max_pages_per_seq),
            np.int32,
        )
        self.temp = _view(
            lib.rt_view_temp(self._rt), (num_slots,), np.float32
        )
        self.top_p = _view(
            lib.rt_view_top_p(self._rt), (num_slots,), np.float32
        )
        self.top_k = _view(
            lib.rt_view_top_k(self._rt), (num_slots,), np.int32
        )

    def __del__(self):
        rt = getattr(self, "_rt", None)
        if rt:
            self._lib.rt_destroy(rt)
            self._rt = None

    # -- allocator/admission ------------------------------------------

    def try_admit(self, prompt_len: int, max_new_tokens: int) -> int:
        """Returns the admitted slot index or -1."""
        return int(
            self._lib.rt_try_admit(self._rt, prompt_len, max_new_tokens)
        )

    def try_admit_pfx(
        self, prompt_len: int, max_new_tokens: int, pfx_pages: List[int]
    ) -> int:
        """Admission with a job-wide shared KV prefix at the table head
        (the pages are referenced, not owned: release frees only the
        slot's own pages). Returns the slot index or -1."""
        arr = np.asarray(pfx_pages, np.int32)
        return int(
            self._lib.rt_try_admit_pfx(
                self._rt, prompt_len, max_new_tokens, len(arr),
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
        )

    def alloc_pages(self, n: int) -> Optional[List[int]]:
        """Job-scoped page block (shared-prefix KV); None when the pool
        cannot supply it. Return with ``free_pages``."""
        out = np.zeros((n,), np.int32)
        rc = self._lib.rt_alloc_pages(
            self._rt, n,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return [int(p) for p in out] if rc == 0 else None

    def free_pages(self, pages: List[int]) -> None:
        arr = np.asarray(pages, np.int32)
        self._lib.rt_free_pages(
            self._rt, len(arr),
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )

    def reserve_pages(self, pages: List[int]) -> bool:
        """Remove specific page ids from the free set (prefix-store
        pages held across sessions). Atomic; False when any id is not
        free — the runtime's free set is then untouched."""
        if not pages:
            return True
        arr = np.asarray(pages, np.int32)
        rc = self._lib.rt_reserve_pages(
            self._rt, len(arr),
            arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return int(rc) == 0

    def arm_slot(
        self, slot: int, pos: int, first_token: int,
        temperature: float, top_p: float, top_k: int,
    ) -> None:
        self._lib.rt_arm_slot(
            self._rt, slot, pos, first_token,
            float(temperature), float(top_p), int(top_k),
        )

    def note_token(self, slot: int, tok: int) -> None:
        self._lib.rt_note_token(self._rt, slot, int(tok))

    def note_bulk(self, slot: int, last_tok: int, n: int) -> None:
        """n accepted tokens ending with last_tok — one ctypes crossing
        per window instead of one per token."""
        self._lib.rt_note_bulk(self._rt, slot, int(last_tok), int(n))

    def release(self, slot: int) -> None:
        self._lib.rt_release(self._rt, slot)

    # -- introspection -------------------------------------------------

    @property
    def free_count(self) -> int:
        return int(self._lib.rt_free_page_count(self._rt))

    @property
    def inflight_tokens(self) -> int:
        return int(self._lib.rt_inflight_tokens(self._rt))

    @property
    def active_count(self) -> int:
        return int(self._lib.rt_active_count(self._rt))

    def is_active(self, slot: int) -> bool:
        return bool(self._lib.rt_is_active(self._rt, slot))

    def pos(self, slot: int) -> int:
        return int(self._lib.rt_pos(self._rt, slot))

    def emitted(self, slot: int) -> int:
        return int(self._lib.rt_emitted(self._rt, slot))

    def slot_pages(self, slot: int) -> List[int]:
        """Pages OWNED by this slot (freed by ``release``) — with a
        shared prefix active, the job-owned prefix pages at the table
        head are excluded (freeing them per slot would double-free job
        pages into the pool)."""
        npfx = int(self._lib.rt_slot_npfx(self._rt, slot))
        row = self.table[slot]
        return [int(p) for p in row[npfx:] if p != 0]


def maybe_native_runtime(
    num_pages: int,
    num_slots: int,
    max_pages_per_seq: int,
    page_size: int,
    max_batch_tokens: int,
    max_context: int,
) -> Optional[NativeRuntime]:
    if not is_available():
        return None
    return NativeRuntime(
        num_pages, num_slots, max_pages_per_seq, page_size,
        max_batch_tokens, max_context,
    )
