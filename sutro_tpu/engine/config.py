"""Engine configuration.

No analogue exists in the reference (its engine is the remote service,
SURVEY §0); this is the "config file + kwargs override" layer SURVEY §5.6
prescribes for the TPU build: mesh shape, dtype policy, KV paging, and
batching budgets, resolved from defaults <- ~/.sutro/engine.json <- kwargs.
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass
class EngineConfig:
    # --- device mesh -------------------------------------------------------
    # Axis sizes; 0 => infer from available devices (tp gets devices not
    # claimed by ep, remainder folds into dp). Defaults are explicit
    # single-device: TP/EP need model-divisibility knowledge, so spreading
    # over all chips is an explicit choice (engine.json or kwargs), not a
    # surprise. Axes: ("data", "seq", "expert", "model") — DP over
    # DCN/outer, SP/EP/TP over ICI (SURVEY §5.8). ``sp`` > 1 enables
    # ring-attention sequence parallelism for long-prompt prefill
    # (ops/ring_attention.py).
    dp: int = 1
    tp: int = 1
    ep: int = 1
    sp: int = 1
    pp: int = 1                     # pipeline stages (parallel/pipeline.py)
    pp_microbatches: int = 0        # 0 => min(pp, batch)
    # --- dtype policy ------------------------------------------------------
    activation_dtype: str = "bfloat16"
    param_dtype: str = "bfloat16"
    quantize: Optional[str] = None  # "int8" => weight-only per-channel
                                    # quantization of the projection
                                    # matrices (ops/quant.py)
    kv_quantize: Optional[str] = None  # "int8" => KV cache pages stored
                                    # int8 with per-token scales: halves
                                    # decode HBM traffic and doubles
                                    # page capacity (kvcache.write_kv
                                    # quantizes, the paged kernel /
                                    # gather fallback dequantize).
                                    # Works under dp/tp/sp/ep meshes
                                    # (scales are full-KD amax, hence
                                    # shard-invariant and replicated);
                                    # pp only warns+ignores (pipeline
                                    # decode carries no scale pools)
    # --- KV cache / batching ----------------------------------------------
    kv_page_size: int = 64          # tokens per KV page
    max_pages_per_seq: int = 128    # => max context 8192 by default
    decode_batch_size: int = 64     # fixed decode slot count (static shapes)
    prefill_chunk: int = 512        # prompts longer than this prefill in
                                    # fixed-size chunks (runner.prefill)
    prefill_batch_size: int = 8     # short rows prefilled per device
                                    # dispatch (runner.prefill_batch)
    interactive_slots: int = 0      # reserved-slot budget for the online
                                    # serving tier (serving/gateway.py):
                                    # up to this many decode slots may be
                                    # taken by interactive /v1 requests,
                                    # preempting batch rows when the
                                    # batch is full (the preempted row
                                    # re-admits row-granularly). 0 = the
                                    # serving endpoints 404 and the batch
                                    # path is bit-identical to before
    max_batch_tokens: int = 32768   # admission budget: sum of in-flight
                                    # worst-case totals (scheduler._reserve)
    max_model_len: int = 8192
    decode_multi_step: int = 8      # decode steps fused into one device
                                    # program when no row needs host-side
                                    # FSM masks/seeds (runner.decode_multi);
                                    # amortizes dispatch+fetch latency.
                                    # NOTE: bench.py's lockstep loop
                                    # measured MULTI=16 fastest (PERF.md),
                                    # but the SCHEDULER pays min-cap
                                    # all-or-nothing tails that grow with
                                    # this value — flip only after the
                                    # chip_validation.py sweep + a
                                    # scheduler-path (bench_e2e
                                    # SUTRO_E2E_MULTI) A/B agree
    decode_lookahead: int = 2       # fused windows in flight at once on the
                                    # unconstrained decode path: window k+1
                                    # chains off window k's device-resident
                                    # tokens, so the host<->device round
                                    # trip is hidden behind device compute
                                    # (scheduler pipelined windows); 1 =
                                    # synchronous (process before dispatch)
    spec_ngram_draft: int = 0       # >0 enables prompt-lookup (n-gram)
                                    # speculative decoding for plain
                                    # GREEDY unconstrained rows: draft up
                                    # to this many tokens from the row's
                                    # own prompt/output history and
                                    # verify them in ONE parallel forward
                                    # (classify rationales echo prompt
                                    # text heavily). Exact for greedy.
                                    # Default OFF: the verify path is
                                    # host-synchronous, so under a
                                    # high-RTT tunnel the pipelined
                                    # fused windows win unless the
                                    # acceptance rate is high — flip
                                    # per the chip A/B (bench_e2e
                                    # SUTRO_E2E_SPEC)
    constrain_fastforward: int = 16  # FSM fast-forward ("jump
                                    # decoding") width: when a schema's
                                    # FSM forces exactly one next token
                                    # (scaffold regions like
                                    # '{"field": "'), peel up to this
                                    # many forced tokens host-side and
                                    # commit them through ONE parallel
                                    # verify forward instead of
                                    # step-by-step windows that reject
                                    # their unmasked samples there.
                                    # Exact for greedy constrained rows
                                    # (forced tokens are
                                    # model-independent; the bonus
                                    # token follows the speculative
                                    # window's accept rule). 0 = off
    prefill_piggyback: bool = True  # Sarathi-style chunked-prefill
                                    # interleave: a long prompt admits as
                                    # a PREFILLING slot that advances one
                                    # prefill chunk per scheduler
                                    # iteration while the active rows
                                    # keep decoding — instead of the
                                    # whole batch stalling for the full
                                    # multi-chunk prefill
    prefix_split: bool = False      # Hydragen-style split decode over
                                    # the shared prefix (Pallas path
                                    # only): member rows' prefix
                                    # attention is computed ONCE per
                                    # step for the whole batch (one HBM
                                    # read of the shared pages per
                                    # layer instead of one per row) and
                                    # injected as the paged kernel's
                                    # initial online-softmax carry
                                    # (ops/pallas_paged.py). Same f32
                                    # math, different summation order —
                                    # last-ulp differences only.
                                    # Default OFF until the chip A/B
                                    # (bench_e2e SUTRO_PREFIX_SPLIT)
    prefix_cache: bool = True       # shared-prefix KV reuse: a job whose
                                    # rows share a common token prefix
                                    # (templates send one system prompt
                                    # for every row) prefills that prefix
                                    # ONCE into page-aligned shared pages;
                                    # slots reference them read-only and
                                    # prefill only their own suffix
                                    # (scheduler._setup_prefix)
    prefix_store: bool = True       # engine-lifetime radix prefix store
                                    # (engine/prefixstore.py): page-
                                    # aligned template shells stay
                                    # resident in the paged KV pool
                                    # ACROSS jobs, co-batched jobs,
                                    # resumes, and interactive requests
                                    # — a repeated shell prefills only
                                    # its novel tail. Refcount-pinned
                                    # pages, LRU eviction under
                                    # allocation pressure.
                                    # $SUTRO_PREFIX_STORE overrides when
                                    # set ("0"/"off" forces off); off =
                                    # bit-identical to the per-job
                                    # prefix_cache path
    kv_tiers: bool = False          # tiered paged-KV pool (engine/
                                    # kvtier.py): HBM -> pinned host RAM
                                    # -> disk. Cold unpinned prefix-store
                                    # leaves DEMOTE to an int8 host tier
                                    # instead of evicting; preemption
                                    # victims hibernate their pages and
                                    # resume by page-upload instead of
                                    # full re-prefill; session-id chat
                                    # checkpoints idle conversations down
                                    # the tiers. $SUTRO_KV_TIERS
                                    # overrides when set ("0"/"off"
                                    # forces off); off = bit-identical,
                                    # ZERO tier ops (tests/test_kv_tiers)
    kv_tier_host_pages: int = 4096  # host-tier budget in KV pages
                                    # (int8: ~page_size*KD bytes/page/
                                    # layer); overflow spills to disk
    kv_tier_disk: bool = True       # disk tier under sutro_home()/
                                    # kvtier (jobstore partial-store
                                    # idiom: atomic rename, torn bundles
                                    # quarantined); off = host-only
    tokenize_threads: int = 0       # >1 splits batched prompt encodes
                                    # across a thread pool — only pays
                                    # for tokenizers whose encode_batch
                                    # releases the GIL (HF rust); the
                                    # byte tokenizer ignores extra
                                    # threads profitably at 0
    # --- generation defaults ----------------------------------------------
    max_new_tokens: int = 1024
    temperature: float = 0.7
    top_p: float = 0.95
    top_k: int = 0                  # 0 = disabled
    # --- robustness / failure domains (engine/faults.py, FAILURES.md) ------
    fault_plan: Optional[str] = None    # deterministic fault-injection
                                        # plan (DSL/JSON); None falls back
                                        # to $SUTRO_FAULT_PLAN; empty/off
                                        # means ZERO added work per row
    control: Optional[str] = None       # SLO enforcement control plane
                                        # (engine/control.py): "1"/"on"
                                        # for defaults, or "k=v,..."
                                        # (window=60,wait=2,aging=30,...).
                                        # $SUTRO_CONTROL overrides when
                                        # set ("0"/"off" forces off).
                                        # None/off = ZERO added work and
                                        # bit-identical batch results
    row_retries: int = 2                # per-row failure domain: a row
                                        # whose decode/constrain raises is
                                        # re-admitted as a fresh request up
                                        # to this many times, then
                                        # quarantined into an error-column
                                        # result (the job still SUCCEEDs)
    io_retries: int = 4                 # bounded attempts for transient
                                        # jobstore I/O (partial flush,
                                        # streamed finalize)
    io_backoff_base: float = 0.05       # first-retry backoff (seconds);
                                        # doubles per attempt with
                                        # deterministic jitter, capped at
                                        # io_backoff_cap
    io_backoff_cap: float = 2.0
    dp_stall_timeout: float = 600.0     # dp coordinator: seconds of
                                        # silence from a connected rank
                                        # before it is declared stalled
                                        # (0 disables the watchdog).
                                        # $SUTRO_DP_STALL_TIMEOUT
                                        # overrides when set; must be
                                        # >= 0 (engine/dphost.py
                                        # configure_channel)
    dp_heartbeat: float = 20.0          # dp worker liveness beacon
                                        # period in seconds (0 disables;
                                        # $SUTRO_DP_HEARTBEAT overrides;
                                        # must be >= 0)
    # --- runtime -----------------------------------------------------------
    use_pallas: Optional[bool] = None   # None => auto (TPU yes, CPU no)
    weights_dir: Optional[str] = None   # local HF-style checkpoint root
    seed: int = 0
    profile_dir: Optional[str] = None   # capture per-job jax.profiler
                                        # traces here (engine/profiling.py)

    def resolved_mesh(
        self, n_devices: int
    ) -> Tuple[int, int, int, int, int]:
        """Resolve (dp, pp, sp, ep, tp) against the actual device count:
        tp gets what's specified (default: all devices not claimed by
        ep/sp/pp), remaining devices fold into dp."""
        pp = self.pp or 1
        sp = self.sp or 1
        ep = self.ep or 1
        tp = self.tp or max(1, n_devices // (ep * sp * pp))
        dp = self.dp or max(1, n_devices // (tp * ep * sp * pp))
        if dp * pp * sp * ep * tp > n_devices:
            raise ValueError(
                f"Mesh dp*pp*sp*ep*tp={dp * pp * sp * ep * tp} exceeds "
                f"{n_devices} devices"
            )
        return dp, pp, sp, ep, tp

    def max_context(self) -> int:
        return min(self.max_model_len, self.kv_page_size * self.max_pages_per_seq)


def sutro_home() -> Path:
    """THE resolution rule for the sutro state directory (one
    definition: load_engine_config, validation.py, and the compile
    cache must never disagree on where sutro-home is)."""
    return Path(os.environ.get("SUTRO_HOME", Path.home() / ".sutro"))


_CACHE_ENABLED = False


def enable_compile_cache() -> None:
    """Point JAX's persistent compilation cache at a durable directory
    (idempotent; opt out with SUTRO_COMPILE_CACHE=0 — tests/conftest.py
    does, so test runs neither pollute ~/.sutro nor latch the cache to
    a soon-deleted pytest tmp dir).

    Every engine process — the HTTP daemon, bench subprocesses, the
    chip-validation queue's per-case isolation, DP workers — compiles
    the same decode/prefill programs; on a TPU behind a slow tunnel
    each first compile costs 20-120 s. The on-disk cache (content-
    addressed, a stock JAX feature) makes every process after the
    first load the executable in seconds. Respects an explicit
    jax_compilation_cache_dir (set via jax config or the
    JAX_COMPILATION_CACHE_DIR env var, which JAX binds at import)."""
    global _CACHE_ENABLED
    if _CACHE_ENABLED or os.environ.get("SUTRO_COMPILE_CACHE") == "0":
        return
    _CACHE_ENABLED = True
    import jax

    if jax.config.jax_compilation_cache_dir:
        return  # user already chose a cache location
    if (
        jax.default_backend() in ("cpu",)
        and os.environ.get("SUTRO_COMPILE_CACHE") != "1"
    ):
        # XLA:CPU AOT cache entries embed the compiling host's machine
        # features, and feature detection can differ between processes
        # on the same box (observed here: '+prefer-no-scatter ...
        # could lead to execution errors such as SIGILL' on every
        # cross-process load). CPU caching is therefore explicit
        # opt-in (SUTRO_COMPILE_CACHE=1); TPU executables target the
        # accelerator and don't carry host-CPU features.
        return
    path = sutro_home() / "xla_cache"
    try:
        path.mkdir(parents=True, exist_ok=True)
        # threshold FIRST: if the dir update below fails the config is
        # untouched, and a retry can't mistake our half-applied state
        # for a user-chosen cache location
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 2.0
        )
        jax.config.update("jax_compilation_cache_dir", str(path))
    except Exception:
        _CACHE_ENABLED = False  # cache is an optimization, never fatal


def load_engine_config(**overrides: Any) -> EngineConfig:
    """defaults <- $SUTRO_HOME/engine.json <- explicit kwargs."""
    cfg: Dict[str, Any] = {}
    path = sutro_home() / "engine.json"
    if path.exists():
        try:
            cfg.update(json.loads(path.read_text()))
        except Exception:
            pass
    cfg.update({k: v for k, v in overrides.items() if v is not None})
    fields = {f.name for f in dataclasses.fields(EngineConfig)}
    return EngineConfig(**{k: v for k, v in cfg.items() if k in fields})
