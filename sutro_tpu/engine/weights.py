"""HF safetensors checkpoint -> engine parameter pytree.

The reference never touches weights (models live server-side; SURVEY §2.3).
Here local checkpoint dirs (``EngineConfig.weights_dir/<engine_key>/``)
holding standard HuggingFace safetensors shards are mapped into the
scan-stacked pytree layout of models/transformer.py:

- per-layer tensors are stacked on a leading layer axis,
- projection matrices are transposed to [in, out] (HF stores [out, in]) so
  the forward is plain ``x @ w`` on the MXU,
- dtype-cast to the engine param dtype (bfloat16 by default),
- shapes validated against the ModelConfig before any device transfer.

Loading is lazy per-tensor (safetensors mmap) so host RSS stays ~one
tensor; sharded device placement happens in the runner via NamedSharding.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..models.configs import ModelConfig
from .config import EngineConfig


class _ShardIndex:
    """name -> (file, loader) over one or many .safetensors shards."""

    def __init__(self, ckpt_dir: str):
        from safetensors import safe_open

        self._open = safe_open
        self.dir = ckpt_dir
        self.files: Dict[str, str] = {}
        index_path = os.path.join(ckpt_dir, "model.safetensors.index.json")
        if os.path.exists(index_path):
            index = json.loads(open(index_path).read())
            for name, fname in index["weight_map"].items():
                self.files[name] = os.path.join(ckpt_dir, fname)
        else:
            for fname in sorted(os.listdir(ckpt_dir)):
                if fname.endswith(".safetensors"):
                    path = os.path.join(ckpt_dir, fname)
                    with safe_open(path, framework="np") as f:
                        for name in f.keys():
                            self.files[name] = path
        self._handles: Dict[str, Any] = {}

    def __contains__(self, name: str) -> bool:
        return name in self.files

    def get(self, name: str) -> np.ndarray:
        path = self.files[name]
        if path not in self._handles:
            self._handles[path] = self._open(path, framework="np")
        return self._handles[path].get_tensor(name)

    def names(self) -> List[str]:
        return list(self.files)


def _first(idx: _ShardIndex, *names: str) -> Optional[str]:
    for n in names:
        if n in idx:
            return n
    return None


def load_checkpoint(
    ckpt_dir: str, mcfg: ModelConfig, ecfg: EngineConfig
) -> Dict[str, Any]:
    """Load + remap an HF checkpoint for any supported family."""
    idx = _ShardIndex(ckpt_dir)
    dtype = jnp.dtype(ecfg.param_dtype)
    L = mcfg.num_layers

    def resolve(name: str) -> str:
        """Embedding-model checkpoints saved from the bare trunk (e.g.
        Qwen3-Embedding's Qwen3Model) drop the ``model.`` prefix."""
        if name in idx:
            return name
        if name.startswith("model.") and name[6:] in idx:
            return name[6:]
        return name

    def get(name: str, transpose: bool = False) -> np.ndarray:
        arr = idx.get(resolve(name))
        if transpose:
            arr = np.ascontiguousarray(arr.T)
        return arr

    def stack(
        fmt: str | Callable[[int], str], transpose: bool = False
    ) -> jnp.ndarray:
        outs = []
        for i in range(L):
            name = fmt(i) if callable(fmt) else fmt.format(i=i)
            outs.append(get(name, transpose))
        return jnp.asarray(np.stack(outs), dtype)

    def maybe_stack(fmt: str, transpose: bool = False) -> Optional[jnp.ndarray]:
        if resolve(fmt.format(i=0)) in idx:
            return stack(fmt, transpose)
        return None

    p = "model.layers.{i}."
    layers: Dict[str, Any] = {
        "attn_norm": stack(p + "input_layernorm.weight"),
        "wq": stack(p + "self_attn.q_proj.weight", transpose=True),
        "wk": stack(p + "self_attn.k_proj.weight", transpose=True),
        "wv": stack(p + "self_attn.v_proj.weight", transpose=True),
        "wo": stack(p + "self_attn.o_proj.weight", transpose=True),
    }
    if mcfg.attn_bias:
        layers["bq"] = stack(p + "self_attn.q_proj.bias")
        layers["bk"] = stack(p + "self_attn.k_proj.bias")
        layers["bv"] = stack(p + "self_attn.v_proj.bias")
        layers["bo"] = stack(p + "self_attn.o_proj.bias")
    if mcfg.qk_norm:
        layers["q_norm"] = stack(p + "self_attn.q_norm.weight")
        layers["k_norm"] = stack(p + "self_attn.k_norm.weight")
    if mcfg.attention_sink:
        layers["sink"] = stack(p + "self_attn.sinks")

    if mcfg.post_norms:
        # Gemma3 norm quartet
        layers["post_attn_norm"] = stack(p + "post_attention_layernorm.weight")
        layers["mlp_norm"] = stack(p + "pre_feedforward_layernorm.weight")
        layers["post_mlp_norm"] = stack(p + "post_feedforward_layernorm.weight")
    else:
        layers["mlp_norm"] = stack(p + "post_attention_layernorm.weight")

    if mcfg.moe_experts:
        E = mcfg.moe_experts
        router = maybe_stack(p + "mlp.gate.weight", transpose=True)
        if router is None:
            router = maybe_stack(p + "mlp.router.weight", transpose=True)
        if router is None:
            raise KeyError("No MoE router weight found in checkpoint")
        layers["router"] = router

        def stack_experts(sub: str) -> jnp.ndarray:
            outs = []
            for i in range(L):
                per = []
                for e in range(E):
                    name = f"model.layers.{i}.mlp.experts.{e}.{sub}.weight"
                    per.append(np.ascontiguousarray(idx.get(name).T))
                outs.append(np.stack(per))
            return jnp.asarray(np.stack(outs), dtype)

        probe = f"model.layers.0.mlp.experts.0.gate_proj.weight"
        if probe in idx:
            layers["we_gate"] = stack_experts("gate_proj")
            layers["we_up"] = stack_experts("up_proj")
            layers["we_down"] = stack_experts("down_proj")
        else:
            # gpt-oss fused layout: experts.gate_up_proj [E, H, 2F] with
            # gate/up interleaved on the last axis (+ biases [E, 2F]),
            # experts.down_proj [E, F, H] (+ bias [E, H])
            gu, down = [], []
            for i in range(L):
                gu.append(idx.get(f"model.layers.{i}.mlp.experts.gate_up_proj"))
                down.append(idx.get(f"model.layers.{i}.mlp.experts.down_proj"))
            gu_arr = np.stack(gu)  # [L, E, H, 2F]
            layers["we_gate"] = jnp.asarray(gu_arr[..., 0::2], dtype)
            layers["we_up"] = jnp.asarray(gu_arr[..., 1::2], dtype)
            layers["we_down"] = jnp.asarray(np.stack(down), dtype)
        if mcfg.moe_bias:
            rb = maybe_stack(p + "mlp.router.bias")
            if rb is None:
                rb = maybe_stack(p + "mlp.gate.bias")
            if rb is not None:
                layers["router_b"] = rb
            gub_probe = "model.layers.0.mlp.experts.gate_up_proj_bias"
            if gub_probe in idx:
                gub = np.stack(
                    [
                        idx.get(
                            f"model.layers.{i}.mlp.experts.gate_up_proj_bias"
                        )
                        for i in range(L)
                    ]
                )  # [L, E, 2F]
                layers["we_gate_b"] = jnp.asarray(gub[..., 0::2], dtype)
                layers["we_up_b"] = jnp.asarray(gub[..., 1::2], dtype)
                layers["we_down_b"] = stack(
                    p + "mlp.experts.down_proj_bias"
                )
    else:
        layers["w_gate"] = stack(p + "mlp.gate_proj.weight", transpose=True)
        layers["w_up"] = stack(p + "mlp.up_proj.weight", transpose=True)
        layers["w_down"] = stack(p + "mlp.down_proj.weight", transpose=True)

    params: Dict[str, Any] = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dtype),
        "final_norm": jnp.asarray(get("model.norm.weight"), dtype),
        "layers": layers,
    }
    if not mcfg.tie_embeddings and mcfg.head == "lm":
        name = _first(idx, "lm_head.weight")
        if name:
            params["lm_head"] = jnp.asarray(get(name, transpose=True), dtype)

    _validate(params, mcfg)
    return params


def _validate(params: Dict[str, Any], mcfg: ModelConfig) -> None:
    H, L = mcfg.hidden_size, mcfg.num_layers
    checks = {
        "embed": (mcfg.vocab_size, H),
        "layers.wq": (L, H, mcfg.q_size),
        "layers.wk": (L, H, mcfg.kv_size),
        "layers.wo": (L, mcfg.q_size, H),
    }
    for path, want in checks.items():
        node: Any = params
        for part in path.split("."):
            node = node[part]
        if tuple(node.shape) != want:
            raise ValueError(
                f"Checkpoint shape mismatch at {path}: got {tuple(node.shape)}, "
                f"want {want} for model {mcfg.name}"
            )
