"""Progress/token metrics bus.

Engine-side producer of the reference's NDJSON progress protocol
(/root/reference/sutro/sdk.py:331-367): ``{"update_type": "progress",
"result": <rows_done>}`` and ``{"update_type": "tokens", "result":
{input_tokens, output_tokens, total_tokens_processed_per_second}}``.
The reference consumes this over a long-lived HTTP stream; here the bus is
an in-process, thread-safe pub/sub keyed by job id, with history retained
so a late ``attach`` (reference sdk.py:800-911) sees current totals
immediately. Token updates may be partial dicts — consumers must merge
monotonically (sdk.py:354-363) — and the bus preserves that contract.

Delivery is CONFLATING: each subscriber holds at most one pending
update per update_type (progress keeps the max, token dicts merge), so
a producer's publish is O(subscribers) pointer work regardless of how
far behind a consumer is, and a slow consumer's backlog is O(1) instead
of an unbounded queue — a 1M-row job cannot out-produce its progress
stream. Consumers see every MONOTONIC milestone coalesced, not every
intermediate value, which is exactly the NDJSON progress contract.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from .stageframes import stage_progress_frame


class _Sub:
    """One subscriber's conflated mailbox (O(1) pending state)."""

    __slots__ = ("cond", "progress", "tokens", "stages", "done")

    def __init__(self, cond: threading.Condition) -> None:
        self.cond = cond
        self.progress: Optional[int] = None
        self.tokens: Optional[Dict[str, Any]] = None
        # stage-graph per-stage rollup: conflates by dict-merge keyed on
        # stage name (each stage's entry replaces wholesale — per-stage
        # counts are monotonic, so latest-wins is the milestone contract)
        self.stages: Optional[Dict[str, Any]] = None
        self.done = False


class JobMetrics:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.latest_tokens: Dict[str, Any] = {}
        self.latest_stages: Dict[str, Any] = {}
        self.rows_completed = 0
        self.done = False
        self._subscribers: List[_Sub] = []

    def progress(self, rows_completed: int) -> None:
        with self.lock:
            self.rows_completed = rows_completed
            for s in self._subscribers:
                # conflate: later counts replace (progress is monotonic)
                if s.progress is None or rows_completed > s.progress:
                    s.progress = rows_completed
                s.cond.notify_all()

    def tokens(self, result: Dict[str, Any]) -> None:
        with self.lock:
            self.latest_tokens.update(result)
            for s in self._subscribers:
                if s.tokens is None:
                    s.tokens = dict(result)
                else:  # partial dicts merge monotonically (contract)
                    s.tokens.update(result)
                s.cond.notify_all()

    def stages(self, result: Dict[str, Any]) -> None:
        """Publish a per-stage progress rollup ``{stage_name: {...}}``.

        Conflating like :meth:`tokens` — a slow NDJSON consumer sees the
        freshest per-stage counters, not every intermediate chunk."""
        with self.lock:
            self.latest_stages.update(result)
            for s in self._subscribers:
                if s.stages is None:
                    s.stages = dict(result)
                else:
                    s.stages.update(result)
                s.cond.notify_all()

    def finish(self) -> None:
        with self.lock:
            self.done = True
            for s in self._subscribers:
                s.done = True
                s.cond.notify_all()

    def subscribe(self) -> Iterator[Dict[str, Any]]:
        """Yields updates until the job finishes. Starts with a snapshot of
        current totals so mid-run attach shows correct state. Pending
        updates drain before the done sentinel is honored, so the final
        progress count is always delivered."""
        cond = threading.Condition(self.lock)
        sub = _Sub(cond)
        with self.lock:
            snapshot_rows = self.rows_completed
            snapshot_tokens = dict(self.latest_tokens)
            snapshot_stages = dict(self.latest_stages)
            already_done = self.done
            self._subscribers.append(sub)
        try:
            yield {"update_type": "progress", "result": snapshot_rows}
            if snapshot_tokens:
                yield {"update_type": "tokens", "result": snapshot_tokens}
            if snapshot_stages:
                # typed wire frame (engine/stageframes.py): carries
                # update_type so pre-stage-graph readers skip it
                yield stage_progress_frame(snapshot_stages)
            if already_done:
                return
            while True:
                with self.lock:
                    while (
                        sub.progress is None
                        and sub.tokens is None
                        and sub.stages is None
                        and not sub.done
                    ):
                        cond.wait()
                    prog, toks, done = sub.progress, sub.tokens, sub.done
                    stgs = sub.stages
                    sub.progress = None
                    sub.tokens = None
                    sub.stages = None
                if prog is not None:
                    yield {"update_type": "progress", "result": prog}
                if toks is not None:
                    yield {"update_type": "tokens", "result": toks}
                if stgs is not None:
                    yield stage_progress_frame(stgs)
                if done:
                    return
        finally:
            with self.lock:
                if sub in self._subscribers:
                    self._subscribers.remove(sub)


class BatchedProgress:
    """Row-progress publisher batched by completion count — THE one
    batching rule for both the embedding and generation paths (the
    embedding loop used to hand-roll this; a 1M-row job must not pay
    one bus publish per row). ``update`` publishes at most once per
    ``every_rows`` completions; ``flush`` publishes unconditionally
    (terminal counts must always land)."""

    def __init__(self, jm: JobMetrics, every_rows: int) -> None:
        self.jm = jm
        self.every = max(int(every_rows), 1)
        self._last = -1

    def update(self, rows_completed: int) -> None:
        if rows_completed - self._last >= self.every:
            self._last = rows_completed
            self.jm.progress(rows_completed)

    def flush(self, rows_completed: int) -> None:
        self._last = rows_completed
        self.jm.progress(rows_completed)


class MetricsBus:
    def __init__(self) -> None:
        self._jobs: Dict[str, JobMetrics] = {}
        self._lock = threading.Lock()

    def job(self, job_id: str) -> JobMetrics:
        with self._lock:
            if job_id not in self._jobs:
                self._jobs[job_id] = JobMetrics()
            return self._jobs[job_id]

    def drop(self, job_id: str) -> None:
        with self._lock:
            self._jobs.pop(job_id, None)


class Throughput:
    """Per-chip tokens/sec estimator (BASELINE.md tracked metric).

    The clock anchors at :meth:`start` — implicitly the first
    :meth:`add`/:meth:`note_total` — NOT at construction: a session
    builds its estimator before tokenize/compile/prefill, and counting
    that dead time deflated early readings after a long compile (the
    rate then crept up for the whole job instead of being honest from
    the first window)."""

    def __init__(self, n_chips: int = 1):
        self.n_chips = max(n_chips, 1)
        self.t0: "float | None" = None
        self.total = 0
        self._base = 0  # total already accounted when the clock anchored

    def start(self) -> None:
        """Anchor the rate clock now (idempotent)."""
        if self.t0 is None:
            self.t0 = time.monotonic()

    def add(self, tokens: int) -> None:
        self.start()
        self.total += tokens

    def note_total(self, total: int) -> None:
        """Replace the running total with an externally accounted
        cumulative count (the progress stream's in+out totals). The
        first report anchors the clock AND the baseline, so the rate
        measures tokens per second *since the anchor* instead of
        dividing a pre-anchor backlog by epsilon."""
        if self.t0 is None:
            self.start()
            self._base = int(total)
        self.total = int(total)

    def per_second(self) -> float:
        if self.t0 is None:
            return 0.0
        return (self.total - self._base) / max(
            time.monotonic() - self.t0, 1e-9
        )

    def per_chip_per_second(self) -> float:
        return self.per_second() / self.n_chips
