"""Progress/token metrics bus.

Engine-side producer of the reference's NDJSON progress protocol
(/root/reference/sutro/sdk.py:331-367): ``{"update_type": "progress",
"result": <rows_done>}`` and ``{"update_type": "tokens", "result":
{input_tokens, output_tokens, total_tokens_processed_per_second}}``.
The reference consumes this over a long-lived HTTP stream; here the bus is
an in-process, thread-safe pub/sub keyed by job id, with history retained
so a late ``attach`` (reference sdk.py:800-911) sees current totals
immediately. Token updates may be partial dicts — consumers must merge
monotonically (sdk.py:354-363) — and the bus preserves that contract.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Iterator, List, Optional


class JobMetrics:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.latest_tokens: Dict[str, Any] = {}
        self.rows_completed = 0
        self.done = False
        self._subscribers: List[queue.Queue] = []

    def _publish(self, update: Dict[str, Any]) -> None:
        with self.lock:
            subs = list(self._subscribers)
        for q in subs:
            q.put(update)

    def progress(self, rows_completed: int) -> None:
        with self.lock:
            self.rows_completed = rows_completed
        self._publish({"update_type": "progress", "result": rows_completed})

    def tokens(self, result: Dict[str, Any]) -> None:
        with self.lock:
            self.latest_tokens.update(result)
        self._publish({"update_type": "tokens", "result": dict(result)})

    def finish(self) -> None:
        with self.lock:
            self.done = True
            subs = list(self._subscribers)
        for q in subs:
            q.put(None)  # sentinel

    def subscribe(self) -> Iterator[Dict[str, Any]]:
        """Yields updates until the job finishes. Starts with a snapshot of
        current totals so mid-run attach shows correct state."""
        q: queue.Queue = queue.Queue()
        with self.lock:
            snapshot_rows = self.rows_completed
            snapshot_tokens = dict(self.latest_tokens)
            already_done = self.done
            self._subscribers.append(q)
        try:
            yield {"update_type": "progress", "result": snapshot_rows}
            if snapshot_tokens:
                yield {"update_type": "tokens", "result": snapshot_tokens}
            if already_done:
                return
            while True:
                item = q.get()
                if item is None:
                    return
                yield item
        finally:
            with self.lock:
                if q in self._subscribers:
                    self._subscribers.remove(q)


class MetricsBus:
    def __init__(self) -> None:
        self._jobs: Dict[str, JobMetrics] = {}
        self._lock = threading.Lock()

    def job(self, job_id: str) -> JobMetrics:
        with self._lock:
            if job_id not in self._jobs:
                self._jobs[job_id] = JobMetrics()
            return self._jobs[job_id]

    def drop(self, job_id: str) -> None:
        with self._lock:
            self._jobs.pop(job_id, None)


class Throughput:
    """Per-chip tokens/sec estimator (BASELINE.md tracked metric)."""

    def __init__(self, n_chips: int = 1):
        self.n_chips = max(n_chips, 1)
        self.t0 = time.monotonic()
        self.total = 0

    def add(self, tokens: int) -> None:
        self.total += tokens

    def per_second(self) -> float:
        return self.total / max(time.monotonic() - self.t0, 1e-9)

    def per_chip_per_second(self) -> float:
        return self.per_second() / self.n_chips
