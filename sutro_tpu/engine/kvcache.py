"""Paged KV cache.

TPU-native replacement for the server-side KV management the reference
delegates to its remote fleet (SURVEY §2.3 row 1: "continuous-batching
scheduler ... paged-KV decode attention"). Layout:

- ``k_pages`` / ``v_pages``: ``[L, NP, PS, KVH*Dh]`` device arrays. Page 0
  is a reserved garbage page — padding tokens scatter there, so the write
  path needs no masks or dynamic shapes. The KV-head and head-dim axes are
  stored FUSED as one trailing axis: the Pallas decode kernel's
  block-diagonal score/value matmuls contract over exactly that axis, and
  Mosaic supports collapsing leading dims of a fetched page but not
  merging (KVH, Dh) into the lane dim in-kernel — so the pool carries the
  kernel-native layout and the small per-step tensors reshape outside.
- ``page_table``: host-side ``numpy`` ``[B, MP]`` int32, passed into each
  jitted step as a device argument. Pages are allocated/freed by a
  host-side free list (allocation is control-plane work; the device only
  ever sees dense int32 tables).

``write_kv`` lands a chunk's K/V into pages (Pallas in-place RMW kernel
on TPU, XLA scatter fallback elsewhere); ``gather_kv_layer`` produces one
layer's contiguous ``[B, CTX, KVH, Dh]`` view for the non-Pallas
attention fallback. Both are pure functions over pytrees, jitted as part
of the runner's step functions.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.configs import ModelConfig
from .config import EngineConfig


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k_pages: jax.Array  # [L, NP, PS, KVH*Dh] — bf16, or int8 quantized
    v_pages: jax.Array  # [L, NP, PS, KVH*Dh]
    # int8 KV mode (EngineConfig.kv_quantize): per-TOKEN dequant scales,
    # amax/127 over the fused KD axis. Per-token (not per-page) so a
    # decode append quantizes exactly once — no page rescale, no
    # clipping against a stale amax. Overhead: 4 bytes per token per
    # layer vs KD int8 bytes (<1% at KD=1024).
    k_scale: "jax.Array | None" = None  # [L, NP, PS] f32
    v_scale: "jax.Array | None" = None

    @property
    def page_size(self) -> int:
        return self.k_pages.shape[2]

    @property
    def num_pages(self) -> int:
        return self.k_pages.shape[1]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def alloc_cache(
    mcfg: ModelConfig, ecfg: EngineConfig, num_pages: int,
    dtype: jnp.dtype = jnp.bfloat16,
) -> KVCache:
    shape = (
        mcfg.num_layers,
        num_pages,
        ecfg.kv_page_size,
        mcfg.num_kv_heads * mcfg.head_dim,
    )
    if getattr(ecfg, "kv_quantize", None) == "int8":
        return KVCache(
            k_pages=jnp.zeros(shape, jnp.int8),
            v_pages=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.zeros(shape[:3], jnp.float32),
            v_scale=jnp.zeros(shape[:3], jnp.float32),
        )
    if getattr(ecfg, "kv_quantize", None):
        raise ValueError(
            f"Unknown kv_quantize mode {ecfg.kv_quantize!r} (only 'int8')"
        )
    return KVCache(k_pages=jnp.zeros(shape, dtype), v_pages=jnp.zeros(shape, dtype))


def _quantize_tokens(x: jax.Array):
    """[..., KD] float -> (int8 values, f32 per-token scales [...])."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(
        jnp.round(xf / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


class PageAllocator:
    """Host-side page allocator. Page 0 is reserved as the garbage page.

    Allocation is CONTIGUOUS-FIRST: a slot's reserved pages form one
    ascending run whenever a large-enough hole exists (first-fit over
    the sorted free set), falling back to scattered pages otherwise.
    Contiguous runs let the Pallas decode kernel fetch a row's whole
    context in a few chunked DMAs instead of one DMA per page — the
    dominant decode-attention cost measured in PERF.md. Since slots
    reserve their worst case up front and runs are uniform per job,
    fragmentation stays bounded in practice; correctness never depends
    on contiguity (the kernel and the gather fallback accept any
    table)."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free: List[int] = list(range(1, num_pages))  # sorted asc

    def alloc(self, n: int = 1) -> List[int]:
        free = self._free
        if len(free) < n:
            raise MemoryError(
                f"KV cache out of pages (requested {n}, free {len(free)})"
            )
        # first-fit contiguous run over the sorted free list
        run_start = 0
        run_len = 1
        for i in range(1, len(free)):
            if free[i] == free[i - 1] + 1:
                run_len += 1
                if run_len == n:
                    pages = free[run_start : run_start + n]
                    del free[run_start : run_start + n]
                    return pages
            else:
                run_start = i
                run_len = 1
        if n == 1 and free:
            return [free.pop(0)]
        # no hole big enough: scattered fallback (ascending)
        pages = free[:n]
        del free[:n]
        return pages

    def free(self, pages: List[int]) -> None:
        import bisect

        for p in pages:
            if p != 0:
                bisect.insort(self._free, p)

    def reserve(self, pages: List[int]) -> None:
        """Remove SPECIFIC page ids from the free list. The engine-
        lifetime prefix store (engine/prefixstore.py) owns pages in the
        runner's pool across batcher sessions; each new session's fresh
        allocator must take them out of circulation before any
        admission. Atomic: raises KeyError leaving the free list
        untouched if any id (or duplicate) is not currently free."""
        import bisect

        free = self._free
        want = sorted(int(p) for p in pages)
        for a, b in zip(want, want[1:]):
            if a == b:
                raise KeyError(f"duplicate page id {a} in reserve()")
        for p in want:
            i = bisect.bisect_left(free, p)
            if i >= len(free) or free[i] != p:
                raise KeyError(f"page {p} is not free (cannot reserve)")
        drop = set(want)
        self._free = [p for p in free if p not in drop]

    @property
    def free_count(self) -> int:
        return len(self._free)


def pages_needed(length: int, page_size: int) -> int:
    return (length + page_size - 1) // page_size


def _flat_slots(
    page_table: jax.Array, start: jax.Array, valid_len: jax.Array,
    T: int, PS: int,
) -> jax.Array:
    """[B, T] flat pool positions for a chunk's tokens; padding tokens
    route to garbage page 0. Single copy of the scatter index math for
    the quantized AND unquantized write paths."""
    pos = start[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    valid = jnp.arange(T, dtype=jnp.int32)[None, :] < valid_len[:, None]
    page_idx = jnp.take_along_axis(page_table, pos // PS, axis=1)
    return jnp.where(valid, page_idx * PS + pos % PS, 0)


def write_kv(
    cache: KVCache,
    k_chunk: jax.Array,        # [L, B, T, KVH, Dh] or fused [L, B, T, KD]
    v_chunk: jax.Array,
    page_table: jax.Array,     # [B, MP] int32
    start: jax.Array,          # [B] int32 — global position of chunk token 0
    valid_len: jax.Array,      # [B] int32 — real tokens in chunk
    use_pallas: bool = False,
) -> KVCache:
    """Scatter a chunk's K/V into pages. Padding positions are routed to
    garbage page 0. With ``use_pallas`` the write is a true in-place DMA
    (ops/pallas_kv.py) instead of an XLA scatter over the full pool."""
    if k_chunk.ndim == 4:  # already fused (decode window buffers)
        L, B, T, KD = k_chunk.shape
    else:
        L, B, T, KVH, Dh = k_chunk.shape
        KD = KVH * Dh
    PS = cache.page_size
    NP = cache.num_pages
    if cache.quantized:
        # int8 KV: quantize per token, then the SAME flat scatter as
        # the unquantized fallback below (shared index helper), plus
        # the scale scatter. The in-place Pallas write kernel is
        # bf16-only — the XLA path serves the quantized cache.
        kq, ks = _quantize_tokens(k_chunk.reshape(L, B, T, KD))
        vq, vs = _quantize_tokens(v_chunk.reshape(L, B, T, KD))
        flat = _flat_slots(page_table, start, valid_len, T, PS)
        k_flat = cache.k_pages.reshape(L, NP * PS, KD)
        v_flat = cache.v_pages.reshape(L, NP * PS, KD)
        ks_flat = cache.k_scale.reshape(L, NP * PS)
        vs_flat = cache.v_scale.reshape(L, NP * PS)
        k_flat = k_flat.at[:, flat].set(kq)
        v_flat = v_flat.at[:, flat].set(vq)
        ks_flat = ks_flat.at[:, flat].set(ks)
        vs_flat = vs_flat.at[:, flat].set(vs)
        return KVCache(
            k_pages=k_flat.reshape(L, NP, PS, KD),
            v_pages=v_flat.reshape(L, NP, PS, KD),
            k_scale=ks_flat.reshape(L, NP, PS),
            v_scale=vs_flat.reshape(L, NP, PS),
        )
    if use_pallas:
        from ..ops.pallas_kv import kv_write_pallas

        k_pages, v_pages = kv_write_pallas(
            cache.k_pages,
            cache.v_pages,
            k_chunk.reshape(L, B, T, KD).astype(cache.k_pages.dtype),
            v_chunk.reshape(L, B, T, KD).astype(cache.v_pages.dtype),
            page_table.astype(jnp.int32),
            start.astype(jnp.int32),
            valid_len.astype(jnp.int32),
        )
        return KVCache(k_pages=k_pages, v_pages=v_pages)

    flat = _flat_slots(page_table, start, valid_len, T, PS)          # [B, T]

    k_flat = cache.k_pages.reshape(L, NP * PS, KD)
    v_flat = cache.v_pages.reshape(L, NP * PS, KD)
    # advanced indexing [L dim kept, flat [B,T]] -> [L, B, T, KD]
    k_flat = k_flat.at[:, flat].set(
        k_chunk.reshape(L, B, T, KD).astype(k_flat.dtype)
    )
    v_flat = v_flat.at[:, flat].set(
        v_chunk.reshape(L, B, T, KD).astype(v_flat.dtype)
    )
    return KVCache(
        k_pages=k_flat.reshape(L, NP, PS, KD),
        v_pages=v_flat.reshape(L, NP, PS, KD),
    )


def gather_kv_layer(
    k_pages_l: jax.Array,  # [NP, PS, KVH*Dh] — one layer's pages
    v_pages_l: jax.Array,
    page_table: jax.Array,  # [B, MP] int32
    kv_heads: int,
    k_scale_l: "jax.Array | None" = None,  # [NP, PS] (int8 KV mode)
    v_scale_l: "jax.Array | None" = None,
    out_dtype=None,  # dequant target (compute dtype); None => float32
) -> Tuple[jax.Array, jax.Array]:
    """Per-layer page gather: [B, MP] table -> ([B, CTX, KVH, Dh]) x2,
    CTX = MP * PS. Used inside the layer scan so only one layer's context
    view is ever live (the XLA fallback when the Pallas paged kernel does
    not run — the kernel reads pages in place and skips this copy).
    With int8 KV scales the gathered pages are dequantized here, INTO
    the caller's compute dtype — a float32 view would quadruple the
    gathered context's bytes and promote the whole fallback attention
    to f32, doubling the HBM traffic the int8 cache exists to halve."""
    NP, PS, KD = k_pages_l.shape
    B, MP = page_table.shape
    k = jnp.take(k_pages_l, page_table.reshape(-1), axis=0)
    v = jnp.take(v_pages_l, page_table.reshape(-1), axis=0)
    if k_scale_l is not None:
        dt = out_dtype or jnp.float32
        ks = jnp.take(k_scale_l, page_table.reshape(-1), axis=0)
        vs = jnp.take(v_scale_l, page_table.reshape(-1), axis=0)
        k = (k.astype(jnp.float32) * ks[..., None]).astype(dt)
        v = (v.astype(jnp.float32) * vs[..., None]).astype(dt)
    return (
        k.reshape(B, MP * PS, kv_heads, KD // kv_heads),
        v.reshape(B, MP * PS, kv_heads, KD // kv_heads),
    )


def make_page_table(rows: List[List[int]], max_pages: int) -> np.ndarray:
    """Pad per-slot page lists to a dense [B, MP] int32 table (garbage page 0)."""
    out = np.zeros((len(rows), max_pages), np.int32)
    for i, r in enumerate(rows):
        out[i, : len(r)] = r
    return out
