"""``shard_map`` version compatibility.

The sharded execution paths (expert-parallel MoE, ring attention,
pipeline parallelism) are written against the current top-level
``jax.shard_map`` API, whose ``axis_names=`` selects the *manual*
axes (partial-manual shard_map). Older jax releases (<= 0.4.x, the
version some of our hosts pin) only ship
``jax.experimental.shard_map.shard_map``, where the same thing is
expressed inversely via ``auto=`` (the axes that stay automatic).

One wrapper, one translation rule:

- new jax: forward verbatim to ``jax.shard_map``;
- old jax: ``auto = mesh.axis_names - axis_names`` (manual-over-all
  when ``axis_names`` is omitted), with ``check_rep=False`` — the
  replication checker predates several collectives these bodies use
  (psum over partial-manual meshes) and the parity tests, not the
  checker, are what pin correctness here.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Set

import jax

# True when the running jax ships the top-level partial-manual
# shard_map API. Legacy jax can emulate full-manual and size-1-auto
# meshes (the wrapper below) but NOT genuinely-sharded auto axes —
# its rewriter raises NotImplementedError and XLA:CPU SPMD rejects
# the PartitionId instruction those programs need. Tests for such
# configs skip on this flag.
HAS_NEW_SHARD_MAP = hasattr(jax, "shard_map")


def pcast(x: Any, axes: Any, to: str = "varying"):
    """``jax.lax.pcast`` when the running jax has varying-manual-axis
    (VMA) types; identity otherwise — under the legacy shard_map every
    value inside the body is already device-varying, so the cast only
    exists to satisfy the new type system."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axes, to=to)
    return x


def shard_map(
    f: Callable,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    axis_names: Optional[Set[str]] = None,
    check_vma: bool = True,
):
    if hasattr(jax, "shard_map"):
        kw: dict = {"check_vma": check_vma}
        if axis_names is not None:
            kw["axis_names"] = set(axis_names)
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as _legacy

    # Size-1 auto axes are dropped: manual over a 1-sized axis is
    # semantically identical (the body sees the only shard), and the
    # legacy partial-auto path is far less supported (NotImplementedError
    # in the 0.4.x rewriter, PartitionId UNIMPLEMENTED in XLA:CPU SPMD) —
    # so only genuinely-sharded auto axes take it.
    auto = (
        frozenset(
            a
            for a in mesh.axis_names
            if a not in axis_names and mesh.shape[a] > 1
        )
        if axis_names is not None
        else frozenset()
    )
    # check_rep is the old name for check_vma; partial-manual bodies
    # (auto axes) predate the checker entirely, so it is off there
    return _legacy(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=bool(check_vma) and not auto,
        auto=auto,
    )
