"""Token sampling with optional constrained-decoding masks.

Implements the ``sampling_params`` surface the reference forwards to its
service (temperature / top_p / top_k; /root/reference/sutro/sdk.py:202-216
payload) plus the logit-mask hook used by schema-constrained decoding
(engine/constrain/): a boolean ``allowed`` mask computed host-side from the
token FSM is applied before sampling, guaranteeing schema-valid JSON.

Everything is jit-safe and static-shape; greedy is the temperature==0.0
special case folded into the same compiled fn (lax.cond-free: we use a
where on the temperature scalar so one executable serves both).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample(
    logits: jax.Array,                  # [B, V] float32
    key: jax.Array,
    *,
    temperature: jax.Array,             # scalar or [B]
    top_p: jax.Array,                   # scalar or [B]; 1.0 disables
    top_k: jax.Array = 0,               # scalar or [B] int32; 0 disables
    allowed: Optional[jax.Array] = None,  # [B, V] bool — constrained decoding
) -> jax.Array:
    """Returns sampled token ids [B]."""
    B, V = logits.shape
    if allowed is not None:
        logits = jnp.where(allowed, logits, NEG_INF)

    temperature = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (B,))
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (B,))

    greedy_tok = jnp.argmax(logits, axis=-1)

    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]

    # one descending sort serves both top-k and top-p filtering
    sort_idx = jnp.argsort(-scaled, axis=-1)
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)

    # top-k (dynamic per row): keep ranks < k; k<=0 disables
    ranks = jnp.arange(V, dtype=jnp.int32)[None, :]
    k_eff = jnp.where(top_k > 0, top_k, V)[:, None]
    keep_k = ranks < k_eff

    # top-p (nucleus): drop tokens outside the smallest prob mass >= top_p
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    keep_p = (cum - sorted_probs) < top_p[:, None]  # always keeps rank-0

    keep_sorted = keep_k & keep_p
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(B)[:, None], sort_idx
    ].set(keep_sorted)
    scaled = jnp.where(keep, scaled, NEG_INF)

    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature <= 0.0, greedy_tok, sampled).astype(jnp.int32)


def cumulative_logprob(
    logits: jax.Array, token: jax.Array
) -> jax.Array:
    """Per-step logprob of the chosen token (for ``include_cumulative_logprobs``,
    reference sdk.py:1138-1151)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, token[:, None], axis=-1)[:, 0]
