"""Token sampling with optional constrained-decoding masks.

Implements the ``sampling_params`` surface the reference forwards to its
service (temperature / top_p / top_k; /root/reference/sutro/sdk.py:202-216
payload) plus the logit-mask hook used by schema-constrained decoding
(engine/constrain/): a boolean ``allowed`` mask computed host-side from the
token FSM is applied before sampling, guaranteeing schema-valid JSON.

Everything is jit-safe and static-shape; greedy is the temperature==0.0
special case folded into the same compiled fn (lax.cond-free: we use a
where on the temperature scalar so one executable serves both).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30
# widest nucleus/top-k head considered for sampling (see sample())
NUCLEUS_CAP = 256


def apply_penalties(
    logits: jax.Array,      # [B, V] float32 (raw, pre-temperature)
    seen_rep: jax.Array,    # [B, V] bool — repetition scope: PROMPT +
    #                         generated tokens (vLLM/HF semantics)
    pen_ids: jax.Array,     # [B, K] int32, -1 padded — distinct GENERATED ids
    pen_cnt: jax.Array,     # [B, K] float32 — their counts
    presence: jax.Array,    # [B] float32; 0 disables
    frequency: jax.Array,   # [B] float32; 0 disables
    repetition: jax.Array,  # [B] float32; 1 disables
) -> jax.Array:
    """Sampling penalties applied to raw logits before temperature:
    repetition divides positive / multiplies negative logits of tokens
    in ``seen_rep`` (prompt + output); presence subtracts a flat bias
    and frequency a count-proportional bias from GENERATED tokens only
    (both derived on-device from the sparse [B, K] id/count list —
    outputs rarely exceed K distinct ids; overflow ids keep the
    repetition penalty via ``seen_rep`` but lose presence/frequency).

    Dtype-preserving: every [B, V] expression stays in ``logits.dtype``
    (the count scatter accumulates in f32, then the bias casts back),
    so bf16 logits keep their bandwidth saving through this path."""
    B, V = logits.shape
    dt = logits.dtype
    rep = repetition[:, None].astype(dt)
    rep_l = jnp.where(
        logits > 0, logits / rep, logits * rep
    )
    logits = jnp.where(seen_rep, rep_l, logits)
    ids = jnp.clip(pen_ids, 0, V - 1)
    counts = jnp.zeros((B, V), jnp.float32).at[
        jnp.arange(B)[:, None], ids
    ].add(jnp.where(pen_ids >= 0, pen_cnt, 0.0))
    logits = logits - (presence[:, None] * (counts > 0)).astype(dt)
    return logits - (frequency[:, None] * counts).astype(dt)


def sample(
    logits: jax.Array,                  # [B, V] float32 OR bfloat16
    key: jax.Array,
    *,
    temperature: jax.Array,             # scalar or [B]
    top_p: jax.Array,                   # scalar or [B]; 1.0 disables
    top_k: jax.Array = 0,               # scalar or [B] int32; 0 disables
    allowed: Optional[jax.Array] = None,  # [B, V] bool — constrained decoding
    row_seeds: Optional[jax.Array] = None,  # [B] int32 — per-row derived keys
) -> jax.Array:
    """Returns sampled token ids [B].

    ``row_seeds`` implements the reference's ``random_seed_per_input``
    (sdk.py payload): each row samples with a key folded from its own seed
    (gumbel-max, equivalent to categorical), so a row's output stream is
    reproducible independent of batch composition.

    bfloat16 logits are supported (SUTRO_LOGITS_BF16 keeps the LM-head
    output in bf16, halving the HBM traffic of the full-vocab passes
    here): the wide [B, V] scans (top-k head, greedy argmax, logsumexp
    input) stay in the input dtype while every accumulation and the
    small [B, K] head math upcast to float32 — the converts fuse into
    the reduction loops. Two deliberate exceptions pay a full f32 pass
    for unbiased gumbel noise: the unfiltered full-vocab categorical
    (rare: top_k=0 AND top_p>=1) and the row-seeded full-vocab draw —
    bf16 gumbel over 150k near-ties would resolve quantized ties toward
    low token ids."""
    B, V = logits.shape
    if allowed is not None:
        logits = jnp.where(allowed, logits, jnp.asarray(NEG_INF, logits.dtype))

    temperature = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (B,))
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (B,))

    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None].astype(
        logits.dtype
    )

    # A full [B, V] argsort is pathologically slow on TPU (sorting networks
    # over 150k lanes). Filtered rows instead use the top NUCLEUS_CAP
    # logits — the nucleus/top-k filters only ever *keep* a head of the
    # distribution — normalized against the exact full-vocab logsumexp, so
    # probabilities are exact. Rows with filtering disabled (top_k==0 and
    # top_p>=1) sample the FULL vocabulary via gumbel-argmax
    # (== categorical, no sort), honoring the "0 disables" contract.
    # Remaining approximations: top_k above the cap clamps to the cap-wide
    # head; a *nucleus* wider than NUCLEUS_CAP tokens (near-uniform
    # distributions with top_p<1) truncates to the cap.
    K = min(NUCLEUS_CAP, V)
    # approx_max_k is ~3x faster than exact top_k on TPU for 150k vocabs;
    # the head feeds *stochastic* nucleus sampling, where a ~2% recall
    # miss in the tail of the head is statistically invisible. Greedy
    # stays exact via a separate argmax (determinism contract). Two cases
    # need the EXACT head: (a) FSM-constrained rows, whose allowed set
    # may be smaller than the approx recall can resolve, and (b) small
    # top_k (a ~5%/element miss inside a 2-wide head is a visible
    # distribution change). (a) is static; (b) is a runtime cond so the
    # common unconstrained/top_p path keeps the fast kernel.
    def _exact():
        return jax.lax.top_k(scaled, K)

    def _approx():
        return jax.lax.approx_max_k(
            scaled, K, recall_target=0.95, aggregate_to_topk=True
        )

    if allowed is not None:
        top_vals, top_idx = _exact()
    else:
        top_vals, top_idx = jax.lax.cond(
            jnp.any((top_k > 0) & (top_k <= 32)), _exact, _approx
        )
    greedy_tok = jnp.argmax(scaled, axis=-1).astype(jnp.int32)

    # f32 accumulation regardless of input dtype (a bf16 accumulator
    # over 150k terms drifts); the convert fuses into the reduction
    lse = jax.scipy.special.logsumexp(
        scaled.astype(jnp.float32), axis=-1, keepdims=True
    )
    top_vals = top_vals.astype(jnp.float32)           # [B, K] — tiny
    probs = jnp.exp(top_vals - lse)                   # exact probabilities

    ranks = jnp.arange(K, dtype=jnp.int32)[None, :]
    k_active = top_k > 0
    # top_k beyond the cap is clamped to the cap-wide head (closest
    # realizable restriction), never silently disabled
    k_eff = jnp.where(k_active, jnp.minimum(top_k, K), K)[:, None]
    keep_k = ranks < k_eff
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = (cum - probs) < top_p[:, None]           # always keeps rank-0
    vals = jnp.where(keep_k & keep_p, top_vals, NEG_INF)

    filtered = k_active | (top_p < 1.0)

    if row_seeds is not None:
        keys = jax.vmap(lambda s: jax.random.fold_in(key, s))(row_seeds)
        g_head = jax.vmap(
            lambda k, lg: jax.random.gumbel(k, lg.shape, jnp.float32)
        )(keys, vals)
        choice = jnp.argmax(vals + g_head, axis=-1)
        g_full = jax.vmap(
            lambda k, lg: jax.random.gumbel(
                jax.random.fold_in(k, 1), lg.shape, jnp.float32
            )
        )(keys, scaled)
        full_tok = jnp.argmax(scaled + g_full, axis=-1)
    else:
        choice = jax.random.categorical(key, vals, axis=-1)
        # the full-vocab draw only matters for rows with filtering
        # disabled — skip the [B, V] gumbel pass when every row filters
        full_tok = jax.lax.cond(
            jnp.all(filtered | (temperature <= 0.0)),
            lambda: jnp.zeros((B,), jnp.int32),
            # f32 ALWAYS: categorical draws gumbel in the logits dtype,
            # and bf16 gumbel over 150k near-ties quantizes into mass
            # exact ties resolved toward low token ids (biased). This
            # rare branch (filters disabled) pays the f32 pass for
            # unbiasedness.
            lambda: jax.random.categorical(
                jax.random.fold_in(key, 1),
                scaled.astype(jnp.float32),
                axis=-1,
            ).astype(jnp.int32),
        )
    head_tok = jnp.take_along_axis(top_idx, choice[:, None], axis=1)[:, 0]
    sampled = jnp.where(filtered, head_tok, full_tok)
    return jnp.where(temperature <= 0.0, greedy_tok, sampled).astype(jnp.int32)


def cumulative_logprob(
    logits: jax.Array, token: jax.Array
) -> jax.Array:
    """Per-step logprob of the chosen token (for ``include_cumulative_logprobs``,
    reference sdk.py:1138-1151). Gather-then-logsumexp so the full [B, V]
    log_softmax is never materialized."""
    chosen = jnp.take_along_axis(logits, token[:, None], axis=-1)[
        :, 0
    ].astype(jnp.float32)
    return chosen - jax.scipy.special.logsumexp(
        logits.astype(jnp.float32), axis=-1
    )
