"""Flash (blockwise, online-softmax) causal prefill attention in Pallas.

Placeholder gate for now: ``flash_prefill_supported`` returns False until
the kernel lands (SURVEY §7.2 step 4); ops/attention.py then uses the XLA
path. Kept as a separate module so the kernel can be developed and
unit-tested against the reference jnp implementation in isolation.
"""

from __future__ import annotations

from typing import Optional

import jax


def flash_prefill_supported(
    q: jax.Array, k: jax.Array, window, sink
) -> bool:
    return False


def flash_prefill(q, k, v, *, positions, valid_len):  # pragma: no cover
    raise NotImplementedError
