"""Flash (blockwise, online-softmax) causal prefill attention in Pallas.

The prefill hot path (SURVEY §2.3 row 1, §7.2 step 4). The reference's
whole value proposition is batch throughput (/root/reference/README.md:36-38)
and classify-style jobs are prefill-dominated, so prefill must not
materialize the O(T^2) score matrix the fused-XLA fallback builds.

Design (TPU-first):

- Layout is head-major: q ``[B, KVH, G, T, Dh]``, k/v ``[B, KVH, T, Dh]``
  so one grid step owns one (batch row, KV head) pair and the MXU sees
  ``[BQ, Dh] x [BK, Dh]^T`` tiles per query-head-in-group.
- Grid ``(B, KVH, nQ, nK)``; the key-block axis is innermost and
  sequential ("arbitrary"), carrying running ``(m, l, acc)`` per grouped
  query head in VMEM scratch — classic flash online softmax.
- Causality is exploited at block granularity: key blocks strictly above
  the diagonal are skipped (``pl.when``), so work is ~half of the full
  rectangle; the output is finalized and written at the diagonal block,
  which under causal masking is always the last contributing key block.
- Per-layer sliding windows (Gemma3 / gpt-oss alternating) arrive as a
  *dynamic* scalar-prefetch operand so one compiled kernel serves every
  layer of the model's ``lax.scan``: fully-out-of-window key blocks are
  skipped dynamically, the diagonal block is never skippable, and partial
  blocks are masked elementwise.
- gpt-oss attention sinks join the softmax denominator at finalization
  (a per-head logit with no value row — same semantics as
  ops/attention.py's jnp path).

Contract: self-attention over a chunk with NO past — query/key positions
are ``[0, T)`` (the runner's bucketed prefill and the embed path both
guarantee this; chunked long-prompt prefill carries paged past and takes
the paged/XLA path instead). Padding rows/tails (``t >= valid_len``) are
computed-and-discarded by the caller exactly as in the jnp path: a padded
query only ever attends causally, so every *used* output position
(t < valid_len) sees only real keys.

All math float32; outputs cast back to the query dtype.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams; accept
# either so the kernels build across the jax versions we run on
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

NEG_INF = -1e30

BLOCK_Q = 128
BLOCK_K = 128
MAX_GROUP = 8  # scratch is [G, BQ, *]; cap G so VMEM stays bounded


def _flash_kernel(
    # scalar prefetch
    window_ref,       # [1] int32 (0 = full attention)
    # operands
    q_ref,            # [1, 1, G, BQ, Dh]
    k_ref,            # [1, 1, BK, Dh]
    v_ref,            # [1, 1, BK, Dh]
    sink_ref,         # [1, G, 128] f32 (NEG_INF rows when no sink)
    # output
    out_ref,          # [1, 1, G, BQ, Dh]
    # scratch
    m_ref,            # [G, BQ, 128] f32
    l_ref,            # [G, BQ, 128] f32
    acc_ref,          # [G, BQ, Dh] f32
    *,
    groups: int,
    scale: float,
):
    qb = pl.program_id(2)
    kb = pl.program_id(3)
    BQ = q_ref.shape[3]
    BK = k_ref.shape[2]
    q0 = qb * BQ
    k0 = kb * BK
    win = window_ref[0]

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Block-level skip: strictly-above-diagonal (causal) or fully below
    # the sliding window. The diagonal block (k0 == q0) satisfies neither
    # condition, so every query row always executes at least one block.
    causal_skip = k0 > q0 + BQ - 1
    window_skip = jnp.logical_and(win > 0, k0 + BK - 1 <= q0 - win)

    @pl.when(jnp.logical_not(jnp.logical_or(causal_skip, window_skip)))
    def _accumulate():
        qpos = q0 + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 0)
        kpos = k0 + jax.lax.broadcasted_iota(jnp.int32, (BQ, BK), 1)
        ok = kpos <= qpos
        # windowless (win <= 0) ORed in — Mosaic cannot legalize
        # arith.select on i1 vectors (same workaround as pallas_paged)
        ok = jnp.logical_and(
            ok, jnp.logical_or(qpos - kpos < win, win <= 0)
        )
        k = k_ref[0, 0].astype(jnp.float32)            # [BK, Dh]
        v = v_ref[0, 0].astype(jnp.float32)            # [BK, Dh]
        for g in range(groups):  # static unroll over heads in the group
            q = q_ref[0, 0, g].astype(jnp.float32)     # [BQ, Dh]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                  # [BQ, BK]
            s = jnp.where(ok, s, NEG_INF)

            m_prev = m_ref[g, :, 0]                    # [BQ]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
            alpha = jnp.exp(m_prev - m_new)            # [BQ]
            p = jnp.exp(s - m_new[:, None])            # [BQ, BK]
            l_new = l_ref[g, :, 0] * alpha + jnp.sum(p, axis=1)
            acc_ref[g] = acc_ref[g] * alpha[:, None] + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[g] = jnp.broadcast_to(m_new[:, None], m_ref.shape[1:])
            l_ref[g] = jnp.broadcast_to(l_new[:, None], l_ref.shape[1:])

    # The diagonal block is the last contributing key block for this query
    # block (everything past it is causally skipped) — finalize here.
    @pl.when(k0 == q0)
    def _finalize():
        for g in range(groups):
            sink = sink_ref[0, g, 0]                   # scalar f32
            m_prev = m_ref[g, :, 0]
            m_new = jnp.maximum(m_prev, sink)
            alpha = jnp.exp(m_prev - m_new)
            # the sink contributes a probability-mass column only
            l = l_ref[g, :, 0] * alpha + jnp.exp(sink - m_new)
            out = acc_ref[g] * alpha[:, None] / jnp.maximum(l, 1e-30)[:, None]
            out_ref[0, 0, g] = out.astype(out_ref.dtype)


def flash_prefill_supported(
    q: jax.Array, k: jax.Array, window, sink
) -> bool:
    """Static shape gate for the compiled TPU path. window/sink are
    dynamic operands of the kernel, so they never gate."""
    B, T, NH, Dh = q.shape
    KVH = k.shape[2]
    if NH % KVH:
        return False
    G = NH // KVH
    return (
        T >= BLOCK_Q
        and T % BLOCK_Q == 0
        and Dh % 128 == 0
        and G <= MAX_GROUP
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def flash_prefill(
    q: jax.Array,                    # [B, T, NH, Dh]
    k: jax.Array,                    # [B, T, KVH, Dh] (post-RoPE)
    v: jax.Array,                    # [B, T, KVH, Dh]
    *,
    window: Optional[jax.Array] = None,   # scalar int32; 0/None => full
    sink: Optional[jax.Array] = None,     # [NH] logits or None
    interpret: bool = False,
) -> jax.Array:
    """Returns [B, T, NH, Dh] causal self-attention over the chunk."""
    B, T, NH, Dh = q.shape
    KVH = k.shape[2]
    G = NH // KVH
    scale = Dh ** -0.5
    nQ = T // BLOCK_Q
    nK = T // BLOCK_K

    # head-major layout: [B, KVH, G, T, Dh] / [B, KVH, T, Dh]
    qh = q.reshape(B, T, KVH, G, Dh).transpose(0, 2, 3, 1, 4)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    if sink is None:
        sink_g = jnp.full((KVH, G, 128), NEG_INF, jnp.float32)
    else:
        sink_g = jnp.broadcast_to(
            sink.astype(jnp.float32).reshape(KVH, G, 1), (KVH, G, 128)
        )
    win = (
        jnp.zeros((1,), jnp.int32)
        if window is None
        else jnp.asarray(window, jnp.int32).reshape(1)
    )

    kernel = functools.partial(_flash_kernel, groups=G, scale=scale)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, KVH, nQ, nK),
        in_specs=[
            pl.BlockSpec(
                (1, 1, G, BLOCK_Q, Dh),
                lambda b, h, qb, kb, win: (b, h, 0, qb, 0),
            ),
            pl.BlockSpec(
                (1, 1, BLOCK_K, Dh),
                lambda b, h, qb, kb, win: (b, h, kb, 0),
            ),
            pl.BlockSpec(
                (1, 1, BLOCK_K, Dh),
                lambda b, h, qb, kb, win: (b, h, kb, 0),
            ),
            pl.BlockSpec(
                (1, G, 128), lambda b, h, qb, kb, win: (h, 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, G, BLOCK_Q, Dh),
            lambda b, h, qb, kb, win: (b, h, 0, qb, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((G, BLOCK_Q, 128), jnp.float32),
            pltpu.VMEM((G, BLOCK_Q, 128), jnp.float32),
            pltpu.VMEM((G, BLOCK_Q, Dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH, G, T, Dh), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=(
                "parallel", "parallel", "parallel", "arbitrary"
            ),
        ),
        interpret=interpret,
    )(win, qh, kh, vh, sink_g)
    return out.transpose(0, 3, 1, 2, 4).reshape(B, T, NH, Dh)
