"""Int8 weight-only quantization.

The catalog's large dense models (SURVEY §2.3: TP for "32B-235B dense
models") need weight compression to fit v5e HBM footprints; this module
implements symmetric per-output-channel int8 for the projection matrices:

- a weight ``w[..., in, out]`` becomes ``{"qw": int8, "scale": f32}``
  with ``scale[..., 1, out] = max|w|/127`` over the reduction axis, so
  dequantization is one fused multiply feeding the MXU matmul;
- HBM at rest drops ~2x vs bf16 (~4x vs f32); XLA streams the dequant
  into the consumer, so no full-precision copy of the stack persists;
- norms, biases, routers, sinks and the token embedding stay in the
  activation dtype (quality-sensitive, tiny fraction of bytes).

Enabled via ``EngineConfig.quantize = "int8"`` (engine/config.py); the
transformer consumes possibly-quantized leaves through ``materialize``.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

# leaves (by name) that get int8 treatment — the big matmul operands
QUANT_LEAVES = frozenset(
    {
        "wq", "wk", "wv", "wo",
        "w_gate", "w_up", "w_down",
        "we_gate", "we_up", "we_down",
        "lm_head",
    }
)


def is_quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "qw" in leaf and "scale" in leaf


def quantize_weight(w) -> Dict[str, np.ndarray]:
    """Symmetric per-output-channel int8 over the reduction (second to
    last) axis. ``w[..., in, out] -> qw int8 + scale[..., 1, out]``.

    Runs on HOST numpy deliberately: quantization happens before the
    params are device_put with their shardings (engine/runner.py), and a
    jnp implementation would materialize every f32 temporary of a 32B+
    stack on the single default device — an OOM before sharding ever
    happens. Host peak is one leaf at a time instead."""
    w32 = np.asarray(w, dtype=np.float32)
    amax = np.max(np.abs(w32), axis=-2, keepdims=True)
    scale = np.maximum(amax, 1e-8) / 127.0
    qw = np.clip(np.round(w32 / scale), -127, 127).astype(np.int8)
    return {"qw": qw, "scale": scale.astype(np.float32)}


def materialize(leaf: Any, dtype: Any) -> jax.Array:
    """Quantized dict -> dequantized array in ``dtype``; plain arrays pass
    through (cast only if needed by the caller's matmul)."""
    if is_quantized(leaf):
        return (
            leaf["qw"].astype(jnp.float32) * leaf["scale"]
        ).astype(dtype)
    return leaf


def quantize_params(params: Any) -> Any:
    """Quantize every QUANT_LEAVES tensor in the params pytree (stacked
    layer layouts included — the channel axis is always last)."""

    def visit(d: Any) -> Any:
        if not isinstance(d, dict):
            return d
        out = {}
        for name, leaf in d.items():
            if isinstance(leaf, dict):
                out[name] = visit(leaf)
            elif name in QUANT_LEAVES:
                out[name] = quantize_weight(leaf)
            else:
                out[name] = leaf
        return out

    return visit(params)


def params_bytes(params: Any) -> int:
    return int(
        sum(x.nbytes for x in jax.tree_util.tree_leaves(params))
    )
