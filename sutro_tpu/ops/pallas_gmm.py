"""Pallas TPU kernel: grouped matmul for expert-parallel MoE.

SURVEY §2.3 row 4 ("EP ... Pallas grouped-matmul kernel"): the MoE MLP's
hot op is E independent GEMMs whose row counts are data-dependent
(tokens routed per expert). ``jax.lax.ragged_dot`` is the always-correct
fallback; this kernel is the MXU-native path:

- lhs rows arrive SORTED BY EXPERT (ops/moe.py ragged path). Each group
  is padded (inside jit, outside the kernel) to a multiple of the row
  tile, so a row tile never spans two experts — the classic
  "megablox-lite" layout. Padding waste is < E*BM rows of zeros, which
  for prefill-sized token counts is small next to the E-fold waste of
  the dense path.
- grid ``(row_tiles, F // BF)``; each step multiplies one [BM, H] row
  tile by its expert's [H, BF] weight block, selected via a
  scalar-prefetched tile->expert map (the index map reads
  ``tile_expert[m]`` — one compiled kernel serves any routing).
- weights stream HBM->VMEM per tile via the BlockSpec pipeline; the MXU
  sees dense [BM, H] x [H, BF] tiles with f32 accumulation.

Expert parallelism composes outside: the expert axis of ``rhs`` is
sharded over the mesh "expert" axis and XLA inserts the all-to-alls
(parallel/sharding.py); inside each shard this kernel runs the local
experts' GEMMs.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams; accept
# either so the kernels build across the jax versions we run on
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

BLOCK_M = 32
BLOCK_F = 128


def _gmm_kernel(tile_expert_ref, x_ref, w_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        x_ref[...],
        w_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(o_ref.dtype)


def grouped_matmul_supported(lhs: jax.Array, rhs: jax.Array) -> bool:
    """Static gate for the compiled TPU path (interpret mode bypasses).
    Requires M large relative to E*BLOCK_M: the padded layout wastes up
    to one row tile per expert, so decode-sized calls (M ~ B*top_k)
    would pay ~E times the FLOPs of exact ragged_dot — prefill-sized
    calls amortize the padding away."""
    M, H = lhs.shape
    E, _, F = rhs.shape
    return H % 128 == 0 and F % BLOCK_F == 0 and M >= E * BLOCK_M


@functools.partial(jax.jit, static_argnames=("interpret",))
def grouped_matmul(
    lhs: jax.Array,          # [M, H] — rows sorted by group
    rhs: jax.Array,          # [E, H, F]
    group_sizes: jax.Array,  # [E] int32, sum == M
    *,
    interpret: bool = False,
) -> jax.Array:
    """Returns [M, F] with ``out[i] = lhs[i] @ rhs[g(i)]`` where ``g(i)``
    is row i's group. Same contract as ``jax.lax.ragged_dot``."""
    M, H = lhs.shape
    E, _, F = rhs.shape
    BM = BLOCK_M

    group_sizes = group_sizes.astype(jnp.int32)
    padded = ((group_sizes + BM - 1) // BM) * BM
    pcum = jnp.cumsum(padded)
    poffs = pcum - padded                                  # padded starts
    gcum = jnp.cumsum(group_sizes)
    gstart = gcum - group_sizes                            # true starts

    # scatter rows into the group-padded layout (zeros between groups)
    MP = ((M + E * BM + BM - 1) // BM) * BM                # static bound
    rows = jnp.arange(M, dtype=jnp.int32)
    row_group = jnp.searchsorted(gcum, rows, side="right").astype(jnp.int32)
    dest = poffs[row_group] + (rows - gstart[row_group])
    xpad = jnp.zeros((MP, H), lhs.dtype).at[dest].set(lhs)

    # tile -> expert map (tiles past the last group hit expert E-1 on
    # zero rows; their output is never gathered back)
    n_tiles = MP // BM
    tile_start = jnp.arange(n_tiles, dtype=jnp.int32) * BM
    tile_expert = jnp.minimum(
        jnp.searchsorted(pcum, tile_start, side="right").astype(jnp.int32),
        E - 1,
    )

    out = pl.pallas_call(
        _gmm_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_tiles, F // BLOCK_F),
            in_specs=[
                pl.BlockSpec((BM, H), lambda m, f, te: (m, 0)),
                pl.BlockSpec(
                    (1, H, BLOCK_F), lambda m, f, te: (te[m], 0, f)
                ),
            ],
            out_specs=pl.BlockSpec(
                (BM, BLOCK_F), lambda m, f, te: (m, f)
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((MP, F), lhs.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel"),
        ),
        interpret=interpret,
    )(tile_expert, xpad, rhs)
    return out[dest]
