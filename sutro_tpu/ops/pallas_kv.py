"""Pallas TPU kernel: in-place KV page writes.

The XLA path for landing a decode step's K/V into the paged cache is a
scatter over a ~GB-scale buffer; under jit donation that costs several ms
per step of pure buffer churn (measured ~8 ms/donated buffer through the
axon PJRT path, ~57 ms for the full two-tensor scatter). This kernel makes
the write a true in-place DMA: grid over (layer, token), each step copies
one [KVH, D] tile into its (page, slot) destination, with
``input_output_aliases`` pinning the output to the input buffer — no
copies, no churn.

Used by engine/runner for both decode (N = batch) and prefill (N = B*T
chunk tokens); invalid/padding tokens are routed to flat index 0, the
reserved garbage page (kvcache.py convention).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kv_write_kernel(
    flat_idx_ref,  # scalar prefetch [N]
    k_new_ref,     # [L, 1, KVH, D] block — all layers of one token
    v_new_ref,
    k_io_ref,      # aliased in/out blocks (unused as input)
    v_io_ref,
    k_out_ref,
    v_out_ref,
):
    del flat_idx_ref, k_io_ref, v_io_ref
    k_out_ref[...] = k_new_ref[...]
    v_out_ref[...] = v_new_ref[...]


@functools.partial(jax.jit, donate_argnums=(0, 1))
def kv_write_pallas(
    k_pages: jax.Array,   # [L, R, KVH, D]  (R = NP * PS, flat rows)
    v_pages: jax.Array,
    k_new: jax.Array,     # [L, N, KVH, D]
    v_new: jax.Array,
    flat_idx: jax.Array,  # [N] int32 row index into R (0 = garbage)
) -> Tuple[jax.Array, jax.Array]:
    L, R, KVH, D = k_pages.shape
    N = k_new.shape[1]

    # one grid step per token, whole layer stack in one block: N DMAs of
    # L*KVH*D elements each, instead of L*N tiny tile copies
    new_spec = pl.BlockSpec(
        (L, 1, KVH, D), lambda n, idx: (0, n, 0, 0)
    )
    io_spec = pl.BlockSpec(
        (L, 1, KVH, D), lambda n, idx: (0, idx[n], 0, 0)
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(N,),
        in_specs=[new_spec, new_spec, io_spec, io_spec],
        out_specs=[io_spec, io_spec],
    )
    out_k, out_v = pl.pallas_call(
        _kv_write_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        # flattened operand order: flat_idx(0), k_new(1), v_new(2),
        # k_pages(3), v_pages(4) -> outputs 0, 1
        input_output_aliases={3: 0, 4: 1},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
    )(flat_idx, k_new, v_new, k_pages, v_pages)
    return out_k, out_v


def kv_write_supported() -> bool:
    return jax.default_backend() == "tpu"
