"""Pallas TPU kernel: in-place KV page writes (fused-layout pool).

The XLA path for landing a chunk's K/V into the paged cache is a scatter
over a ~GB-scale buffer; under jit donation that costs several ms per
call of pure buffer churn (measured ~8 ms/donated buffer through the
axon PJRT path round 1). This kernel keeps the pool in place with
``input_output_aliases`` and explicit DMAs.

Constraint driving the design: the pool's fused layout ``[L, NP, PS,
KVH*Dh]`` (engine/kvcache.py) makes the page-slot axis a TILED memref
dim, so single-row DMA writes are illegal (8-row alignment). Instead the
kernel is a page-granular read-modify-write:

- the token run of each batch row is split IN-GRAPH into per-page
  segments (page id, row range, shift), passed as scalar prefetch;
- grid ``(segments, layer-chunks)``: each step DMAs a ``[lc, PS, KD]``
  slab of the target page (``lc`` layers at once, sized to a VMEM
  budget — fewer, bigger DMAs), rotates the row's token buffer so token
  ``j`` lands on its page row ((start+j) % PS) via ``pltpu.roll``
  (dynamic shift, f32 — Mosaic's rotate is 32-bit only), blends rows
  inside the segment's range, and DMAs the slab back;
- empty segments (rows whose run touches fewer pages than the static
  bound, padding rows) skip all work under ``pl.when``.

The RMW costs one extra page read per touched page — writes happen once
per prefill chunk / decode window, so this is noise next to the decode
loop — and buys exact in-place semantics at any offset with zero pool
copies or padding blowup.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams; accept
# either so the kernels build across the jax versions we run on
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


def _kv_write_kernel(
    # scalar prefetch (flattened [B*S] segment tables)
    seg_page_ref, seg_rs_ref, seg_re_ref, seg_shift_ref, seg_row_ref,
    # operands
    k_new_ref,     # VMEM block [lc, 1, Tb, KD] — (layer chunk, seg row)
    v_new_ref,
    k_io_ref,      # ANY [L, NP, PS, KD] aliased inputs
    v_io_ref,
    k_out_ref,     # ANY aliased outputs
    v_out_ref,
    # scratch
    kpage, vpage, ksem, vsem,
    *,
    page_size: int,
    layer_chunk: int,
):
    del k_io_ref, v_io_ref
    s = pl.program_id(0)
    lchunk = pl.program_id(1)
    PS = page_size
    lc = layer_chunk
    page = seg_page_ref[s]
    rs = seg_rs_ref[s]
    re = seg_re_ref[s]

    @pl.when(re > rs)
    def _do():
        lsl = pl.ds(lchunk * lc, lc)
        kin = pltpu.make_async_copy(
            k_out_ref.at[lsl, page], kpage, ksem
        )
        vin = pltpu.make_async_copy(
            v_out_ref.at[lsl, page], vpage, vsem
        )
        kin.start()
        vin.start()

        # token j lives at page row (start + j) % PS; rolling the token
        # buffer by -shift puts token (r + shift) at row r for every r
        shift = seg_shift_ref[s]
        Tb = k_new_ref.shape[2]
        row = jax.lax.broadcasted_iota(
            jnp.int32, (PS, k_new_ref.shape[3]), 0
        )
        sel = jnp.logical_and(row >= rs, row < re)

        def rotated(tok):  # [Tb, KD] -> [PS, KD] rolled into page rows
            t = tok.astype(jnp.float32)
            if Tb < PS:  # decode windows are narrower than a page
                t = jnp.concatenate(
                    [t, jnp.zeros((PS - Tb, t.shape[-1]), jnp.float32)],
                    axis=0,
                )
            return pltpu.roll(t, -shift, 0)[:PS]

        kin.wait()
        vin.wait()
        for j in range(lc):  # static unroll over the layer chunk
            krot = rotated(k_new_ref[j, 0])
            vrot = rotated(v_new_ref[j, 0])
            kpage[j] = jnp.where(
                sel, krot.astype(kpage.dtype), kpage[j]
            )
            vpage[j] = jnp.where(
                sel, vrot.astype(vpage.dtype), vpage[j]
            )

        kout = pltpu.make_async_copy(
            kpage, k_out_ref.at[lsl, page], ksem
        )
        vout = pltpu.make_async_copy(
            vpage, v_out_ref.at[lsl, page], vsem
        )
        kout.start()
        vout.start()
        kout.wait()
        vout.wait()


def _layer_chunk(L: int, Tb: int, PS: int, KD: int, itemsize: int) -> int:
    """Largest divisor of L whose token blocks + page slabs fit a ~4 MiB
    VMEM budget per tensor."""
    budget = 4 << 20
    per_layer = (Tb + PS) * KD * itemsize
    lc = max(1, min(L, budget // max(per_layer, 1)))
    while L % lc:
        lc -= 1
    return lc


@functools.partial(
    jax.jit, donate_argnums=(0, 1), static_argnames=("interpret",)
)
def kv_write_pallas(
    k_pages: jax.Array,   # [L, NP, PS, KD] fused page pool
    v_pages: jax.Array,
    k_new: jax.Array,     # [L, B, Tb, KD]
    v_new: jax.Array,
    page_table: jax.Array,  # [B, MP] int32
    start: jax.Array,       # [B] int32 — global position of token 0
    valid_len: jax.Array,   # [B] int32 — real tokens in the chunk
    *,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    L, NP, PS, KD = k_pages.shape
    _, B, Tb, _ = k_new.shape
    MP = page_table.shape[1]

    # per-(row, page) segments; a run of Tb tokens at any offset touches
    # at most ceil(Tb/PS)+1 pages
    S = (Tb + PS - 1) // PS + 1
    si = jnp.arange(S, dtype=jnp.int32)[None, :]          # [1, S]
    start = start.astype(jnp.int32)[:, None]              # [B, 1]
    end = start + valid_len.astype(jnp.int32)[:, None]
    pi = start // PS + si                                 # [B, S]
    page = jnp.take_along_axis(
        page_table.astype(jnp.int32), jnp.clip(pi, 0, MP - 1), axis=1
    )
    lo = jnp.maximum(start, pi * PS)
    hi = jnp.minimum(end, (pi + 1) * PS)
    rs = lo - pi * PS
    re = jnp.maximum(hi - pi * PS, rs)                    # empty => re==rs
    # page 0 is the garbage page: it backs padding rows' tables, and
    # clipped out-of-table indices may alias real entries — mask those
    # segments off entirely (re = rs)
    ok = jnp.logical_and(page > 0, pi < MP)
    re = jnp.where(ok, re, rs)
    shift = pi * PS - start                               # [B, S]
    row = jnp.broadcast_to(
        jnp.arange(B, dtype=jnp.int32)[:, None], (B, S)
    )

    lc = _layer_chunk(L, Tb, PS, KD, k_pages.dtype.itemsize)
    kernel = functools.partial(
        _kv_write_kernel, page_size=PS, layer_chunk=lc
    )
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    new_spec = pl.BlockSpec(
        (lc, 1, Tb, KD), lambda s, l, *refs: (l, refs[4][s], 0, 0)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(B * S, L // lc),
        in_specs=[new_spec, new_spec, any_spec, any_spec],
        out_specs=[any_spec, any_spec],
        scratch_shapes=[
            pltpu.VMEM((lc, PS, KD), k_pages.dtype),
            pltpu.VMEM((lc, PS, KD), v_pages.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    out_k, out_v = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        # flattened operands: scalars(0-4), k_new(5), v_new(6),
        # k_pages(7), v_pages(8) -> outputs 0, 1
        input_output_aliases={7: 0, 8: 1},
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
        ),
        interpret=interpret,
    )(
        page.reshape(-1), rs.reshape(-1), re.reshape(-1),
        shift.reshape(-1), row.reshape(-1),
        k_new, v_new, k_pages, v_pages,
    )
    return out_k, out_v


def kv_write_supported() -> bool:
    return jax.default_backend() == "tpu"
