"""Pallas TPU attention kernels (flash prefill / paged decode).

Dispatched from ops/attention.py with ``use_pallas=True``. Each entry point
returns ``None`` when it cannot handle the given shapes/flags, in which
case the caller falls back to the fused-XLA path — so correctness never
depends on kernel coverage.

Kernels are implemented incrementally; see pallas kernels section of
SURVEY §7.2 step 4.
"""

from __future__ import annotations

from typing import Optional

import jax


def try_chunk_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    positions: jax.Array,
    valid_len: jax.Array,
    past_k: Optional[jax.Array],
    past_v: Optional[jax.Array],
    past_len: Optional[jax.Array],
    window: Optional[jax.Array],
    sink: Optional[jax.Array],
) -> Optional[jax.Array]:
    from .pallas_flash import flash_prefill_supported, flash_prefill

    if past_k is None and flash_prefill_supported(q, k, window, sink):
        return flash_prefill(q, k, v, window=window, sink=sink)
    return None
