"""Mixture-of-experts MLP.

Covers Qwen3-MoE (30b-a3b, 235b-a22b) and gpt-oss (20b/120b) from the
reference catalog (/root/reference/sutro/common.py:28-39). Two execution
paths behind one call:

- ``dense``: computes every expert for every token and combines with the
  gate matrix. Correct and simple; the E/top_k FLOP overhead is fine for
  tiny test models and small E.
- ``ragged``: sorts the (token, expert) assignments by expert and runs two
  grouped GEMMs via ``jax.lax.ragged_dot`` — the MXU-friendly path for
  large E. Static shapes: the expanded token count is exactly ``N * top_k``.

Router convention: softmax over the top-k logits (equivalent to
renormalized top-k of the full softmax — matches Qwen3's
``norm_topk_prob=True`` and gpt-oss).

Expert parallelism shards the expert axis of ``we_*`` over the mesh
"expert" axis; XLA turns the resulting gather/scatter into all-to-alls over
ICI (see parallel/sharding.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _grouped(lhs: jax.Array, rhs: jax.Array, group_sizes: jax.Array):
    """Grouped GEMM: the Pallas MXU kernel on TPU when shapes allow
    (ops/pallas_gmm.py), ``jax.lax.ragged_dot`` otherwise."""
    if jax.default_backend() == "tpu":
        from .pallas_gmm import grouped_matmul, grouped_matmul_supported

        if grouped_matmul_supported(lhs, rhs):
            return grouped_matmul(lhs, rhs, group_sizes)
    return jax.lax.ragged_dot(lhs, rhs, group_sizes)


def _route(
    xt: jax.Array,        # [N, H]
    router: jax.Array,    # [H, E]
    router_b,             # [E] or None
    top_k: int,
):
    """Shared routing: fp32 logits -> top-k -> renormalized softmax,
    plus the flattened [N*top_k] expansion (token, expert, prob) used by
    the grouped-GEMM paths. One definition so the EP path
    (ops/moe_ep.py) can never diverge from the single-device reference."""
    N = xt.shape[0]
    logits = xt.astype(jnp.float32) @ router.astype(jnp.float32)  # [N, E]
    if router_b is not None:
        logits = logits + router_b.astype(jnp.float32)
    top_logits, top_idx = jax.lax.top_k(logits, top_k)            # [N, K]
    probs = jax.nn.softmax(top_logits, axis=-1)
    M = N * top_k
    flat_expert = top_idx.reshape(M)
    flat_token = jnp.repeat(jnp.arange(N), top_k)
    flat_prob = probs.reshape(M)
    return top_idx, probs, flat_expert, flat_token, flat_prob


def _act(gate: jax.Array, up: jax.Array, activation: str):
    if activation == "gelu":
        a = jax.nn.gelu(gate.astype(jnp.float32), approximate=True)
        return a.astype(gate.dtype), up
    if activation == "swiglu_oss":
        g = jnp.clip(gate.astype(jnp.float32), max=7.0)
        a = (g * jax.nn.sigmoid(1.702 * g)).astype(gate.dtype)
        u = jnp.clip(up.astype(jnp.float32), -7.0, 7.0).astype(up.dtype) + 1.0
        return a, u
    a = jax.nn.silu(gate.astype(jnp.float32)).astype(gate.dtype)
    return a, up


def moe_mlp(
    x: jax.Array,          # [B, T, H]
    router: jax.Array,     # [H, E]
    we_gate: jax.Array,    # [E, H, F]
    we_up: jax.Array,      # [E, H, F]
    we_down: jax.Array,    # [E, F, H]
    *,
    top_k: int,
    activation: str = "silu",
    method: str = "auto",
    router_b: "jax.Array | None" = None,   # [E]
    bias_gate: "jax.Array | None" = None,  # [E, F]  (gpt-oss)
    bias_up: "jax.Array | None" = None,    # [E, F]
    bias_down: "jax.Array | None" = None,  # [E, H]
) -> jax.Array:
    B, T, H = x.shape
    E = router.shape[-1]
    N = B * T
    xt = x.reshape(N, H)

    top_idx, probs, flat_expert, flat_token, flat_prob = _route(
        xt, router, router_b, top_k
    )

    if method == "auto":
        method = "dense" if E <= 8 else "ragged"

    if method == "dense":
        gates = jnp.zeros((N, E), jnp.float32)
        gates = gates.at[jnp.arange(N)[:, None], top_idx].add(probs)
        g = jnp.einsum("nh,ehf->nef", xt, we_gate)
        u = jnp.einsum("nh,ehf->nef", xt, we_up)
        if bias_gate is not None:
            g = g + bias_gate[None].astype(g.dtype)
            u = u + bias_up[None].astype(u.dtype)
        a, u = _act(g, u, activation)
        y = jnp.einsum("nef,efh->neh", a * u, we_down)
        if bias_down is not None:
            y = y + bias_down[None].astype(y.dtype)
        out = jnp.einsum("ne,neh->nh", gates.astype(y.dtype), y)
        return out.reshape(B, T, H)

    # ragged grouped-GEMM path
    order = jnp.argsort(flat_expert)                      # stable order by expert
    sorted_expert = flat_expert[order]
    sorted_token = flat_token[order]
    sorted_prob = flat_prob[order]
    group_sizes = jnp.bincount(sorted_expert, length=E).astype(jnp.int32)

    lhs = xt[sorted_token]                                # [M, H]
    g = _grouped(lhs, we_gate, group_sizes)               # [M, F]
    u = _grouped(lhs, we_up, group_sizes)
    if bias_gate is not None:
        g = g + bias_gate[sorted_expert].astype(g.dtype)
        u = u + bias_up[sorted_expert].astype(u.dtype)
    a, u = _act(g, u, activation)
    y = _grouped(a * u, we_down, group_sizes)             # [M, H]
    if bias_down is not None:
        y = y + bias_down[sorted_expert].astype(y.dtype)
    y = y * sorted_prob[:, None].astype(y.dtype)
    out = jnp.zeros((N, H), y.dtype).at[sorted_token].add(y)
    return out.reshape(B, T, H)
