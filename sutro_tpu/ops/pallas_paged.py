"""Pallas TPU kernel: paged-KV decode attention.

The decode hot loop (SURVEY §7.3 "Paged-KV attention in Pallas"). For each
decode step the jnp fallback gathers a contiguous ``[B, CTX, KVH, Dh]``
view of the page pool per layer — a pure HBM copy that dominates decode
time at long context. This kernel instead reads K/V pages **in place**,
walking the page table via scalar prefetch, with flash-style online
softmax across pages:

- grid ``(B, KVH, MP)``: batch and kv-head are parallel; the page axis is
  sequential and carries running ``(m, l, acc)`` in VMEM scratch;
- page blocks are addressed by ``page_table[b, ki]`` in the BlockSpec
  index_map (scalar-prefetch — the DMA for page ``ki+1`` overlaps the
  compute on page ``ki``);
- pages at or beyond ``past_len[b]`` are skipped entirely (``pl.when``), so
  work is proportional to actual context, not table capacity;
- the current token's K/V (not yet in the page pool) and the optional
  gpt-oss attention sink join the softmax in the finalization step;
- per-layer sliding windows (Gemma3 / gpt-oss) are dynamic operands, so one
  compiled kernel serves every layer of the ``lax.scan``.

GQA is expressed by blocking q as ``[B, KVH, G, Dh]``; scores are
``[G, PS]`` per grid step. All math is float32.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _paged_decode_kernel(
    # scalar prefetch
    page_table_ref,   # [B * MP] int32 (flattened)
    past_len_ref,     # [B] int32
    window_ref,       # [1] int32 (0 = full attention)
    # operands
    q_ref,            # [1, 1, G, Dh]
    k_page_ref,       # [1, PS, 1, Dh]
    v_page_ref,       # [1, PS, 1, Dh]
    k_cur_ref,        # [1, 1, Dh]
    v_cur_ref,        # [1, 1, Dh]
    sink_ref,         # [1, G]
    # output
    out_ref,          # [1, 1, G, Dh]
    # scratch
    m_ref,            # [G, 128] f32
    l_ref,            # [G, 128] f32
    acc_ref,          # [G, Dh] f32
    *,
    num_pages_per_seq: int,
    page_size: int,
    scale: float,
):
    b = pl.program_id(0)
    ki = pl.program_id(2)
    PS = page_size
    G, Dh = q_ref.shape[2], q_ref.shape[3]

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    past = past_len_ref[b]
    pos = past  # current token's global position
    win = window_ref[0]
    page_start = ki * PS

    @pl.when(page_start < past)
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)           # [G, Dh]
        k = k_page_ref[0, :, 0].astype(jnp.float32)   # [PS, Dh]
        v = v_page_ref[0, :, 0].astype(jnp.float32)   # [PS, Dh]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                     # [G, PS]
        tok = page_start + jax.lax.broadcasted_iota(jnp.int32, (G, PS), 1)
        ok = tok < past
        ok = jnp.logical_and(
            ok, jnp.where(win > 0, pos - tok < win, True)
        )
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_ref[:, 0]                          # [G]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_new)               # [G]
        p = jnp.exp(s - m_new[:, None])               # [G, PS]
        l_new = l_ref[:, 0] * alpha + jnp.sum(p, axis=1)  # [G]
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)

    @pl.when(ki == num_pages_per_seq - 1)
    def _finalize():
        q = q_ref[0, 0].astype(jnp.float32)           # [G, Dh]
        k_cur = k_cur_ref[0, 0].astype(jnp.float32)   # [Dh]
        v_cur = v_cur_ref[0, 0].astype(jnp.float32)   # [Dh]
        sink = sink_ref[0].astype(jnp.float32)        # [G]

        s_self = jnp.sum(q * k_cur[None, :], axis=1) * scale  # [G]
        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.maximum(s_self, sink))
        alpha = jnp.exp(m_prev - m_new)
        p_self = jnp.exp(s_self - m_new)
        p_sink = jnp.exp(sink - m_new)
        l = l_ref[:, 0] * alpha + p_self + p_sink
        acc = acc_ref[...] * alpha[:, None] + p_self[:, None] * v_cur[None, :]
        out = acc / jnp.maximum(l, 1e-30)[:, None]
        out_ref[0, 0] = out.astype(out_ref.dtype)


def paged_decode_supported(
    q: jax.Array, k_pages: jax.Array
) -> bool:
    """Shape gate for the compiled TPU path (interpret mode has no such
    constraints — tests call paged_decode_attention(interpret=True))."""
    Dh = q.shape[-1]
    PS = k_pages.shape[1]
    return Dh % 128 == 0 and PS % 8 == 0


@functools.partial(
    jax.jit,
    static_argnames=("interpret",),
)
def paged_decode_attention(
    q: jax.Array,          # [B, NH, Dh] — current-step queries
    k_pages: jax.Array,    # [NP, PS, KVH, Dh] — one layer's page pool
    v_pages: jax.Array,
    page_table: jax.Array, # [B, MP] int32
    past_len: jax.Array,   # [B] int32 — tokens already in the cache
    k_cur: jax.Array,      # [B, KVH, Dh] — current token K (post-RoPE)
    v_cur: jax.Array,
    window: jax.Array,     # scalar int32; 0 => full attention
    sink: Optional[jax.Array] = None,   # [NH] logits or None
    *,
    interpret: bool = False,
) -> jax.Array:
    """Returns [B, NH, Dh] attention outputs for one decode step."""
    B, NH, Dh = q.shape
    NP, PS, KVH, _ = k_pages.shape
    MP = page_table.shape[1]
    G = NH // KVH
    scale = Dh ** -0.5

    qg = q.reshape(B, KVH, G, Dh)
    if sink is None:
        sink_g = jnp.full((KVH, G), NEG_INF, jnp.float32)
    else:
        sink_g = sink.astype(jnp.float32).reshape(KVH, G)

    kernel = functools.partial(
        _paged_decode_kernel,
        num_pages_per_seq=MP,
        page_size=PS,
        scale=scale,
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, KVH, MP),
        in_specs=[
            pl.BlockSpec(
                (1, 1, G, Dh), lambda b, h, ki, pt, pls, win: (b, h, 0, 0)
            ),
            pl.BlockSpec(
                (1, PS, 1, Dh),
                lambda b, h, ki, pt, pls, win: (pt[b * MP + ki], 0, h, 0),
            ),
            pl.BlockSpec(
                (1, PS, 1, Dh),
                lambda b, h, ki, pt, pls, win: (pt[b * MP + ki], 0, h, 0),
            ),
            pl.BlockSpec(
                (1, 1, Dh), lambda b, h, ki, pt, pls, win: (b, h, 0)
            ),
            pl.BlockSpec(
                (1, 1, Dh), lambda b, h, ki, pt, pls, win: (b, h, 0)
            ),
            pl.BlockSpec((1, G), lambda b, h, ki, pt, pls, win: (h, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, G, Dh), lambda b, h, ki, pt, pls, win: (b, h, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((G, 128), jnp.float32),
            pltpu.VMEM((G, 128), jnp.float32),
            pltpu.VMEM((G, Dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH, G, Dh), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        page_table.reshape(-1).astype(jnp.int32),
        past_len.astype(jnp.int32),
        jnp.asarray(window, jnp.int32).reshape(1),
        qg,
        k_pages,
        v_pages,
        k_cur,
        v_cur,
        sink_g,
    )
    return out.reshape(B, NH, Dh)
