"""Pallas TPU kernel: paged-KV decode attention.

The decode hot loop (SURVEY §7.3 "Paged-KV attention in Pallas"). For each
decode step the jnp fallback gathers a contiguous ``[B, CTX, KVH, Dh]``
view of the page pool per layer — a pure HBM copy that dominates decode
time. This kernel reads K/V pages **in place** with flash-style online
softmax across pages.

Design (second generation — the first used grid ``(B, MP)`` with one
BlockSpec-fetched page per grid step, which cost a block DMA for every
table slot, used or not, and ~µs of grid overhead per tiny block; at
28 layers x B=64 x MP=8 that grid tax dominated the whole decode step):

- grid ``(B,)``: one grid step per decode row;
- the page walk lives INSIDE the kernel as a ``fori_loop`` bounded by the
  row's ACTUAL page count (``ceil(past_len/PS)``) — unused table slots
  cost nothing;
- pages are fetched from the HBM-resident pool (``memory_space=ANY``)
  with double-buffered ``make_async_copy``: the DMA for page ``i+1``
  overlaps compute on page ``i``;
- KV heads are processed by a static in-kernel loop, one ``[G, PS]``
  score tile per head, accumulating ``(m, l, acc)`` in VMEM scratch;
- the current token's K/V, the optional multi-step decode window buffer
  (tokens sampled in the current fused window, not yet written to the
  pool — see engine/runner.decode_multi), and the optional gpt-oss
  attention sink all join the softmax in the finalization step;
- per-layer sliding windows (Gemma3 / gpt-oss) are dynamic operands, so
  one compiled kernel serves every layer of the ``lax.scan``.

All math is float32.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams; accept
# either so the kernels build across the jax versions we run on
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)

NEG_INF = -1e30


def _paged_decode_kernel(
    # scalar prefetch: page_table [B*MP], past_len [B], window [1],
    # then — in shared-prefix (Hydragen-style) mode — pfx_pages_cnt [B],
    # and — when the caller carries a decode window buffer — win_len [1]
    *refs,
    max_pages_per_seq: int,
    page_size: int,
    scale: float,
    kvh: int,
    window_slots: int = 0,
    chunk_pages: int = 1,
    cross_row: bool = False,
    quantized: bool = False,
    prefix: bool = False,
):
    # ref layout varies with (window_slots, quantized, prefix) — walk an
    # index instead of a per-case tuple unpack
    it = iter(refs)
    page_table_ref = next(it)
    past_len_ref = next(it)
    window_ref = next(it)
    pfx_cnt_ref = next(it) if prefix else None
    win_len_ref = next(it) if window_slots else None
    q_ref = next(it)
    k_pool_ref = next(it)
    v_pool_ref = next(it)
    ks_pool_ref = next(it) if quantized else None
    vs_pool_ref = next(it) if quantized else None
    k_cur_ref = next(it)
    v_cur_ref = next(it)
    wk_ref = next(it) if window_slots else None
    wv_ref = next(it) if window_slots else None
    m0_ref = next(it) if prefix else None
    l0_ref = next(it) if prefix else None
    acc0_ref = next(it) if prefix else None
    sink_ref = next(it)
    out_ref = next(it)
    kbuf = next(it)
    vbuf = next(it)
    ksem = next(it)
    vsem = next(it)
    ksbuf = next(it) if quantized else None
    vsbuf = next(it) if quantized else None
    kssem = next(it) if quantized else None
    vssem = next(it) if quantized else None
    m_ref = next(it)
    l_ref = next(it)
    acc_ref = next(it)

    b = pl.program_id(0)
    MP = max_pages_per_seq
    PS = page_size
    CH = chunk_pages
    CT = CH * PS  # tokens per fetched chunk
    NH = q_ref.shape[1]
    Dh = q_ref.shape[2]
    G = NH // kvh
    KD = kvh * Dh

    past = past_len_ref[b]
    nchunks = (past + CT - 1) // CT
    # current token's global position: tokens already in pages plus any
    # fused-window tokens not yet written back
    pos = past + (win_len_ref[0] if window_slots else 0)
    win = window_ref[0]

    # Block-diagonal queries: fold the per-KV-head loop into ONE score
    # matmul and ONE value matmul per chunk. Row i (= head i, KV head
    # i // G) of q_bd carries q[i] in column block i // G of the fused
    # [KVH*Dh] axis and zeros elsewhere, so q_bd @ k_chunk.T computes
    # every head's scores in a single MXU op (the off-block FLOPs are
    # wasted but free — the kernel is bound by op count / latency, not
    # MXU throughput: 2*KVH tiny per-head dots per chunk cost ~3x more
    # wall time than these two). Mosaic cannot merge (KVH, Dh) into the
    # lane dim in-kernel, so the page pool arrives pre-fused [.., KD]
    # and lane-space masks are built from iota instead of reshapes.
    q = q_ref[0].astype(jnp.float32)                      # [NH, Dh]
    row_head = jax.lax.broadcasted_iota(jnp.int32, (NH, KD), 0) // G
    col_head = jax.lax.broadcasted_iota(jnp.int32, (NH, KD), 1) // Dh
    blk_kd = (row_head == col_head).astype(jnp.float32)   # [NH, KD]
    q_rep = jnp.concatenate([q] * kvh, axis=1)            # [NH, KD]
    q_bd = q_rep * blk_kd
    # selector S[kd, d] = (kd % Dh == d): one dot extracts each row's
    # own head block from fused-lane space back to [NH, Dh]
    sel_kd = jax.lax.broadcasted_iota(jnp.int32, (KD, Dh), 0)
    sel_d = jax.lax.broadcasted_iota(jnp.int32, (KD, Dh), 1)
    S = (sel_kd % Dh == sel_d).astype(jnp.float32)        # [KD, Dh]

    # Shared-prefix (Hydragen-style) mode: the first pfx_cnt pages of
    # this row's table hold a prefix whose K/V is SHARED with other
    # rows. Their attention was computed ONCE for the whole batch
    # outside the kernel (prefix_attention_carry — the pages are read
    # from HBM once instead of once per row) and arrives as the initial
    # online-softmax carry; the page walk below starts AFTER them.
    # Non-member rows carry (m=-inf, l=0, acc=0) — exactly the cold
    # init — and start at page 0. Online softmax is associative, so the
    # result is bit-comparable to walking the prefix pages in-row.
    if prefix:
        m_ref[...] = jnp.broadcast_to(
            m0_ref[0][:, None].astype(jnp.float32), m_ref.shape
        )
        l_ref[...] = jnp.broadcast_to(
            l0_ref[0][:, None].astype(jnp.float32), l_ref.shape
        )
        acc_ref[...] = acc0_ref[0].astype(jnp.float32)
    else:
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # CH == 1: each chunk is one table-walked page (any layout).
    # CH > 1: the row's pages are one ascending run (contiguous-first
    # allocator) — chunk i is pages [start + i*CH, start + (i+1)*CH),
    # ONE DMA for CH pages instead of CH DMAs. The caller guarantees
    # CH-1 slack pages at the pool end so the final chunk's over-read
    # stays in bounds (over-read tokens are masked by ``tok < past``).
    #
    # cross_row: row b also starts row b+1's FIRST chunk after its own
    # page walk drains (all kbuf/vbuf reads done), so the next grid
    # step's warmup latency hides behind this row's finalize + the grid
    # transition. Slots are row-parity offset (chunk i of row r lives in
    # slot (r+i)%2) so the handed-over chunk lands where the next row's
    # walk expects it and never collides with a buffer still being read.
    # Requires "arbitrary" grid semantics (cross-step scratch flow).

    def _slot(row, i):
        return jax.lax.rem(row + i, 2) if cross_row else jax.lax.rem(i, 2)

    def k_dma(row, i, slot):
        if CH == 1:  # per-page walk: any table layout
            return pltpu.make_async_copy(
                k_pool_ref.at[page_table_ref[row * MP + i]],
                kbuf.at[slot, 0],
                ksem.at[slot],
            )
        return pltpu.make_async_copy(
            k_pool_ref.at[pl.ds(page_table_ref[row * MP] + i * CH, CH)],
            kbuf.at[slot],
            ksem.at[slot],
        )

    def v_dma(row, i, slot):
        if CH == 1:
            return pltpu.make_async_copy(
                v_pool_ref.at[page_table_ref[row * MP + i]],
                vbuf.at[slot, 0],
                vsem.at[slot],
            )
        return pltpu.make_async_copy(
            v_pool_ref.at[pl.ds(page_table_ref[row * MP] + i * CH, CH)],
            vbuf.at[slot],
            vsem.at[slot],
        )

    def _scale_dmas(row, i, slot):
        # int8 KV: the per-token dequant scales ride their own (tiny)
        # DMAs — pools arrive pre-shaped [NP, 1, PS] so the fetched
        # chunk lands lane-major [CH, 1, PS] and each page's scale row
        # is a legal [1, PS] broadcast against a score slice (merging
        # sublanes into lanes in-kernel is unsupported)
        if CH == 1:
            return (
                pltpu.make_async_copy(
                    ks_pool_ref.at[page_table_ref[row * MP + i]],
                    ksbuf.at[slot, 0],
                    kssem.at[slot],
                ),
                pltpu.make_async_copy(
                    vs_pool_ref.at[page_table_ref[row * MP + i]],
                    vsbuf.at[slot, 0],
                    vssem.at[slot],
                ),
            )
        start = page_table_ref[row * MP] + i * CH
        return (
            pltpu.make_async_copy(
                ks_pool_ref.at[pl.ds(start, CH)],
                ksbuf.at[slot],
                kssem.at[slot],
            ),
            pltpu.make_async_copy(
                vs_pool_ref.at[pl.ds(start, CH)],
                vsbuf.at[slot],
                vssem.at[slot],
            ),
        )

    def _start_chunk(row, i, slot):
        k_dma(row, i, slot).start()
        v_dma(row, i, slot).start()
        if quantized:
            for dma in _scale_dmas(row, i, slot):
                dma.start()

    def _chunks_of(row):
        return (past_len_ref[row] + CT - 1) // CT

    # shared-prefix mode: skip the prefix pages (their carry was
    # injected above). Requires CH == 1 and no cross_row (wrapper
    # enforces both), so chunk index == page index.
    i0 = pfx_cnt_ref[b] if prefix else 0

    # warmup: row 0 fetches its own first chunk; under cross_row every
    # later row's first chunk was started by its predecessor
    self_warm = (b == 0) if cross_row else (nchunks > i0)

    @pl.when(jnp.logical_and(self_warm, nchunks > i0))
    def _warmup():
        _start_chunk(b, i0, _slot(b, i0))

    def page_step(i, _):
        slot = _slot(b, i)
        nxt = _slot(b, i + 1)

        @pl.when(i + 1 < nchunks)
        def _prefetch_next():
            _start_chunk(b, i + 1, nxt)

        k_dma(b, i, slot).wait()
        v_dma(b, i, slot).wait()
        if quantized:
            for dma in _scale_dmas(b, i, slot):
                dma.wait()

        chunk_start = i * CT
        tok = chunk_start + jax.lax.broadcasted_iota(
            jnp.int32, (NH, CT), 1
        )
        ok = tok < past
        # windowless (win <= 0) ORed in instead of a boolean select —
        # Mosaic cannot legalize arith.select on i1 vectors
        ok = jnp.logical_and(
            ok, jnp.logical_or(pos - tok < win, win <= 0)
        )
        # [CH, PS, KD] -> [CT, KD]: leading-dim collapse only (the lane
        # dim KD is untouched — Mosaic supports this shape cast)
        k = kbuf[slot].reshape(CT, KD).astype(jnp.float32)
        v = vbuf[slot].reshape(CT, KD).astype(jnp.float32)
        s = jax.lax.dot_general(
            q_bd, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                        # [NH, CT]
        if quantized:
            # K dequant folds into the scores: q.(k_int*ks) = (q.k_int)*ks
            # — one [1, PS] lane-broadcast multiply per page of the
            # chunk (CH is static), lane-concatenated back to [NH, CT]
            s = jnp.concatenate(
                [
                    s[:, pg * PS : (pg + 1) * PS] * ksbuf[slot, pg]
                    for pg in range(CH)
                ],
                axis=1,
            )
        s = jnp.where(ok, s, NEG_INF)

        m_prev = m_ref[:, 0]                             # [NH]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_new)                  # [NH]
        p = jnp.exp(s - m_new[:, None])                  # [NH, CT]
        l_new = l_ref[:, 0] * alpha + jnp.sum(p, axis=1)
        l_ref[...] = jnp.broadcast_to(l_new[:, None], l_ref.shape)
        if quantized:
            # V dequant folds into the probabilities for the value dot
            # ONLY — the normalizer l above sums the true p:
            # p.(v_int*vs) = (p*vs).v_int
            pv = jnp.concatenate(
                [
                    p[:, pg * PS : (pg + 1) * PS] * vsbuf[slot, pg]
                    for pg in range(CH)
                ],
                axis=1,
            )
        else:
            pv = p
        # acc holds the full [NH, KVH*Dh] product; only each row's own
        # head block is meaningful (extracted at the end)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            pv, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        return 0

    jax.lax.fori_loop(i0, nchunks, page_step, 0)

    if cross_row:
        # hand off: start the NEXT row's first chunk now that every DMA
        # of this row has been waited (both slots idle). The matching
        # wait is the next grid step's page_step(0) on slot (b+1)%2 —
        # predicated on the same ``nchunks > 0`` so semaphores balance.
        nb = b + 1
        # clamp the probe: logical_and evaluates both operands, so the
        # last row must not read past_len_ref[B] (OOB SMEM on hardware)
        nb_c = jnp.minimum(nb, pl.num_programs(0) - 1)

        @pl.when(jnp.logical_and(nb < pl.num_programs(0), _chunks_of(nb_c) > 0))
        def _handoff():
            _start_chunk(nb, 0, _slot(nb, 0))

    # finalize: fused-window tokens + current token + attention sink,
    # in the same block-diagonal space (2 dots total, not 2 per head)
    W = window_slots
    k_cur = k_cur_ref[0].astype(jnp.float32)             # [1, KD]
    v_cur = v_cur_ref[0].astype(jnp.float32)             # [1, KD]
    sink = sink_ref[0].astype(jnp.float32)               # [NH]

    s_self = jax.lax.dot_general(
        q_bd, k_cur, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )[:, 0] * scale                                      # [NH]
    m_prev = m_ref[:, 0]
    m_new = jnp.maximum(m_prev, jnp.maximum(s_self, sink))
    if W:
        # window tokens: slot s holds the fused window's s-th sampled
        # token at position past+s; the query is at pos
        wlen = win_len_ref[0]
        wk = wk_ref[0].astype(jnp.float32)               # [W, KD]
        wv = wv_ref[0].astype(jnp.float32)
        slot_i = jax.lax.broadcasted_iota(jnp.int32, (NH, W), 1)
        ok_w = slot_i < wlen
        ok_w = jnp.logical_and(
            ok_w,
            jnp.logical_or(wlen - slot_i < win, win <= 0),
        )
        s_w = jax.lax.dot_general(
            q_bd, wk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale                                        # [NH, W]
        s_w = jnp.where(ok_w, s_w, NEG_INF)
        m_new = jnp.maximum(m_new, jnp.max(s_w, axis=1))
    alpha = jnp.exp(m_prev - m_new)
    p_self = jnp.exp(s_self - m_new)
    p_sink = jnp.exp(sink - m_new)
    l = l_ref[:, 0] * alpha + p_self + p_sink
    acc = acc_ref[...] * alpha[:, None] + p_self[:, None] * v_cur
    if W:
        p_w = jnp.exp(s_w - m_new[:, None])              # [NH, W]
        l = l + jnp.sum(p_w, axis=1)
        acc = acc + jax.lax.dot_general(
            p_w, wv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    # extract each row's own head block from the block-diagonal acc:
    # zero the off-blocks, then sum the lane blocks with the selector dot
    acc_bd = jax.lax.dot_general(
        acc * blk_kd, S, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                    # [NH, Dh]
    out = acc_bd / jnp.maximum(l, 1e-30)[:, None]
    out_ref[0] = out.astype(out_ref.dtype)


def prefix_attention_carry(
    q: jax.Array,            # [B, NH, Dh] current-step queries
    k_pages: jax.Array,      # [NP, PS, KVH*Dh] one layer's page pool
    v_pages: jax.Array,
    pfx_pages: jax.Array,    # [Pp] int32 — the SHARED prefix's pages
    pfx_len: jax.Array,      # [B] int32 — prefix tokens per row (0 for
    #                          rows outside the prefix group)
    q_pos: jax.Array,        # [B] int32 — each query's global position
    window: jax.Array,       # scalar int32; 0 => full attention
    k_scale: Optional[jax.Array] = None,  # [NP, PS] int8-KV scales
    v_scale: Optional[jax.Array] = None,
):
    """Online-softmax carry ``(m0, l0, acc0)`` of attention over a
    job-shared page-aligned prefix, computed ONCE for the whole batch
    (Hydragen / cascade-inference decomposition: the prefix K/V is the
    same physical pages for every member row, so one [Pp] gather reads
    them from HBM once per layer per step instead of once per row
    inside the paged kernel's per-row walk).

    Returned in the paged kernel's spaces for direct carry injection
    (``paged_decode_attention(..., pfx_cnt, m0, l0, acc0)``): m0/l0
    ``[B, NH]`` f32, acc0 ``[B, NH, KVH*Dh]`` f32 block-diagonal (each
    query row's accumulator sits in its own KV head's lane block).
    Rows with ``pfx_len == 0`` get the cold carry (-inf, 0, 0) — inside
    the kernel they are indistinguishable from non-prefix rows.
    Softmax-associativity makes the final attention equal to walking
    the prefix pages in-row (same f32 math, different summation order).
    """
    B, NH, Dh = q.shape
    NP, PS, KD = k_pages.shape
    KVH = KD // Dh
    G = NH // KVH
    scale = Dh ** -0.5
    Pp = pfx_pages.shape[0]
    Lp = Pp * PS

    kp = k_pages[pfx_pages].astype(jnp.float32)      # [Pp, PS, KD]
    vp = v_pages[pfx_pages].astype(jnp.float32)
    if k_scale is not None:
        kp = kp * k_scale[pfx_pages][..., None].astype(jnp.float32)
        vp = vp * v_scale[pfx_pages][..., None].astype(jnp.float32)
    kp = kp.reshape(Lp, KVH, Dh)
    vp = vp.reshape(Lp, KVH, Dh)

    qg = q.reshape(B, KVH, G, Dh).astype(jnp.float32)
    s = jnp.einsum("bkgd,lkd->bkgl", qg, kp) * scale  # [B, KVH, G, Lp]
    t = jnp.arange(Lp, dtype=jnp.int32)
    ok = t[None, :] < pfx_len[:, None]                # [B, Lp]
    win = jnp.asarray(window, jnp.int32)
    ok = jnp.logical_and(
        ok,
        jnp.logical_or(
            (q_pos[:, None] - t[None, :]) < win, win <= 0
        ),
    )
    okb = ok[:, None, None, :]
    s = jnp.where(okb, s, NEG_INF)
    m = jnp.max(s, axis=-1)                           # [B, KVH, G]
    # p computed under the mask, NOT as exp(s - m): an all-masked row
    # has m = -inf and exp(-inf - -inf) would be 1, not 0
    p = jnp.where(okb, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bkgl,lkd->bkgd", p, vp)         # [B, KVH, G, Dh]

    m0 = m.reshape(B, NH)
    l0 = l.reshape(B, NH)
    # block-diagonal fused space: query row i's accumulator goes into
    # lane block i // G
    head = jnp.arange(NH, dtype=jnp.int32) // G       # [NH]
    onehot = jax.nn.one_hot(head, KVH, dtype=jnp.float32)  # [NH, KVH]
    acc0 = jnp.einsum(
        "bnd,nk->bnkd", acc.reshape(B, NH, Dh), onehot
    ).reshape(B, NH, KD)
    return m0, l0, acc0


def _prefix_carry_kernel(
    # scalar prefetch: pfx_pages [Pp] int32 (drives the K/V index maps)
    pages_ref,
    q_bd_ref,      # [B*NH, KD] f32 block-diagonal queries (resident)
    k_page_ref,    # [1, PS, KD] — THE prefix page for this grid step,
    #                fetched in place from the HBM pool by the
    #                page-indexed BlockSpec index map (no gather)
    v_page_ref,
    ok_ref,        # [1, B, PS] f32 0/1 — combined len+window mask
    m_out_ref,     # [B*NH, 128] f32 (lane-broadcast; caller takes [:,0])
    l_out_ref,
    acc_out_ref,   # [B*NH, KD] f32 block-diagonal accumulator
    m_ref, l_ref, acc_ref,  # VMEM scratch carries across grid steps
    *, scale: float, n_heads: int,
):
    p = pl.program_id(0)
    BNH, KD = acc_ref.shape
    PS = k_page_ref.shape[1]
    B = BNH // n_heads

    @pl.when(p == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_bd = q_bd_ref[...].astype(jnp.float32)            # [BNH, KD]
    k = k_page_ref[0].astype(jnp.float32)               # [PS, KD]
    v = v_page_ref[0].astype(jnp.float32)
    # [B, PS] row mask -> every head of row b shares it: sublane
    # broadcast then leading-dim collapse (the only reshape Mosaic
    # supports — the lane dim PS is untouched)
    ok = jnp.broadcast_to(
        ok_ref[0][:, None, :], (B, n_heads, PS)
    ).reshape(BNH, PS) > 0.0
    s = jax.lax.dot_general(
        q_bd, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                           # [BNH, PS]
    s = jnp.where(ok, s, NEG_INF)
    m_prev = m_ref[:, 0]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
    alpha = jnp.exp(m_prev - m_new)
    # p under the mask, NOT bare exp(s - m): an all-masked step keeps
    # m_new = -inf and exp(-inf - -inf) would contribute 1, not 0
    pr = jnp.where(ok, jnp.exp(s - m_new[:, None]), 0.0)
    l_ref[...] = jnp.broadcast_to(
        (l_ref[:, 0] * alpha + jnp.sum(pr, axis=1))[:, None],
        l_ref.shape,
    )
    acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
        pr, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[...] = jnp.broadcast_to(m_new[:, None], m_ref.shape)

    @pl.when(p == pl.num_programs(0) - 1)
    def _writeback():
        m_out_ref[...] = m_ref[...]
        l_out_ref[...] = l_ref[...]
        acc_out_ref[...] = acc_ref[...]


def prefix_carry_supported(
    q: jax.Array, k_pages: jax.Array,
    k_scale: Optional[jax.Array] = None,
) -> bool:
    """Gate for the in-place Pallas prefix-carry kernel. int8-KV rides
    the XLA-gather fallback (the dequant-scale plumbing isn't worth a
    second kernel variant for a cache whose pages are read once per
    step either way)."""
    Dh = q.shape[-1]
    PS = k_pages.shape[1]
    return Dh % 128 == 0 and PS % 8 == 0 and k_scale is None


@functools.partial(jax.jit, static_argnames=("interpret",))
def prefix_attention_carry_pallas(
    q: jax.Array,            # [B, NH, Dh]
    k_pages: jax.Array,      # [NP, PS, KVH*Dh]
    v_pages: jax.Array,
    pfx_pages: jax.Array,    # [Pp] int32
    pfx_len: jax.Array,      # [B] int32
    q_pos: jax.Array,        # [B] int32
    window: jax.Array,       # scalar int32; 0 => full attention
    *,
    interpret: bool = False,
):
    """``prefix_attention_carry`` with the shared pages read IN PLACE:
    grid ``(Pp,)`` over the prefix's pages, each step's K/V block
    fetched straight out of the HBM page pool by a page-indexed
    BlockSpec index map (``pages_ref[p]``) — the [Pp, PS, KD] gather
    copy the XLA path materializes per layer per step never exists.
    Sequential grid; the online-softmax carry lives in VMEM scratch and
    writes back on the last page. Bit-comparable to the XLA path: same
    f32 math in the same per-page order."""
    B, NH, Dh = q.shape
    NP, PS, KD = k_pages.shape
    KVH = KD // Dh
    G = NH // KVH
    scale = Dh ** -0.5
    Pp = pfx_pages.shape[0]
    Lp = Pp * PS

    # block-diagonal fused queries (XLA side — reshapes are free here):
    # row b*NH+n carries q[b, n] in lane block n // G, zeros elsewhere
    row_head = jax.lax.broadcasted_iota(jnp.int32, (NH, KD), 0) // G
    col_head = jax.lax.broadcasted_iota(jnp.int32, (NH, KD), 1) // Dh
    blk = (row_head == col_head).astype(jnp.float32)     # [NH, KD]
    q_rep = jnp.concatenate([q.astype(jnp.float32)] * KVH, axis=-1)
    q_bd = (q_rep * blk[None]).reshape(B * NH, KD)

    # combined length+window mask, page-major [Pp, B, PS] so each grid
    # step loads its page's [B, PS] slab
    t = jnp.arange(Lp, dtype=jnp.int32)
    ok = t[None, :] < pfx_len[:, None]                   # [B, Lp]
    win = jnp.asarray(window, jnp.int32)
    ok = jnp.logical_and(
        ok,
        jnp.logical_or((q_pos[:, None] - t[None, :]) < win, win <= 0),
    )
    ok_pg = (
        ok.astype(jnp.float32).reshape(B, Pp, PS).swapaxes(0, 1)
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(Pp,),
        in_specs=[
            pl.BlockSpec((B * NH, KD), lambda p, pages: (0, 0)),
            # THE in-place read: this step's block is HBM page
            # pages[p] of the pool, DMA'd by the pipeline itself
            pl.BlockSpec((1, PS, KD), lambda p, pages: (pages[p], 0, 0)),
            pl.BlockSpec((1, PS, KD), lambda p, pages: (pages[p], 0, 0)),
            pl.BlockSpec((1, B, PS), lambda p, pages: (p, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((B * NH, 128), lambda p, pages: (0, 0)),
            pl.BlockSpec((B * NH, 128), lambda p, pages: (0, 0)),
            pl.BlockSpec((B * NH, KD), lambda p, pages: (0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((B * NH, 128), jnp.float32),
            pltpu.VMEM((B * NH, 128), jnp.float32),
            pltpu.VMEM((B * NH, KD), jnp.float32),
        ],
    )
    m_o, l_o, acc_o = pl.pallas_call(
        functools.partial(
            _prefix_carry_kernel, scale=scale, n_heads=NH
        ),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B * NH, 128), jnp.float32),
            jax.ShapeDtypeStruct((B * NH, 128), jnp.float32),
            jax.ShapeDtypeStruct((B * NH, KD), jnp.float32),
        ],
        # the carry threads scratch state page to page: sequential grid
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(pfx_pages.astype(jnp.int32), q_bd, k_pages, v_pages, ok_pg)
    m0 = m_o[:, 0].reshape(B, NH)
    l0 = l_o[:, 0].reshape(B, NH)
    # the kernel's value matmul fills every lane; only each row's own
    # head block is meaningful — zero the off-blocks (XLA side) so the
    # carry is exactly the XLA path's block-diagonal acc0 and group
    # sums stay garbage-free
    acc0 = acc_o.reshape(B, NH, KD) * blk[None]
    return m0, l0, acc0


# Below this table capacity (tokens) the XLA gather fallback wins on
# grid/DMA overhead. With the in-kernel page walk the kernel's work is
# proportional to ACTUAL context, so it wins essentially everywhere —
# the gate is kept env-overridable for benchmarking the crossover.
PALLAS_PAGED_MIN_CTX = int(
    os.environ.get("SUTRO_PAGED_MIN_CTX", "0")
)

# Cross-row DMA warmup: each row starts the next row's first chunk as
# soon as its own page walk drains, hiding per-row first-fetch latency
# behind finalize + grid transition. Costs "arbitrary" grid semantics
# (rows run sequentially on one core) — free on single-TensorCore chips
# (v5e); on megacore parts (v4/v5p) "parallel" row-splitting may win
# instead. Default OFF until chip-validated (interpret mode cannot model
# DMA/semaphore timing): SUTRO_KV_XROW=1 enables.
PALLAS_PAGED_XROW = os.environ.get("SUTRO_KV_XROW", "0") == "1"


def chunk_pages_for(
    page_size: int,
    max_pages_per_seq: int,
    kv_heads: int = 8,
    head_dim: int = 128,
    dtype_bytes: int = 2,
    budget_bytes: int = 1 << 20,
) -> int:
    """Pages fetched per DMA in contiguous-KV mode: the largest divisor
    of MP whose chunk stays under ``budget_bytes`` PER double-buffer
    slot (4 buffers total: K+V x 2 slots — 1 MiB each keeps the scratch
    well inside ~16 MiB VMEM alongside m/l/acc). Callers enabling
    chunked fetch must (a) allocate slots as contiguous page runs and
    (b) leave ``chunk-1`` unallocatable slack pages at the pool end for
    the final chunk's masked over-read (engine/runner)."""
    page_bytes = max(page_size * kv_heads * head_dim * dtype_bytes, 1)
    budget = max(1, budget_bytes // page_bytes)
    ch = min(max_pages_per_seq, budget)
    while ch > 1 and max_pages_per_seq % ch:
        ch -= 1
    return max(ch, 1)


def paged_decode_supported(
    q: jax.Array, k_pages: jax.Array, page_table: jax.Array
) -> bool:
    """Shape/size gate for the compiled TPU path (interpret mode has no
    such constraints — tests call paged_decode_attention(interpret=True))."""
    Dh = q.shape[-1]
    PS = k_pages.shape[1]
    ctx_capacity = page_table.shape[1] * PS
    return (
        Dh % 128 == 0 and PS % 8 == 0
        and ctx_capacity >= PALLAS_PAGED_MIN_CTX
    )


@functools.partial(
    jax.jit,
    static_argnames=("kv_chunk", "interpret", "cross_row"),
)
def paged_decode_attention(
    q: jax.Array,          # [B, NH, Dh] — current-step queries
    k_pages: jax.Array,    # [NP, PS, KVH*Dh] — one layer's FUSED page pool
    v_pages: jax.Array,
    page_table: jax.Array, # [B, MP] int32
    past_len: jax.Array,   # [B] int32 — tokens already in the cache
    k_cur: jax.Array,      # [B, KVH, Dh] — current token K (post-RoPE)
    v_cur: jax.Array,
    window: jax.Array,     # scalar int32; 0 => full attention
    sink: Optional[jax.Array] = None,   # [NH] logits or None
    win_k: Optional[jax.Array] = None,  # [B, W, KVH*Dh] fused-window K
    win_v: Optional[jax.Array] = None,
    win_len: Optional[jax.Array] = None,  # scalar int32 — valid slots
    *,
    kv_chunk: int = 1,  # pages per DMA (>1 requires contiguous runs)
    interpret: bool = False,
    cross_row: Optional[bool] = None,  # None => PALLAS_PAGED_XROW
    # int8 KV mode: pages are int8 and these carry the per-token
    # dequant scales [NP, PS] f32 (engine/kvcache.py)
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    # shared-prefix (Hydragen-style) mode: rows whose table head holds a
    # job-shared prefix skip those pages (pfx_cnt[b] of them) and start
    # from the injected online-softmax carry (prefix_attention_carry) —
    # the shared pages are then read from HBM once per step for the
    # whole batch instead of once per row. Forces kv_chunk=1, no
    # cross_row.
    pfx_cnt: Optional[jax.Array] = None,   # [B] int32 pages to skip
    m0: Optional[jax.Array] = None,        # [B, NH] f32
    l0: Optional[jax.Array] = None,        # [B, NH] f32
    acc0: Optional[jax.Array] = None,      # [B, NH, KVH*Dh] f32 (block-diag)
) -> jax.Array:
    """Returns [B, NH, Dh] attention outputs for one decode step.

    The page pools carry the fused ``[NP, PS, KVH*Dh]`` layout
    (engine/kvcache.py): the kernel's block-diagonal matmuls contract
    over exactly that axis. The small per-step tensors (k_cur, win_k,
    sink) are reshaped into the fused layout HERE, outside the kernel,
    where XLA reshapes are free.

    ``win_k/win_v/win_len`` carry the multi-step decode window buffer
    (engine/runner decode_multi): tokens sampled earlier in the fused
    window whose K/V have NOT been written to the page pool yet — the
    bulk page write happens once per window, outside the step scan, so
    the multi-GB pool is never copied per step."""
    B, NH, Dh = q.shape
    NP, PS, KD = k_pages.shape
    KVH = k_cur.shape[1]
    MP = page_table.shape[1]
    scale = Dh ** -0.5
    W = 0 if win_k is None else win_k.shape[1]

    if sink is None:
        sink_g = jnp.full((1, NH), NEG_INF, jnp.float32)
    else:
        sink_g = sink.astype(jnp.float32).reshape(1, NH)

    if cross_row is None:
        cross_row = PALLAS_PAGED_XROW
    quantized = k_scale is not None
    prefix = pfx_cnt is not None
    if prefix:
        # carry injection needs chunk index == page index, and the
        # cross-row handoff fetches the next row's chunk 0 which a
        # prefix row would skip
        assert kv_chunk == 1, "shared-prefix mode requires kv_chunk=1"
        cross_row = False
    kernel = functools.partial(
        _paged_decode_kernel,
        max_pages_per_seq=MP,
        page_size=PS,
        scale=scale,
        kvh=KVH,
        window_slots=W,
        chunk_pages=kv_chunk,
        cross_row=cross_row,
        quantized=quantized,
        prefix=prefix,
    )

    # index maps take *s so the scalar-prefetch arity (3 without a
    # window buffer, 4 with) needs no per-case lambdas
    in_specs = [
        pl.BlockSpec((1, NH, Dh), lambda b, *s: (b, 0, 0)),
        pl.BlockSpec(memory_space=pl.ANY),  # K pool stays in HBM
        pl.BlockSpec(memory_space=pl.ANY),  # V pool stays in HBM
    ]
    scalars = [
        page_table.reshape(-1).astype(jnp.int32),
        past_len.astype(jnp.int32),
        jnp.asarray(window, jnp.int32).reshape(1),
    ]
    if prefix:
        scalars.append(pfx_cnt.astype(jnp.int32))
    operands = [
        q,
        k_pages,
        v_pages,
    ]
    if quantized:
        # pre-shaped [NP, 1, PS]: the kernel's scale chunks land
        # lane-major (see _scale_dmas)
        in_specs += [
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ]
        operands += [
            k_scale.astype(jnp.float32).reshape(NP, 1, PS),
            v_scale.astype(jnp.float32).reshape(NP, 1, PS),
        ]
    in_specs += [
        pl.BlockSpec((1, 1, KD), lambda b, *s: (b, 0, 0)),
        pl.BlockSpec((1, 1, KD), lambda b, *s: (b, 0, 0)),
    ]
    operands += [
        k_cur.reshape(B, 1, KD),
        v_cur.reshape(B, 1, KD),
    ]
    if W:
        scalars.append(jnp.asarray(win_len, jnp.int32).reshape(1))
        in_specs += [
            pl.BlockSpec((1, W, KD), lambda b, *s: (b, 0, 0)),
            pl.BlockSpec((1, W, KD), lambda b, *s: (b, 0, 0)),
        ]
        operands += [win_k, win_v]
    if prefix:
        in_specs += [
            pl.BlockSpec((1, NH), lambda b, *s: (b, 0)),
            pl.BlockSpec((1, NH), lambda b, *s: (b, 0)),
            pl.BlockSpec((1, NH, KD), lambda b, *s: (b, 0, 0)),
        ]
        operands += [
            m0.astype(jnp.float32),
            l0.astype(jnp.float32),
            acc0.astype(jnp.float32),
        ]
    in_specs.append(pl.BlockSpec((1, NH), lambda b, *s: (0, 0)))
    operands.append(sink_g)

    scratch_shapes = [
        # K/V double-buffers: [2, chunk, PS, KD]
        pltpu.VMEM((2, kv_chunk, PS, KD), k_pages.dtype),
        pltpu.VMEM((2, kv_chunk, PS, KD), v_pages.dtype),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
    ]
    if quantized:
        scratch_shapes += [
            # per-token scale double-buffers, lane-major [.., 1, PS]
            pltpu.VMEM((2, kv_chunk, 1, PS), jnp.float32),
            pltpu.VMEM((2, kv_chunk, 1, PS), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ]
    scratch_shapes += [
        pltpu.VMEM((NH, 128), jnp.float32),          # m
        pltpu.VMEM((NH, 128), jnp.float32),          # l
        pltpu.VMEM((NH, KD), jnp.float32),           # block-diag acc
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, NH, Dh), lambda b, *s: (b, 0, 0)),
        scratch_shapes=scratch_shapes,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, NH, Dh), q.dtype),
        # without cross-row warmup, batch rows are independent (disjoint
        # out rows, scratch reinitialized per step) and "parallel" lets
        # megacore TPUs split the grid; the cross-row handoff threads
        # DMA state between steps and needs sequential "arbitrary" rows
        compiler_params=_CompilerParams(
            dimension_semantics=(
                "arbitrary" if cross_row else "parallel",
            ),
        ),
        interpret=interpret,
    )(*scalars, *operands)
