"""Pallas TPU kernel: paged-KV decode attention.

The decode hot loop (SURVEY §7.3 "Paged-KV attention in Pallas"). For each
decode step the jnp fallback gathers a contiguous ``[B, CTX, KVH, Dh]``
view of the page pool per layer — a pure HBM copy that dominates decode
time at long context. This kernel instead reads K/V pages **in place**,
walking the page table via scalar prefetch, with flash-style online
softmax across pages:

- grid ``(B, MP)``: batch is parallel; the page axis is sequential and
  carries running ``(m, l, acc)`` per KV head in VMEM scratch;
- page blocks are addressed by ``page_table[b, ki]`` in the BlockSpec
  index_map (scalar-prefetch — the DMA for page ``ki+1`` overlaps the
  compute on page ``ki``);
- each block carries the page's full ``[PS, KVH, Dh]`` tile (Mosaic
  requires the trailing two block dims to be full or (8,128)-aligned;
  blocking a single KV head would put a size-1 block on the KVH axis,
  which the TPU lowering rejects). KV heads are processed by a static
  in-kernel loop, one ``[G, PS]`` score tile per head;
- pages at or beyond ``past_len[b]`` are skipped entirely (``pl.when``), so
  work is proportional to actual context, not table capacity;
- the current token's K/V (not yet in the page pool) and the optional
  gpt-oss attention sink join the softmax in the finalization step;
- per-layer sliding windows (Gemma3 / gpt-oss) are dynamic operands, so one
  compiled kernel serves every layer of the ``lax.scan``.

All math is float32.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _paged_decode_kernel(
    # scalar prefetch
    page_table_ref,   # [B * MP] int32 (flattened)
    past_len_ref,     # [B] int32
    window_ref,       # [1] int32 (0 = full attention)
    # operands
    q_ref,            # [1, KVH, G, Dh]
    k_page_ref,       # [1, PS, KVH, Dh]
    v_page_ref,       # [1, PS, KVH, Dh]
    k_cur_ref,        # [1, KVH, Dh]
    v_cur_ref,        # [1, KVH, Dh]
    sink_ref,         # [KVH, G]
    # output
    out_ref,          # [1, KVH, G, Dh]
    # scratch
    m_ref,            # [KVH, G, 128] f32
    l_ref,            # [KVH, G, 128] f32
    acc_ref,          # [KVH, G, Dh] f32
    *,
    num_pages_per_seq: int,
    page_size: int,
    scale: float,
    kvh: int,
):
    b = pl.program_id(0)
    ki = pl.program_id(1)
    PS = page_size
    G = q_ref.shape[2]

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    past = past_len_ref[b]
    pos = past  # current token's global position
    win = window_ref[0]
    page_start = ki * PS

    @pl.when(page_start < past)
    def _accumulate():
        tok = page_start + jax.lax.broadcasted_iota(jnp.int32, (G, PS), 1)
        ok = tok < past
        # windowless (win <= 0) ORed in instead of a boolean select —
        # Mosaic cannot legalize arith.select on i1 vectors
        ok = jnp.logical_and(
            ok, jnp.logical_or(pos - tok < win, win <= 0)
        )
        for h in range(kvh):  # static unroll over KV heads
            q = q_ref[0, h].astype(jnp.float32)            # [G, Dh]
            k = k_page_ref[0, :, h, :].astype(jnp.float32)  # [PS, Dh]
            v = v_page_ref[0, :, h, :].astype(jnp.float32)  # [PS, Dh]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                      # [G, PS]
            s = jnp.where(ok, s, NEG_INF)

            m_prev = m_ref[h, :, 0]                        # [G]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
            alpha = jnp.exp(m_prev - m_new)                # [G]
            p = jnp.exp(s - m_new[:, None])                # [G, PS]
            l_new = l_ref[h, :, 0] * alpha + jnp.sum(p, axis=1)
            l_ref[h] = jnp.broadcast_to(
                l_new[:, None], l_ref.shape[1:]
            )
            acc_ref[h] = acc_ref[h] * alpha[:, None] + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[h] = jnp.broadcast_to(
                m_new[:, None], m_ref.shape[1:]
            )

    @pl.when(ki == num_pages_per_seq - 1)
    def _finalize():
        for h in range(kvh):
            q = q_ref[0, h].astype(jnp.float32)            # [G, Dh]
            k_cur = k_cur_ref[0, h].astype(jnp.float32)    # [Dh]
            v_cur = v_cur_ref[0, h].astype(jnp.float32)    # [Dh]
            sink = sink_ref[h].astype(jnp.float32)         # [G]

            s_self = jnp.sum(q * k_cur[None, :], axis=1) * scale  # [G]
            m_prev = m_ref[h, :, 0]
            m_new = jnp.maximum(m_prev, jnp.maximum(s_self, sink))
            alpha = jnp.exp(m_prev - m_new)
            p_self = jnp.exp(s_self - m_new)
            p_sink = jnp.exp(sink - m_new)
            l = l_ref[h, :, 0] * alpha + p_self + p_sink
            acc = (
                acc_ref[h] * alpha[:, None]
                + p_self[:, None] * v_cur[None, :]
            )
            out = acc / jnp.maximum(l, 1e-30)[:, None]
            out_ref[0, h] = out.astype(out_ref.dtype)


# Below this table capacity (tokens) the XLA gather fallback wins: the
# gathered view is small, while the kernel pays per-grid-step overhead on
# B x MP tiny blocks per layer. Above it, gather traffic grows with
# capacity but the kernel's work stays proportional to *actual* context.
# Crossover measured on v5e (qwen3-0.6b, B=64): gather 4.5 ms vs kernel
# 12.9 ms at 384-token tables; gather scales ~linearly past that.
PALLAS_PAGED_MIN_CTX = 1024


def paged_decode_supported(
    q: jax.Array, k_pages: jax.Array, page_table: jax.Array
) -> bool:
    """Shape/size gate for the compiled TPU path (interpret mode has no
    such constraints — tests call paged_decode_attention(interpret=True))."""
    Dh = q.shape[-1]
    PS = k_pages.shape[1]
    ctx_capacity = page_table.shape[1] * PS
    return (
        Dh % 128 == 0 and PS % 8 == 0
        and ctx_capacity >= PALLAS_PAGED_MIN_CTX
    )


@functools.partial(
    jax.jit,
    static_argnames=("interpret",),
)
def paged_decode_attention(
    q: jax.Array,          # [B, NH, Dh] — current-step queries
    k_pages: jax.Array,    # [NP, PS, KVH, Dh] — one layer's page pool
    v_pages: jax.Array,
    page_table: jax.Array, # [B, MP] int32
    past_len: jax.Array,   # [B] int32 — tokens already in the cache
    k_cur: jax.Array,      # [B, KVH, Dh] — current token K (post-RoPE)
    v_cur: jax.Array,
    window: jax.Array,     # scalar int32; 0 => full attention
    sink: Optional[jax.Array] = None,   # [NH] logits or None
    *,
    interpret: bool = False,
) -> jax.Array:
    """Returns [B, NH, Dh] attention outputs for one decode step."""
    B, NH, Dh = q.shape
    NP, PS, KVH, _ = k_pages.shape
    MP = page_table.shape[1]
    G = NH // KVH
    scale = Dh ** -0.5

    qg = q.reshape(B, KVH, G, Dh)
    if sink is None:
        sink_g = jnp.full((KVH, G), NEG_INF, jnp.float32)
    else:
        sink_g = sink.astype(jnp.float32).reshape(KVH, G)

    kernel = functools.partial(
        _paged_decode_kernel,
        num_pages_per_seq=MP,
        page_size=PS,
        scale=scale,
        kvh=KVH,
    )

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, MP),
        in_specs=[
            pl.BlockSpec(
                (1, KVH, G, Dh), lambda b, ki, pt, pls, win: (b, 0, 0, 0)
            ),
            pl.BlockSpec(
                (1, PS, KVH, Dh),
                lambda b, ki, pt, pls, win: (pt[b * MP + ki], 0, 0, 0),
            ),
            pl.BlockSpec(
                (1, PS, KVH, Dh),
                lambda b, ki, pt, pls, win: (pt[b * MP + ki], 0, 0, 0),
            ),
            pl.BlockSpec(
                (1, KVH, Dh), lambda b, ki, pt, pls, win: (b, 0, 0)
            ),
            pl.BlockSpec(
                (1, KVH, Dh), lambda b, ki, pt, pls, win: (b, 0, 0)
            ),
            pl.BlockSpec(
                (KVH, G), lambda b, ki, pt, pls, win: (0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, KVH, G, Dh), lambda b, ki, pt, pls, win: (b, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((KVH, G, 128), jnp.float32),
            pltpu.VMEM((KVH, G, 128), jnp.float32),
            pltpu.VMEM((KVH, G, Dh), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH, G, Dh), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(
        page_table.reshape(-1).astype(jnp.int32),
        past_len.astype(jnp.int32),
        jnp.asarray(window, jnp.int32).reshape(1),
        qg,
        k_pages,
        v_pages,
        k_cur,
        v_cur,
        sink_g,
    )
    return out.reshape(B, NH, Dh)
