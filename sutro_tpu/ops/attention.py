"""Attention ops.

``chunk_attention`` is the single attention entry point for both prefill
(T=chunk, no past) and decode (T=1, past gathered from the paged KV cache).
The reference has no kernels at all (SURVEY §2.3); this is the TPU-native
hot path. Two implementations sit behind one signature:

- a pure-``jnp`` path (XLA fuses it well; used on CPU tests and as the
  always-correct fallback), and
- Pallas flash/paged kernels (ops/pallas_attention.py), dispatched with
  ``use_pallas=True`` on TPU.

Semantics handled here, uniformly: GQA head grouping, causal masking within
the chunk, past-length masking, per-layer sliding windows (Gemma3 5:1
local:global, gpt-oss alternating — SURVEY §5.7), and gpt-oss learnable
attention sinks (an extra per-head softmax logit that absorbs probability
mass).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def chunk_attention(
    q: jax.Array,                       # [B, T, NH, Dh]
    k: jax.Array,                       # [B, T, KVH, Dh] (chunk, post-RoPE)
    v: jax.Array,                       # [B, T, KVH, Dh]
    *,
    positions: jax.Array,               # [B, T] global positions of queries
    valid_len: jax.Array,               # [B] valid tokens in the chunk
    past_k: Optional[jax.Array] = None, # [B, CTX, KVH, Dh]
    past_v: Optional[jax.Array] = None,
    past_len: Optional[jax.Array] = None,  # [B]
    # paged past (decode): one layer's page pool + table; mutually
    # exclusive with past_k/past_v. Pools carry the FUSED [NP, PS,
    # KVH*Dh] layout (engine/kvcache.py). The Pallas paged kernel reads
    # pages in place; the fallback gathers this layer's contiguous view.
    past_k_pages: Optional[jax.Array] = None,  # [NP, PS, KVH*Dh]
    past_v_pages: Optional[jax.Array] = None,
    # int8 KV mode: per-token dequant scales for this layer's pages
    past_k_scale: Optional[jax.Array] = None,  # [NP, PS] f32
    past_v_scale: Optional[jax.Array] = None,
    page_table: Optional[jax.Array] = None,    # [B, MP] int32
    window: Optional[jax.Array] = None,    # scalar int32; 0 => full attention
    sink: Optional[jax.Array] = None,      # [NH] attention-sink logits
    use_pallas: bool = False,
    ring_mesh=None,                        # Mesh with a >1 "seq" axis =>
                                           # sequence-parallel ring prefill
    # fused-decode window buffer (runner.decode_multi): K/V of tokens
    # sampled earlier in the window, not yet written to the page pool.
    # win_k/win_v [B, W, KVH*Dh] (FUSED trailing axis, matching the page
    # pool); win_len scalar = valid slots, positions are past_len + slot.
    win_k: Optional[jax.Array] = None,
    win_v: Optional[jax.Array] = None,
    win_len: Optional[jax.Array] = None,
    kv_chunk: int = 1,  # static: pages per decode-kernel DMA (>1 means
                        # the caller guarantees contiguous page runs)
    # shared-prefix (Hydragen-style) decode: each group is a
    # ``(pages [Pp_g] int32, pfx_len [B] int32)`` pair — member rows'
    # tables START with the group's shared pages (pfx_len 0 = row not
    # in that group; groups are disjoint). The Pallas path computes
    # each group's prefix attention once for the whole batch (one HBM
    # read of the shared pages per layer-step instead of one per row),
    # combines the per-row carries exactly (max/sum/sum over disjoint
    # groups), and injects them as the paged kernel's initial
    # online-softmax carry. The fallback path ignores this (the tables
    # still contain the prefix pages, so its full-table gather computes
    # the identical function).
    pfx_groups: Optional[tuple] = None,
) -> jax.Array:
    """Returns [B, T, NH, Dh]."""
    B, T = q.shape[:2]
    if (
        ring_mesh is not None
        and past_k is None
        and past_k_pages is None
        and T > 1
    ):
        from .ring_attention import ring_self_attention

        return ring_self_attention(
            ring_mesh, q, k, v,
            positions=positions, valid_len=valid_len,
            window=window, sink=sink,
        )
    if past_k_pages is not None:
        if use_pallas and T == 1:
            from .pallas_paged import paged_decode_attention, paged_decode_supported

            if paged_decode_supported(q[:, 0], past_k_pages, page_table):
                win = (
                    jnp.asarray(0, jnp.int32) if window is None
                    else jnp.asarray(window, jnp.int32)
                )
                pfx_kw = {}
                if pfx_groups:
                    from .pallas_paged import (
                        prefix_attention_carry,
                        prefix_attention_carry_pallas,
                        prefix_carry_supported,
                    )

                    PS = past_k_pages.shape[1]
                    q_pos = past_len + (
                        win_len if win_len is not None else 0
                    )
                    # in-place carry kernel when shapes allow: the
                    # shared pages are read straight from the HBM pool
                    # (page-indexed BlockSpecs); otherwise the XLA
                    # gather computes the identical carry
                    in_place = prefix_carry_supported(
                        q[:, 0], past_k_pages, past_k_scale
                    )
                    # groups have DISJOINT member rows, so per-row
                    # carries combine exactly: cold rows contribute
                    # (-inf, 0, 0) to max/sum/sum
                    m0 = l0 = acc0 = None
                    pfx_cnt = jnp.zeros_like(past_len)
                    for pages_g, len_g in pfx_groups:
                        if in_place:
                            mg, lg, ag = prefix_attention_carry_pallas(
                                q[:, 0], past_k_pages, past_v_pages,
                                pages_g, len_g, q_pos, win,
                            )
                        else:
                            mg, lg, ag = prefix_attention_carry(
                                q[:, 0], past_k_pages, past_v_pages,
                                pages_g, len_g, q_pos, win,
                                k_scale=past_k_scale,
                                v_scale=past_v_scale,
                            )
                        if m0 is None:
                            m0, l0, acc0 = mg, lg, ag
                        else:
                            m0 = jnp.maximum(m0, mg)
                            l0 = l0 + lg
                            acc0 = acc0 + ag
                        pfx_cnt = pfx_cnt + len_g // PS
                    pfx_kw = dict(
                        pfx_cnt=pfx_cnt, m0=m0, l0=l0, acc0=acc0
                    )
                out = paged_decode_attention(
                    q[:, 0], past_k_pages, past_v_pages, page_table,
                    past_len, k[:, 0], v[:, 0], win, sink,
                    win_k=win_k, win_v=win_v, win_len=win_len,
                    kv_chunk=1 if pfx_groups else kv_chunk,
                    k_scale=past_k_scale, v_scale=past_v_scale,
                    **pfx_kw,
                )
                return out[:, None]
        from ..engine.kvcache import gather_kv_layer

        past_k, past_v = gather_kv_layer(
            past_k_pages, past_v_pages, page_table, k.shape[2],
            k_scale_l=past_k_scale, v_scale_l=past_v_scale,
            out_dtype=q.dtype,
        )

    if use_pallas:
        from . import pallas_attention as pa

        out = pa.try_chunk_attention(
            q, k, v, positions=positions, valid_len=valid_len,
            past_k=past_k, past_v=past_v, past_len=past_len,
            window=window, sink=sink,
        )
        if out is not None:
            return out

    B, T, NH, Dh = q.shape
    KVH = k.shape[2]
    G = NH // KVH
    scale = Dh ** -0.5

    if past_k is not None:
        ctx = past_k.shape[1]
        key_segs = [past_k, k]
        val_segs = [past_v, v]
        pos_segs = [
            jnp.broadcast_to(
                jnp.arange(ctx, dtype=jnp.int32)[None], (B, ctx)
            ),
            positions,
        ]
        valid_segs = [
            jnp.arange(ctx, dtype=jnp.int32)[None] < past_len[:, None],
            jnp.arange(T, dtype=jnp.int32)[None] < valid_len[:, None],
        ]
        if win_k is not None and win_k.shape[1] > 0:
            # fused-window tokens: positions past_len + slot, valid
            # while slot < win_len (they are not in the pages yet);
            # buffers arrive lane-fused [B, W, KVH*Dh]
            W = win_k.shape[1]
            slot = jnp.arange(W, dtype=jnp.int32)[None]
            key_segs.insert(1, win_k.reshape(B, W, KVH, Dh))
            val_segs.insert(1, win_v.reshape(B, W, KVH, Dh))
            pos_segs.insert(1, past_len[:, None] + slot)
            valid_segs.insert(
                1, jnp.broadcast_to(slot < win_len, (B, W))
            )
        keys = jnp.concatenate(key_segs, axis=1)
        vals = jnp.concatenate(val_segs, axis=1)
        key_pos = jnp.concatenate(pos_segs, axis=1)
        key_valid = jnp.concatenate(valid_segs, axis=1)
    else:
        keys, vals = k, v
        key_pos = positions
        key_valid = jnp.arange(T, dtype=jnp.int32)[None] < valid_len[:, None]

    S = keys.shape[1]
    qg = q.reshape(B, T, KVH, G, Dh).astype(jnp.float32)
    kf = keys.astype(jnp.float32)
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, kf) * scale  # [B,KVH,G,T,S]

    # Mask: causal (key_pos <= q_pos), key validity, sliding window.
    qp = positions[:, :, None]                     # [B, T, 1]
    kp = key_pos[:, None, :]                       # [B, 1, S]
    allowed = (kp <= qp) & key_valid[:, None, :]
    if window is not None:
        win = jnp.asarray(window, jnp.int32)
        in_window = (qp - kp) < jnp.where(win > 0, win, jnp.iinfo(jnp.int32).max)
        allowed = allowed & in_window
    # mask shape [B,1,1,T,S] broadcasts over (KVH, G)
    scores = jnp.where(allowed[:, None, None, :, :], scores, NEG_INF)

    if sink is not None:
        sink_col = sink.astype(jnp.float32).reshape(1, KVH, G, 1, 1)
        sink_col = jnp.broadcast_to(sink_col, (B, KVH, G, T, 1))
        scores = jnp.concatenate([scores, sink_col], axis=-1)
        weights = jax.nn.softmax(scores, axis=-1)[..., :S]
    else:
        weights = jax.nn.softmax(scores, axis=-1)

    out = jnp.einsum("bkgts,bskd->btkgd", weights, vals.astype(jnp.float32))
    return out.reshape(B, T, NH, Dh).astype(q.dtype)
