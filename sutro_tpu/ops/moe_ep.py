"""Explicit expert-parallel MoE execution (shard_map over the mesh).

Why this exists: `ops/moe.py` under plain GSPMD works, but its ragged
path gathers tokens by a data-dependent permutation and feeds
`ragged_dot` group sizes for ALL experts — the partitioner's only safe
lowering is to all-gather the expert weights onto every shard. For the
models EP exists for (qwen-3-235b-a22b, gpt-oss-120b — reference
catalog /root/reference/sutro/common.py:28-39), replicating expert
weights is exactly the thing that cannot happen: weight residency
1/ep-per-shard IS the point (SURVEY §2.3 "EP expert parallelism").

This path makes the partitioning manual and exact:

- shard_map over the engine mesh; expert weights arrive pre-sharded
  ``[E/ep, H, F/tp]`` (the `parallel/sharding.py` rules — EP on the
  expert axis composes with Megatron TP on the FFN axis);
- every shard computes the (cheap, replicated) router for its token
  shard, then sorts the N*top_k expanded rows so the rows owned by
  THIS shard's experts come first, grouped by local expert — a static
  ``[M]`` argsort, no capacity factor and **no token dropping**:
  unowned rows are zero-masked into the trailing group, so outputs are
  exact (a batch-inference engine cannot silently drop tokens — the
  results contract is 1:1, reference README.md:221);
- two grouped GEMMs (+ activation) against the local expert shard,
  combine by scatter-add, then ONE psum over ("expert", "model")
  merges expert contributions and the TP partial sums in a single
  collective.

FLOP note: the zero-masked tail means each shard still streams M rows
through its GEMMs — EP here buys weight residency and HBM traffic
(1/ep of expert bytes per shard, the decode bottleneck), not FLOP
scaling; FLOPs scale with the ``data`` axis as usual.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .moe import _act, _grouped, _route
from .shard_compat import shard_map as _shard_map


def moe_mlp_ep(
    x: jax.Array,          # [B, T, H]
    router: jax.Array,     # [H, E] (replicated)
    we_gate: jax.Array,    # [E, H, F] — expert/model sharded
    we_up: jax.Array,
    we_down: jax.Array,    # [E, F, H]
    *,
    mesh: Mesh,
    top_k: int,
    activation: str = "silu",
    router_b: Optional[jax.Array] = None,   # [E]
    bias_gate: Optional[jax.Array] = None,  # [E, F]
    bias_up: Optional[jax.Array] = None,    # [E, F]
    bias_down: Optional[jax.Array] = None,  # [E, H]
) -> jax.Array:
    B, T, H = x.shape
    E = router.shape[-1]
    F = we_gate.shape[-1]
    ep = int(mesh.shape.get("expert", 1))
    tp = int(mesh.shape.get("model", 1))
    if E % max(ep, 1):
        raise ValueError(f"expert axis {ep} must divide num_experts {E}")
    if F % max(tp, 1):
        raise ValueError(f"model axis {tp} must divide moe FFN dim {F}")

    # shard tokens over "data" only when divisible; otherwise replicate
    # (correct either way — replication just duplicates router math)
    dp = int(mesh.shape.get("data", 1))
    x_spec = P("data", None, None) if B % max(dp, 1) == 0 else P()

    def body(x_s, router, wg, wu, wd, rb, bg, bu, bd):
        Bl, Tl, _ = x_s.shape
        El = wg.shape[0]
        N = Bl * Tl
        K = top_k
        M = N * K
        eidx = jax.lax.axis_index("expert")
        xt = x_s.reshape(N, H)

        _, _, flat_expert, flat_token, flat_prob = _route(
            xt, router, rb, K
        )
        loc = flat_expert - eidx * El                        # local id
        owned = jnp.logical_and(loc >= 0, loc < El)
        # owned rows first, grouped by local expert; unowned pushed to
        # a trailing pseudo-group El (stable sort keeps token order)
        key = jnp.where(owned, loc, El)
        order = jnp.argsort(key, stable=True)
        s_key = key[order]
        s_token = flat_token[order]
        s_weight = jnp.where(owned, flat_prob, 0.0)[order]   # [M]
        counts = jnp.bincount(s_key, length=El + 1)
        # unowned tail rides the last real group with zeroed inputs —
        # static shapes, no capacity factor, no dropped tokens
        group_sizes = (
            counts[:El].at[El - 1].add(counts[El]).astype(jnp.int32)
        )
        s_eidx = jnp.minimum(s_key, El - 1)                  # bias index

        lhs = xt[s_token] * (s_weight > 0)[:, None].astype(xt.dtype)
        g = _grouped(lhs, wg, group_sizes)                   # [M, F/tp]
        u = _grouped(lhs, wu, group_sizes)
        if bg is not None:
            g = g + bg[s_eidx].astype(g.dtype)
            u = u + bu[s_eidx].astype(u.dtype)
        a, u = _act(g, u, activation)
        y = _grouped(a * u, wd, group_sizes)                 # [M, H]
        if bd is not None:
            # gate/up biases live on the tp-sharded F axis (distinct
            # slices per shard), but bias_down lands on the unsharded H
            # output — every model shard would add it, so pre-divide by
            # the axis size to survive the psum intact
            # axis size via psum(1): works on every jax version (the
            # top-level jax.lax.axis_size helper is newer than some
            # hosts' pins) and folds to a constant under shard_map
            y = y + (
                bd[s_eidx] / jax.lax.psum(1, "model")
            ).astype(y.dtype)
        y = y * s_weight[:, None].astype(y.dtype)
        out = jnp.zeros((N, H), y.dtype).at[s_token].add(y)
        # one collective: expert contributions + TP partial sums (the
        # F-axis contraction in the down GEMM is tp-sharded)
        out = jax.lax.psum(out, ("expert", "model"))
        return out.reshape(Bl, Tl, H)

    opt = lambda spec, v: None if v is None else spec  # noqa: E731
    fn = _shard_map(
        body,
        mesh=mesh,
        in_specs=(
            x_spec,
            P(),
            P("expert", None, "model"),
            P("expert", None, "model"),
            P("expert", "model", None),
            opt(P(), router_b),
            opt(P("expert", "model"), bias_gate),
            opt(P("expert", "model"), bias_up),
            opt(P("expert", None), bias_down),
        ),
        out_specs=x_spec,
        check_vma=False,
    )
    return fn(
        x, router, we_gate, we_up, we_down,
        router_b, bias_gate, bias_up, bias_down,
    )
