"""Ring-attention sequence/context parallelism over the ``seq`` mesh axis.

The reference has no long-context mechanism beyond server-side truncation
(`/root/reference/sutro/sdk.py:457,480` — ``truncate_rows``); this is the
TPU-native capability that makes truncation optional (SURVEY §5.7): shard
the sequence over devices so a prompt longer than one chip's HBM still
prefills at full attention.

Design (blockwise/flash over a device ring — the standard TPU recipe):

- Queries stay resident: each ``seq``-axis device holds one contiguous
  chunk of the sequence's Q, K and V (``[B, T/S, ...]``).
- K/V chunks rotate around the ring with ``lax.ppermute`` (neighbor
  exchange over ICI); after S steps every device has seen every K/V block.
- Each step folds its block into a running flash-attention accumulator
  (fp32 running max ``m``, denominator ``l``, numerator ``acc``) so the
  softmax is exact — identical numerics to full attention up to fp32
  reduction order.
- Causality, padding validity, sliding windows, and gpt-oss attention
  sinks are all handled by *global position* masks, so correctness is
  independent of ring rotation order; with a sliding window the distant
  blocks simply contribute nothing.
- Composes with TP: the head axes of Q/K/V keep their ``model`` sharding
  inside the shard_map (heads are embarrassingly parallel in attention),
  so ring steps move only ``1/tp`` of the K/V per device.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .attention import NEG_INF
from .shard_compat import shard_map as _shard_map


def _ring_body(
    qg: jax.Array,       # [B, Tq, KVH, G, Dh] fp32
    q_pos: jax.Array,    # [B, Tq]
    scale: float,
    window: jax.Array,   # scalar int32; 0 => full attention
    carry,
):
    k_c, v_c, kp_c, kval_c, m, l, acc = carry
    scores = (
        jnp.einsum("btkgd,bskd->bkgts", qg, k_c.astype(jnp.float32)) * scale
    )  # [B, KVH, G, Tq, S]
    qp = q_pos[:, :, None]                  # [B, Tq, 1]
    kp = kp_c[:, None, :]                   # [B, 1, S]
    allowed = (kp <= qp) & kval_c[:, None, :]
    in_window = (qp - kp) < jnp.where(
        window > 0, window, jnp.iinfo(jnp.int32).max
    )
    allowed = allowed & in_window
    mask = allowed[:, None, None, :, :]     # [B, 1, 1, Tq, S]
    scores = jnp.where(mask, scores, NEG_INF)
    s_max = jnp.max(scores, axis=-1)        # [B, KVH, G, Tq]
    m_new = jnp.maximum(m, s_max)
    p = jnp.exp(scores - m_new[..., None])
    p = jnp.where(mask, p, 0.0)             # exact zeros on masked entries
    corr = jnp.exp(m - m_new)
    l = l * corr + p.sum(axis=-1)
    acc = acc * corr[..., None] + jnp.einsum(
        "bkgts,bskd->bkgtd", p, v_c.astype(jnp.float32)
    )
    return k_c, v_c, kp_c, kval_c, m_new, l, acc


def ring_attention_local(
    q: jax.Array,        # [B, Tq, NH_local, Dh] — this device's Q chunk
    k: jax.Array,        # [B, Tc, KVH_local, Dh] — this device's K chunk
    v: jax.Array,
    q_pos: jax.Array,    # [B, Tq] int32 global positions
    kv_pos: jax.Array,   # [B, Tc] int32 global positions of the K/V chunk
    kv_valid: jax.Array,  # [B, Tc] bool — real (non-pad) K/V tokens
    window: jax.Array,   # scalar int32 (0 = full)
    sink: jax.Array,     # [NH_local] fp32 (zeros when has_sink=False)
    *,
    axis_name: str,
    ring_size: int,
    has_sink: bool,
) -> jax.Array:
    """Per-shard body (call inside shard_map). Returns [B, Tq, NH, Dh]."""
    B, Tq, NH, Dh = q.shape
    KVH = k.shape[2]
    G = NH // KVH
    scale = Dh ** -0.5
    qg = q.reshape(B, Tq, KVH, G, Dh).astype(jnp.float32)

    m0 = jnp.full((B, KVH, G, Tq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, KVH, G, Tq), jnp.float32)
    acc0 = jnp.zeros((B, KVH, G, Tq, Dh), jnp.float32)
    perm = [(j, (j + 1) % ring_size) for j in range(ring_size)]

    def body(i, carry):
        carry = _ring_body(qg, q_pos, scale, window, carry)
        k_c, v_c, kp_c, kval_c, m, l, acc = carry
        if ring_size > 1 and i < ring_size - 1:  # last rotation is unused
            k_c = jax.lax.ppermute(k_c, axis_name, perm)
            v_c = jax.lax.ppermute(v_c, axis_name, perm)
            kp_c = jax.lax.ppermute(kp_c, axis_name, perm)
            kval_c = jax.lax.ppermute(kval_c, axis_name, perm)
        return k_c, v_c, kp_c, kval_c, m, l, acc

    carry = (k, v, kv_pos, kv_valid, m0, l0, acc0)
    for i in range(ring_size):  # static unroll; perm list is static anyway
        carry = body(i, carry)
    *_, m, l, acc = carry

    if has_sink:
        sk = sink.astype(jnp.float32).reshape(KVH, G)
        l = l + jnp.exp(sk[None, :, :, None] - m)
    out = acc / jnp.maximum(l[..., None], 1e-30)
    # [B, KVH, G, Tq, Dh] -> [B, Tq, NH, Dh]
    return (
        out.transpose(0, 3, 1, 2, 4).reshape(B, Tq, NH, Dh).astype(q.dtype)
    )


def ring_self_attention(
    mesh: Mesh,
    q: jax.Array,              # [B, T, NH, Dh]
    k: jax.Array,              # [B, T, KVH, Dh]
    v: jax.Array,
    *,
    positions: jax.Array,      # [B, T] int32
    valid_len: jax.Array,      # [B] int32
    window: Optional[jax.Array] = None,
    sink: Optional[jax.Array] = None,
    axis_name: str = "seq",
    head_axis: Optional[str] = "model",
) -> jax.Array:
    """Sequence-parallel causal self-attention (prefill; no past).

    ``T`` must be a multiple of ``mesh.shape[axis_name]`` (the runner pads
    prefill buckets accordingly). Head axes stay sharded over
    ``head_axis`` so the op composes with TP.
    """
    S = mesh.shape[axis_name]
    B, T, NH, _ = q.shape
    if T % S:
        raise ValueError(f"T={T} not divisible by seq axis size {S}")
    kv_valid = jnp.arange(T, dtype=jnp.int32)[None, :] < valid_len[:, None]
    win = (
        jnp.asarray(0, jnp.int32)
        if window is None
        else jnp.asarray(window, jnp.int32)
    )
    has_sink = sink is not None
    sk = (
        jnp.zeros((NH,), jnp.float32)
        if sink is None
        else sink.astype(jnp.float32)
    )

    h = head_axis if (head_axis and mesh.shape.get(head_axis, 1) > 1) else None
    spec_qkv = P(None, axis_name, h, None)
    spec_bt = P(None, axis_name)

    fn = _shard_map(
        functools.partial(
            ring_attention_local,
            axis_name=axis_name,
            ring_size=S,
            has_sink=has_sink,
        ),
        mesh=mesh,
        in_specs=(
            spec_qkv, spec_qkv, spec_qkv, spec_bt, spec_bt, spec_bt,
            P(), P(h),
        ),
        out_specs=spec_qkv,
        check_vma=False,
    )
    return fn(q, k, v, positions, positions, kv_valid, win, sk)
