"""The ``Sutro`` client: DataFrame-in/DataFrame-out batch inference.

Contract-compatible re-design of the reference client core
(/root/reference/sutro/sdk.py:52-1715, method map SURVEY §2.2). The
decisive change: ``backend="tpu"`` (default) dispatches every job-lifecycle
call to the in-process ``LocalEngine`` (engine/api.py) running on TPU via
JAX/XLA — the remote fleet behind the reference's ``do_request`` becomes a
local object. ``backend="remote"`` keeps the HTTP path for parity with the
hosted service (same endpoints, §3.6).

Intentional divergences from reference quirks (SURVEY §2.5):
- results rename+cache are unconditional, not gated on LangSmith state
  (reference sdk.py:1172-1190 indentation quirk);
- ``run_function`` traces under the caller's name, not the hardcoded
  "clay-query-match-judge" (sdk.py:566);
- ``cancel_job`` on the local path is a real mutation, though the remote
  path keeps the reference's GET quirk for wire compatibility
  (sdk.py:1280).
"""

from __future__ import annotations

import json
import math
import os
import shutil
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Type, Union

import pandas as pd
from pydantic import BaseModel

from .common import (
    HAS_POLARS,
    ModelOptions,
    Spinner,
    fancy_tqdm,
    human_bytes,
    make_clickable_link,
    normalize_output_schema,
    prepare_input_data,
    to_colored_text,
)
from .interfaces import JobStatus
from .observability import (
    _complete_batch_traces,
    _create_batch_traces,
    _has_open_batch_traces,
    _traced_run,
    tracing_enabled,
)
from .templates.classification import ClassificationTemplates
from .templates.embed import EmbeddingTemplates
from .templates.evals import EvalTemplates
from .validation import check_for_api_key, check_version, config_dir

if HAS_POLARS:
    import polars as pl  # type: ignore

MAX_NAME_LENGTH = 45        # reference sdk.py:38
MAX_DESCRIPTION_LENGTH = 512  # reference sdk.py:39
DEFAULT_BASE_URL = "https://api.sutro.sh"
DEFAULT_SERVING_BASE_URL = "https://serve.sutro.sh"
JOB_URL_TEMPLATE = "https://app.sutro.sh/jobs/{job_id}"


class Sutro(EmbeddingTemplates, ClassificationTemplates, EvalTemplates):
    """Batch LLM inference client with a local TPU engine backend."""

    def __init__(
        self,
        api_key: Optional[str] = None,
        base_url: str = DEFAULT_BASE_URL,
        serving_base_url: str = DEFAULT_SERVING_BASE_URL,
        backend: str = "tpu",
        engine_config: Optional[Dict[str, Any]] = None,
    ):
        self.api_key = api_key or check_for_api_key()
        self.base_url = base_url
        self.serving_base_url = serving_base_url
        # "fleet" targets a fleet router (sutro fleet serve): identical
        # wire contract to a single daemon, so it IS the remote transport
        self.backend = "remote" if backend == "fleet" else backend
        self._engine_config = engine_config or {}
        self._engine = None
        check_version()

    # ------------------------------------------------------------------
    # configuration mutators (reference sdk.py:64-101)
    # ------------------------------------------------------------------

    def set_api_key(self, api_key: str) -> None:
        self.api_key = api_key

    def set_base_url(self, base_url: str) -> None:
        self.base_url = base_url

    def set_serving_base_url(self, serving_base_url: str) -> None:
        self.serving_base_url = serving_base_url

    def set_backend(self, backend: str) -> None:
        if backend not in ("tpu", "remote", "fleet"):
            raise ValueError("backend must be 'tpu', 'remote', or 'fleet'")
        self.backend = "remote" if backend == "fleet" else backend

    # ------------------------------------------------------------------
    # transports
    # ------------------------------------------------------------------

    @property
    def engine(self):
        if self._engine is None:
            from .engine.api import get_engine
            from .engine.config import load_engine_config

            self._engine = get_engine(
                load_engine_config(**self._engine_config)
            )
        return self._engine

    def do_request(
        self,
        method: str,
        endpoint: str,
        base_url: Optional[str] = None,
        **kwargs: Any,
    ):
        """Authenticated HTTP dispatch for the remote backend — retries
        HTTP 524 with exponential backoff, max 5 (reference
        sdk.py:103-172), and connection-level failures on IDEMPOTENT
        reads (GET/HEAD) with bounded backoff, so a daemon restart or a
        fleet-router failover under a polling client resumes instead of
        raising. Non-idempotent verbs never replay — a connection error
        on a submit is surfaced, not retried into a duplicate job."""
        import requests

        url = f"{(base_url or self.base_url).rstrip('/')}/{endpoint.lstrip('/')}"
        headers = kwargs.pop("headers", {})
        if self.api_key:
            headers["Authorization"] = f"Key {self.api_key}"
        fn = getattr(requests, method.lower())
        idempotent = method.lower() in ("get", "head")
        for attempt in range(5):
            try:
                resp = fn(url, headers=headers, **kwargs)
            except (
                requests.exceptions.ConnectionError,
                requests.exceptions.Timeout,
            ):
                if not idempotent or attempt == 4:
                    raise
                time.sleep(min(0.2 * (2 ** attempt), 2.0))
                continue
            if resp.status_code != 524:
                return resp
            time.sleep(2 ** attempt)
        return resp

    def _remote_json(self, method: str, endpoint: str, **kw: Any) -> Dict:
        resp = self.do_request(method, endpoint, **kw)
        resp.raise_for_status()
        return resp.json()

    # ------------------------------------------------------------------
    # core submit path (reference _run_one_batch_inference, sdk.py:174-440)
    # ------------------------------------------------------------------

    def _run_one_batch_inference(
        self,
        data: Any,
        model: str,
        column: Optional[Union[str, List[Any]]],
        output_column: str,
        job_priority: int,
        output_schema: Optional[Dict[str, Any]],
        system_prompt: Optional[str],
        name: Optional[str],
        description: Optional[str],
        dry_run: bool,
        stay_attached: bool,
        truncate_rows: bool,
        random_seed_per_input: bool,
        sampling_params: Optional[Dict[str, Any]],
        tenant: Optional[str] = None,
        stages: Optional[List[Dict[str, Any]]] = None,
    ) -> Any:
        if name and len(name) > MAX_NAME_LENGTH:
            raise ValueError(
                f"name must be <= {MAX_NAME_LENGTH} characters"
            )
        if description and len(description) > MAX_DESCRIPTION_LENGTH:
            raise ValueError(
                f"description must be <= {MAX_DESCRIPTION_LENGTH} characters"
            )
        inputs = prepare_input_data(data, column=column)
        payload = {
            "model": model,
            "inputs": inputs,
            "column": column,
            "job_priority": job_priority,
            "output_schema": output_schema,
            "system_prompt": system_prompt,
            "name": name,
            "description": description,
            "dry_run": dry_run,
            "truncate_rows": truncate_rows,
            "random_seed_per_input": random_seed_per_input,
            "sampling_params": sampling_params,
            "tenant": tenant,
        }
        if stages is not None:
            # key only present for stage-graph jobs: a plain submit's
            # wire payload stays byte-identical (the DAG off switch)
            payload["stages"] = stages

        if self.backend == "remote":
            resp = self.do_request("post", "batch-inference", json=payload)
            if resp.status_code == 400:
                # the daemon's structured INVALID_PRIORITY body maps
                # back to the same typed error the local backend
                # raises, so both paths surface one exception shape
                try:
                    err = resp.json().get("error") or {}
                except ValueError:
                    err = {}
                if err.get("code") == "INVALID_PRIORITY":
                    from .engine.jobstore import InvalidPriority

                    hi = (err.get("valid_range") or [0, 0])[1]
                    raise InvalidPriority(err.get("priority"), hi + 1)
                if err.get("code") == "INVALID_GRAPH":
                    # same typed-error parity for stage graphs: remote
                    # and local backends raise one exception shape
                    from .engine.stagegraph import InvalidGraph

                    raise InvalidGraph(
                        err.get("reason") or "invalid",
                        err.get("message") or "invalid stage graph",
                    )
            resp.raise_for_status()
            job_id = resp.json()["results"]
        else:
            job_id = self.engine.submit_batch_inference(payload)

        if dry_run:
            with Spinner("Estimating cost...") as sp:
                ok = self.await_job_completion(
                    job_id, obtain_results=False, timeout=600
                )
                if ok is None:
                    sp.fail()
                    return None
            est = self._get_job_cost_estimate(job_id)
            print(
                to_colored_text(
                    f"Estimated cost for this job: ${est:.4f}"
                    if est is not None
                    else "No cost estimate available", "callout",
                )
            )
            return est

        status = self.get_job_status(job_id)
        if status == JobStatus.FAILED.value:
            reason = self._get_failure_reason(job_id)
            print(to_colored_text(f"✗ Job failed: {reason}", "fail"))
            return None

        link = make_clickable_link(JOB_URL_TEMPLATE.format(job_id=job_id))
        if not stay_attached:
            print(to_colored_text(f"Job created: {job_id}", "success"))
            print(to_colored_text(f"View progress at: {link}"))
            return job_id

        started = self._await_job_start(job_id)
        if not started:
            reason = self._get_failure_reason(job_id)
            print(to_colored_text(f"✗ Job did not start: {reason}", "fail"))
            return None
        self._stream_progress_to_tqdm(job_id)

        status = self.get_job_status(job_id)
        if status != JobStatus.SUCCEEDED.value:
            reason = self._get_failure_reason(job_id)
            print(to_colored_text(f"✗ Job {status}: {reason}", "fail"))
            return None

        results_df = self.get_job_results(
            job_id, output_column=output_column
        )
        if results_df is not None and len(results_df):
            preview = results_df.head(5)
            print(to_colored_text("Results preview:", "success"))
            print(preview)
        return job_id

    def _stream_progress_to_tqdm(self, job_id: str) -> None:
        """Consume progress updates into a styled bar — the client hot loop
        of reference stack §3.1 (sdk.py:311-367), minus the network."""
        rec = self._fetch_job(job_id)
        total = rec.get("num_rows", 0) or 0
        pbar = fancy_tqdm(total=total, desc="Rows", color="blue")
        token_state: Dict[str, Any] = {}
        stage_state: Dict[str, Any] = {}

        def postfix() -> None:
            parts = []
            tps = token_state.get("total_tokens_processed_per_second")
            if tps is not None:
                parts.append(f"{tps:,.0f} tok/s")
            if stage_state:
                # per-stage rollup (stage-graph jobs): gen 12/50 ...
                parts.append(
                    " ".join(
                        f"{n} {s.get('rows_done', 0)}/"
                        f"{s.get('rows_total', 0)}"
                        for n, s in stage_state.items()
                    )
                )
            if parts:
                pbar.set_postfix_str(" | ".join(parts))

        try:
            for update in self._iter_progress(job_id):
                if update.get("update_type") == "progress":
                    done = int(update.get("result", 0))
                    pbar.update(done - pbar.n)
                elif update.get("update_type") == "tokens":
                    # partial dicts merge monotonically (sdk.py:354-363)
                    token_state.update(update.get("result") or {})
                    postfix()
                elif update.get("update_type") == "stages":
                    # conflating per-stage counters (metrics bus
                    # "stages" channel, stage_progress wire frame) —
                    # latest rollup wins; tolerant parse so a newer
                    # engine's extra keys never break the bar
                    from .engine.stageframes import parse_stage_progress

                    stage_state.update(parse_stage_progress(update) or {})
                    postfix()
        finally:
            pbar.close()

    def _iter_progress(self, job_id: str):
        if self.backend == "remote":
            yield from self._iter_progress_remote(job_id)
        else:
            yield from self.engine.stream_job_progress(job_id)

    def _iter_progress_remote(self, job_id: str):
        """Remote progress tail with reconnect-by-cursor: a stream that
        closes WITHOUT the terminal ``{"t":"end"}`` frame means the
        daemon died (or a fleet replica crashed) mid-poll — reconnect
        with ``?cursor=<rows done>`` so the resumed stream carries on
        where the last one dropped instead of raising or replaying.
        The tqdm consumer's monotone ``update(done - pbar.n)`` merge
        makes any overlap harmless on old servers that ignore the
        cursor parameter."""
        import requests

        cursor = 0
        retries = 0
        while True:
            try:
                resp = self.do_request(
                    "get",
                    f"stream-job-progress/{job_id}?cursor={cursor}",
                    stream=True,
                )
                resp.raise_for_status()
                for line in resp.iter_lines():
                    if not line:
                        continue
                    update = json.loads(line)
                    if update.get("t") == "end":
                        # explicit terminal frame (newer servers);
                        # older servers just close the stream
                        return
                    if update.get("update_type") == "progress":
                        try:
                            cursor = max(
                                cursor, int(update.get("result") or 0)
                            )
                        except (TypeError, ValueError):
                            pass
                    retries = 0
                    yield update
                # closed with no end frame: either an old server that
                # finished, or a mid-stream death — disambiguate below
            except (
                requests.exceptions.ConnectionError,
                requests.exceptions.ChunkedEncodingError,
                requests.exceptions.Timeout,
            ):
                pass
            retries += 1
            try:
                status = self.get_job_status(job_id)
            except (requests.exceptions.RequestException, ValueError):
                status = None  # daemon still restarting
            if status is not None and JobStatus(status).is_terminal():
                return
            if retries > 6:
                raise RuntimeError(
                    f"progress stream for {job_id} lost after "
                    f"{retries} reconnect attempts"
                )
            time.sleep(min(0.2 * (2 ** retries), 2.0))

    # ------------------------------------------------------------------
    # interactive serving API (the serving/ tier's OpenAI surface)
    # ------------------------------------------------------------------

    def chat(
        self,
        messages: Union[str, List[Dict[str, Any]]],
        model: str = "qwen-3-4b",
        *,
        stream: bool = False,
        system_prompt: Optional[str] = None,
        response_format: Optional[Dict[str, Any]] = None,
        max_tokens: Optional[int] = None,
        temperature: Optional[float] = None,
        top_p: Optional[float] = None,
        stop: Optional[Union[str, List[str]]] = None,
        seed: Optional[int] = None,
        session_id: Optional[str] = None,
    ) -> Any:
        """One interactive chat completion against the serving tier.

        ``session_id`` makes the conversation sticky: the server keeps
        the token transcript (and its KV, tiered HBM→host→disk), so
        each later call with the same id sends ONLY the new user turn
        and resumes in milliseconds instead of re-prefilling the
        history.

        ``messages`` is a string (one user turn) or an OpenAI-style
        message list. Non-streaming returns the ``chat.completion``
        response dict; ``stream=True`` returns an iterator of
        ``chat.completion.chunk`` dicts (closing it cancels the request
        and frees its engine slot). ``response_format`` takes the
        OpenAI ``json_object`` / ``json_schema`` shapes and routes
        through the engine's constrained-decode path.

        The tier lives on the same engine daemon as batch: remote
        backends POST ``/v1/chat/completions`` to ``base_url``; the
        local backend submits straight to the engine's gateway, which
        requires ``engine_config={"interactive_slots": N}`` with N > 0.
        """
        if isinstance(messages, str):
            messages = [{"role": "user", "content": messages}]
        else:
            messages = list(messages)
        if system_prompt:
            messages = [
                {"role": "system", "content": system_prompt}
            ] + messages
        body: Dict[str, Any] = {
            "model": model,
            "messages": messages,
            "stream": bool(stream),
        }
        if response_format is not None:
            body["response_format"] = response_format
        if max_tokens is not None:
            body["max_tokens"] = int(max_tokens)
        if temperature is not None:
            body["temperature"] = float(temperature)
        if top_p is not None:
            body["top_p"] = float(top_p)
        if stop is not None:
            body["stop"] = stop
        if seed is not None:
            body["seed"] = int(seed)
        if session_id is not None:
            body["session_id"] = str(session_id)

        if self.backend == "remote":
            resp = self.do_request(
                "post", "v1/chat/completions", json=body, stream=stream
            )
            if resp.status_code == 404:
                raise RuntimeError(
                    "the server's interactive tier is disabled — start "
                    "it with EngineConfig.interactive_slots > 0"
                )
            resp.raise_for_status()
            if stream:
                return self._iter_sse(resp)
            return resp.json()

        gw = getattr(self.engine, "gateway", None)
        if gw is None:
            raise RuntimeError(
                "interactive serving is disabled: construct "
                "Sutro(engine_config={'interactive_slots': N}) with N > 0"
            )
        from .serving import openai as oai

        sreq = oai.parse_request(body, chat=True)
        ir = gw.submit(sreq)
        if stream:
            return self._iter_local_stream(ir)
        return oai.collect(ir, chat=True)

    def _iter_local_stream(self, ir: Any):
        """Local streaming chat: the gateway's channel, heartbeats
        filtered out. An abandoned iterator cancels the request so the
        scheduler frees its slot."""
        from .serving import openai as oai

        try:
            for obj in oai.iter_stream(ir, chat=True):
                if obj is not None:
                    yield obj
        except GeneratorExit:
            ir.channel.cancel()
            raise

    def _iter_sse(self, resp: Any):
        """Parse an SSE chat stream (``data:`` frames until [DONE])."""
        for raw in resp.iter_lines():
            if not raw:
                continue
            line = raw.decode() if isinstance(raw, bytes) else raw
            if not line.startswith("data:"):
                continue  # ": ping" heartbeats / comments
            data = line[5:].strip()
            if data == "[DONE]":
                return
            yield json.loads(data)

    # ------------------------------------------------------------------
    # public inference API
    # ------------------------------------------------------------------

    def infer(
        self,
        data: Any,
        model: ModelOptions = "gpt-oss-20b",
        column: Optional[Union[str, List[Any]]] = None,
        output_column: str = "inference_result",
        job_priority: int = 0,
        output_schema: Optional[
            Union[Type[BaseModel], Dict[str, Any]]
        ] = None,
        system_prompt: Optional[str] = None,
        name: Optional[str] = None,
        description: Optional[str] = None,
        dry_run: bool = False,
        stay_attached: Optional[bool] = None,
        truncate_rows: bool = True,
        random_seed_per_input: bool = False,
        sampling_params: Optional[Dict[str, Any]] = None,
        tenant: Optional[str] = None,
    ) -> Any:
        """Submit a batch-inference job. Returns the job id (or the cost
        estimate for ``dry_run=True``).

        Default model matches the reference (``gpt-oss-20b``, sdk.py:445);
        ``stay_attached`` defaults to ``job_priority == 0``
        (sdk.py:486-488). ``tenant`` attributes the job's rows/tokens to
        a named tenant in the live monitor (OBSERVABILITY.md "Live
        monitor"); unset means tenant ``"default"``."""
        if stay_attached is None:
            stay_attached = job_priority == 0
        schema = normalize_output_schema(output_schema)
        if schema is not None and (sampling_params or {}).get("stop"):
            # surfaced HERE so the caller sees it even for detached /
            # remote jobs; the engine enforces the same rule at run time
            import warnings

            warnings.warn(
                "sampling_params['stop'] is ignored for output_schema "
                "jobs: stopping mid-JSON would break the schema "
                "guarantee (the schema's own closure ends generation)",
                stacklevel=2,
            )
        return self._run_one_batch_inference(
            data=data,
            model=model,
            column=column,
            output_column=output_column,
            job_priority=job_priority,
            output_schema=schema,
            system_prompt=system_prompt,
            name=name,
            description=description,
            dry_run=dry_run,
            stay_attached=stay_attached,
            truncate_rows=truncate_rows,
            random_seed_per_input=random_seed_per_input,
            sampling_params=sampling_params,
            tenant=tenant,
        )

    def run_graph(
        self,
        data: Any,
        stages: List[Dict[str, Any]],
        model: ModelOptions = "gpt-oss-20b",
        column: Optional[Union[str, List[Any]]] = None,
        output_column: str = "inference_result",
        job_priority: int = 0,
        name: Optional[str] = None,
        description: Optional[str] = None,
        dry_run: bool = False,
        stay_attached: Optional[bool] = None,
        truncate_rows: bool = True,
        sampling_params: Optional[Dict[str, Any]] = None,
        tenant: Optional[str] = None,
    ) -> Any:
        """Submit a stage-graph job: a small DAG of stages executed
        entirely server-side as ONE job (engine/stagegraph.py).

        ``stages`` is a list of stage dicts — ``map`` stages carry
        per-stage ``model`` / ``system_prompt`` / ``prompt_template``
        (must contain ``{input}``) / ``output_schema`` /
        ``sampling_params``; ``filter`` / ``elo`` / ``pair`` stages are
        host-side reduces over their upstream stage. Edges are named in
        ``after``; the single sink stage's rows become the job's
        results. Rows stream between stages inside the engine (no
        client round-trips, shared context rides the server's prefix
        cache), the whole DAG is priced and quota-checked at submit,
        and an invalid graph raises a structured ``InvalidGraph``
        (HTTP 400 ``INVALID_GRAPH`` for remote backends).

        Example — rank + ELO in one submit::

            so.run_graph(df, column="pair", stages=[
                {"name": "rank", "kind": "map",
                 "system_prompt": "You are an expert evaluator...",
                 "output_schema": {...}},
                {"name": "elo", "kind": "elo", "after": ["rank"]},
            ])
        """
        if stay_attached is None:
            stay_attached = job_priority == 0
        norm = []
        for s in stages:
            s = dict(s) if isinstance(s, dict) else s
            if isinstance(s, dict) and s.get("output_schema") is not None:
                s["output_schema"] = normalize_output_schema(
                    s["output_schema"]
                )
            norm.append(s)
        return self._run_one_batch_inference(
            data=data,
            model=model,
            column=column,
            output_column=output_column,
            job_priority=job_priority,
            output_schema=None,
            system_prompt=None,
            name=name,
            description=description,
            dry_run=dry_run,
            stay_attached=stay_attached,
            truncate_rows=truncate_rows,
            random_seed_per_input=False,
            sampling_params=sampling_params,
            tenant=tenant,
            stages=norm,
        )

    def infer_per_model(
        self,
        data: Any,
        models: List[str],
        column: Optional[Union[str, List[Any]]] = None,
        names: Optional[List[str]] = None,
        descriptions: Optional[List[str]] = None,
        **kwargs: Any,
    ) -> List[Any]:
        """Fan-out: same data to N models as N detached jobs (reference
        sdk.py:696-798; names/descriptions must match length)."""
        if names is not None and len(names) != len(models):
            raise ValueError("names must be same length as models")
        if descriptions is not None and len(descriptions) != len(models):
            raise ValueError("descriptions must be same length as models")
        job_ids = []
        for i, model in enumerate(models):
            job_ids.append(
                self.infer(
                    data,
                    model=model,
                    column=column,
                    name=names[i] if names else None,
                    description=descriptions[i] if descriptions else None,
                    stay_attached=False,
                    **kwargs,
                )
            )
        return job_ids

    # ------------------------------------------------------------------
    # Functions (serving path; reference sdk.py:512-694)
    # ------------------------------------------------------------------

    def run_function(
        self,
        name: str,
        input_data: Union[BaseModel, Dict[str, Any], str],
        **kwargs: Any,
    ) -> Dict[str, Any]:
        """Single online call. Remote backend POSTs
        ``{serving_base_url}/functions/run``; the TPU backend runs a 1-row
        synchronous job against the model the function name resolves to."""
        if isinstance(input_data, BaseModel):
            input_data = input_data.model_dump()

        def _call() -> Dict[str, Any]:
            if self.backend == "remote":
                return self._remote_json(
                    "post",
                    "functions/run",
                    base_url=self.serving_base_url,
                    json={"name": name, "input_data": input_data},
                )
            text = (
                json.dumps(input_data)
                if isinstance(input_data, dict)
                else str(input_data)
            )
            job_id = self.engine.submit_batch_inference(
                {"model": name, "inputs": [text], "job_priority": 0,
                 "truncate_rows": False}
            )
            self._wait_terminal(job_id, timeout=600)
            res = self.engine.job_results(
                job_id, include_cumulative_logprobs=True
            )
            # reference contract carries a confidence score
            # (/root/reference/sutro/sdk.py:535-544); locally it is the
            # geometric-mean token probability of the generation
            # (cumulative logprob over the SAME sampled-token count the
            # engine recorded). ``predictions`` stays empty: remote
            # Functions return model-specific candidate lists the local
            # single-model path has no analogue for.
            logps = res.get("cumulative_logprobs") or [None]
            gen_tokens = (res.get("gen_tokens") or [0])[0]
            confidence = (
                float(math.exp(logps[0] / max(gen_tokens, 1)))
                if logps[0] is not None
                else None
            )
            return {
                "response": res["outputs"][0],
                "confidence": confidence,
                "predictions": [],
                "run_id": job_id,
            }

        # traced under the function's name (reference bug sdk.py:566 fixed)
        return _traced_run(name, _call, inputs={"input_data": input_data})

    def batch_run_function(
        self,
        name: str,
        data: Any,
        column: Optional[Union[str, List[Any]]] = None,
        job_priority: int = 0,
        stay_attached: Optional[bool] = None,
        **kwargs: Any,
    ) -> Any:
        """Functions over tables: rows become JSON dicts, delegated to
        ``infer(model=name, truncate_rows=False)`` (reference sdk.py:590-694)."""
        if stay_attached and tracing_enabled():
            raise ValueError(
                "stay_attached=True is incompatible with LangSmith tracing"
            )
        if isinstance(data, pd.DataFrame):
            rows = [
                json.dumps(r._asdict() if hasattr(r, "_asdict") else dict(r))
                for r in data.to_dict(orient="records")
            ]
        elif HAS_POLARS and isinstance(data, pl.DataFrame):
            rows = [json.dumps(d) for d in data.to_dicts()]
        else:
            rows = [
                json.dumps(x) if isinstance(x, dict) else str(x) for x in data
            ]
        job_id = self.infer(
            rows,
            model=name,
            job_priority=job_priority,
            stay_attached=stay_attached,
            truncate_rows=False,
            **kwargs,
        )
        if job_id and tracing_enabled():
            _create_batch_traces(job_id, rows, model=name)
        return job_id

    # ------------------------------------------------------------------
    # job lifecycle
    # ------------------------------------------------------------------

    def _fetch_job(self, job_id: str) -> Dict[str, Any]:
        if self.backend == "remote":
            return self._remote_json("get", f"jobs/{job_id}")["job"]
        return self.engine.get_job(job_id)

    def _get_job_cost_estimate(self, job_id: str) -> Optional[float]:
        return self._fetch_job(job_id).get("cost_estimate")

    def _get_failure_reason(self, job_id: str) -> str:
        reason = self._fetch_job(job_id).get("failure_reason") or {}
        return reason.get("message", "unknown")

    def get_job_status(
        self, job_id: str, with_failure_log: bool = False
    ) -> Any:
        """Job status string; with ``with_failure_log`` a dict
        ``{"status", "failure_log", "has_telemetry_dump"}`` — the
        engine's structured retry/quarantine/terminal-failure trail
        (FAILURES.md) plus whether a flight-recorder dump exists
        (``sutro telemetry --job`` / ``sutro doctor``)."""
        if self.backend == "remote":
            body = self._remote_json("get", f"job-status/{job_id}")
            status = body["job_status"][job_id]
        else:
            status = self.engine.job_status(job_id)
        if with_failure_log:
            rec = self._fetch_job(job_id)
            return {
                "status": status,
                "failure_log": rec.get("failure_log") or [],
                "has_telemetry_dump": bool(
                    rec.get("has_telemetry_dump")
                ),
            }
        return status

    def get_job_failure_log(self, job_id: str) -> List[Dict[str, Any]]:
        """Structured failure events for a job: per-row retries and
        quarantines, transient-I/O retries, torn-chunk quarantines, and
        terminal failures. Empty for clean jobs (and for jobs predating
        the failure_log schema)."""
        return self._fetch_job(job_id).get("failure_log") or []

    def get_job_telemetry(self, job_id: str) -> Dict[str, Any]:
        """The job's flight-recorder document (OBSERVABILITY.md): span
        timeline across engine stages (tokenize, prefill, decode
        windows, accept, flush, finalize, ...) plus exact per-job
        counters (rows by outcome, tokens in/out). Dumped automatically
        when a job FAILs; this fetches/refreshes it on demand."""
        if self.backend == "remote":
            return self._remote_json("get", f"job-telemetry/{job_id}")[
                "telemetry"
            ]
        return self.engine.job_telemetry(job_id)

    def diagnose_job(self, job_id: str) -> Dict[str, Any]:
        """Bottleneck doctor (OBSERVABILITY.md "Doctor"): per-process
        stage attribution over the job's merged cross-process telemetry
        document, roofline grades for its device windows, and one named
        bottleneck verdict with evidence lines. Both backends (the
        remote daemon serves it as ``GET /job-doctor/{id}``)."""
        if self.backend == "remote":
            return self._remote_json("get", f"job-doctor/{job_id}")[
                "doctor"
            ]
        return self.engine.diagnose_job(job_id)

    def get_trace(self, ident: str) -> Dict[str, Any]:
        """Chrome trace-event JSON (Perfetto-loadable) for a forensics
        trace id (``tr-...``, e.g. from an alert's
        ``exemplar_trace_ids``), a request/job id whose trace is still
        in the ring, or a plain job id (whole flight record). Both
        backends; the daemon serves the raw document at
        ``GET /trace/{id}`` so it can be piped straight into Perfetto.
        Raises ``KeyError`` locally / 404 remotely when unknown."""
        if self.backend == "remote":
            return self._remote_json("get", f"trace/{ident}")
        return self.engine.get_trace(ident)

    def get_job_fleet(self, job_id: str) -> Dict[str, Any]:
        """Elastic dp fleet view for a job (FAILURES.md "Elastic
        fleet"): per-rank membership state (running, idle, lost,
        drained, late-joined), row ownership, and the round's
        requeue/steal/duplicate counters. Live while the coordinator is
        serving the round, else the snapshot persisted at round end;
        ``{"elastic": False}`` for jobs that never ran one. Both
        backends (the remote daemon serves it as
        ``GET /job-fleet/{id}``)."""
        if self.backend == "remote":
            return self._remote_json("get", f"job-fleet/{job_id}")[
                "fleet"
            ]
        return self.engine.job_fleet(job_id)

    def get_monitor(self) -> Dict[str, Any]:
        """The live SLO monitor's consolidated document
        (OBSERVABILITY.md "Live monitor"): windowed rates and
        p50/p99 percentiles, per-tenant attribution, SLO rule states,
        the active/recent alert events, the in-flight doctor verdicts
        for running jobs, and the tick history trail. Both backends
        (the remote daemon serves it as ``GET /monitor``); raises
        ``KeyError`` locally / 404 remotely when the monitor is
        disabled (``SUTRO_TELEMETRY=0`` or ``SUTRO_MONITOR=0``)."""
        if self.backend == "remote":
            return self._remote_json("get", "monitor")["monitor"]
        return self.engine.monitor_doc()

    def get_metrics_text(self) -> str:
        """Engine metrics registry in Prometheus text exposition format
        (the same payload ``GET /metrics`` serves on the daemon)."""
        if self.backend == "remote":
            resp = self.do_request("get", "metrics")
            resp.raise_for_status()
            return resp.text
        from . import telemetry

        return telemetry.REGISTRY.to_prometheus()

    def list_jobs(self) -> List[Dict[str, Any]]:
        if self.backend == "remote":
            return self._remote_json("get", "list-jobs")["jobs"]
        return self.engine.list_jobs()

    def cancel_job(self, job_id: str) -> Dict[str, Any]:
        if self.backend == "remote":
            # reference wire quirk: GET for a mutation (sdk.py:1280)
            return self._remote_json("get", f"job-cancel/{job_id}")
        return self.engine.cancel_job(job_id)

    def resume_job(self, job_id: str) -> Dict[str, Any]:
        """Re-queue a FAILED/CANCELLED (or orphaned) job; rows already in
        the partial store are not recomputed (engine row-granular resume,
        SURVEY §5.3 — an extension over the reference API)."""
        if self.backend == "remote":
            return self._remote_json("get", f"job-resume/{job_id}")
        return self.engine.resume_job(job_id)

    def _await_job_start(self, job_id: str, timeout: int = 3600) -> bool:
        """Poll until RUNNING/STARTING (True) or FAILED/CANCELLED (False)
        (reference sdk.py:1677-1715)."""
        poll = self._poll_s()
        deadline = time.monotonic() + timeout
        with Spinner("Waiting for job to start...") as sp:
            while time.monotonic() < deadline:
                status = self.get_job_status(job_id)
                if status in (
                    JobStatus.RUNNING.value,
                    JobStatus.STARTING.value,
                    JobStatus.SUCCEEDED.value,
                ):
                    sp.ok()
                    return True
                if status in (
                    JobStatus.FAILED.value,
                    JobStatus.CANCELLED.value,
                    JobStatus.CANCELLING.value,
                ):
                    sp.fail()
                    return False
                time.sleep(poll)
                poll = self._poll_next(poll)
        sp.fail()
        return False

    def _poll_s(self) -> float:
        """Initial status-poll interval. The local backend is a direct
        call so it polls fast; the remote backend starts fast too — a
        tiny job finishes in well under a second and a fixed 5 s sleep
        before the FIRST poll just burns latency — and backs off
        geometrically to the reference's 5 s steady-state."""
        return 0.1

    def _poll_next(self, poll: float) -> float:
        if self.backend == "tpu":
            return poll
        return min(5.0, poll * 1.6)

    def _wait_terminal(self, job_id: str, timeout: int) -> str:
        poll = self._poll_s()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if JobStatus(status).is_terminal():
                return status
            time.sleep(poll)
            poll = self._poll_next(poll)
        raise TimeoutError(f"Job {job_id} still running after {timeout}s")

    def await_job_completion(
        self,
        job_id: str,
        timeout: int = 7200,
        obtain_results: bool = True,
        output_column: str = "inference_result",
        unpack_json: bool = True,
        with_original_df: Optional[Any] = None,
    ) -> Any:
        """Block until terminal state; fetch results on success (reference
        sdk.py:1563-1638; 5 s poll remote, fast poll local)."""
        try:
            status = self._wait_terminal(job_id, timeout)
        except TimeoutError:
            print(to_colored_text("✗ Timed out awaiting job", "fail"))
            return None
        if status != JobStatus.SUCCEEDED.value:
            reason = self._get_failure_reason(job_id)
            print(to_colored_text(f"✗ Job {status}: {reason}", "fail"))
            return None
        if not obtain_results:
            return job_id
        return self.get_job_results(
            job_id,
            output_column=output_column,
            unpack_json=unpack_json,
            with_original_df=with_original_df,
        )

    def attach(self, job_id: str) -> None:
        """Re-attach a progress bar to a job (reference sdk.py:800-911)."""
        rec = self._fetch_job(job_id)
        status = rec.get("status")
        if status in (JobStatus.FAILED.value, JobStatus.CANCELLED.value):
            print(
                to_colored_text(
                    f"Cannot attach: job is {status}", "fail"
                )
            )
            return
        if status == JobStatus.SUCCEEDED.value:
            print(to_colored_text("Job already succeeded", "success"))
            return
        self._stream_progress_to_tqdm(job_id)

    # ------------------------------------------------------------------
    # results (reference sdk.py:1078-1260; exact contract SURVEY §2.4)
    # ------------------------------------------------------------------

    def _cache_dir(self) -> Path:
        d = config_dir() / "job-results"
        d.mkdir(parents=True, exist_ok=True)
        return d

    def get_job_results(
        self,
        job_id: str,
        include_inputs: bool = False,
        include_cumulative_logprobs: bool = False,
        output_column: str = "inference_result",
        unpack_json: bool = True,
        with_original_df: Optional[Any] = None,
        disable_cache: bool = False,
    ) -> Optional[pd.DataFrame]:
        cache_path = self._cache_dir() / f"{job_id}.snappy.parquet"
        expected_cols = 1 + int(include_inputs) + int(
            include_cumulative_logprobs
        )
        df: Optional[pd.DataFrame] = None
        if not disable_cache and cache_path.exists():
            cached = pd.read_parquet(cache_path)
            # cache hit requires matching column count (sdk.py:1109-1113)
            if len(cached.columns) == expected_cols:
                df = cached.rename(columns={"outputs": output_column})

        if df is None:
            if self.backend == "remote":
                body = self._remote_json(
                    "post",
                    "job-results",
                    json={
                        "job_id": job_id,
                        "include_inputs": include_inputs,
                        "include_cumulative_logprobs": include_cumulative_logprobs,
                    },
                )
                results = body["results"]
            else:
                results = self.engine.job_results(
                    job_id,
                    include_inputs=include_inputs,
                    include_cumulative_logprobs=include_cumulative_logprobs,
                )
            cols: Dict[str, Any] = {}
            if include_inputs and "inputs" in results:
                cols["inputs"] = results["inputs"]
            cols["outputs"] = results["outputs"]
            if (
                include_cumulative_logprobs
                and "cumulative_logprobs" in results
            ):
                cols["cumulative_logprobs"] = results["cumulative_logprobs"]
            if "confidence_score" in results:  # Functions only
                cols["confidence_score"] = results["confidence_score"]
            df = pd.DataFrame(cols)
            if not disable_cache:
                # always cache (the reference's tracing-gated cache write,
                # sdk.py:1172-1190, is a bug we don't reproduce); stage
                # ids ("job-X/stages/rank") nest below the cache root
                cache_path.parent.mkdir(parents=True, exist_ok=True)
                df.to_parquet(cache_path)
            df = df.rename(columns={"outputs": output_column})

        # LangSmith batch-trace completion (reference sdk.py:1173-1181)
        if tracing_enabled() and _has_open_batch_traces(job_id):
            rec = self._fetch_job(job_id)
            _complete_batch_traces(
                job_id,
                df[output_column].tolist(),
                rec.get("input_tokens", 0) or 0,
                rec.get("output_tokens", 0) or 0,
            )

        if unpack_json:
            df = self._unpack_json_outputs(df, output_column)

        if with_original_df is not None:
            if HAS_POLARS and isinstance(with_original_df, pl.DataFrame):
                df = with_original_df.with_columns(
                    **{c: pl.Series(df[c]) for c in df.columns}
                )
            elif isinstance(with_original_df, pd.DataFrame):
                df = pd.concat(
                    [
                        with_original_df.reset_index(drop=True),
                        df.reset_index(drop=True),
                    ],
                    axis=1,
                )
        return df

    @staticmethod
    def _unpack_json_outputs(
        df: pd.DataFrame, output_column: str
    ) -> pd.DataFrame:
        """If row 0 JSON-decodes to a dict, unpack top-level fields to
        columns; thinking models' {content, reasoning_content} get content
        additionally unpacked (reference sdk.py:1207-1240; failures no-op)."""
        try:
            if not len(df):
                return df
            first = df[output_column].iloc[0]
            parsed = json.loads(first) if isinstance(first, str) else None
            if not isinstance(parsed, dict):
                return df
            unpacked = [
                json.loads(x) if isinstance(x, str) else {}
                for x in df[output_column]
            ]
            keys = list(parsed.keys())
            if set(keys) == {"content", "reasoning_content"}:
                # thinking models: unpack content struct, drop it
                content = [
                    u.get("content") for u in unpacked
                ]
                df = df.assign(
                    reasoning_content=[
                        u.get("reasoning_content") for u in unpacked
                    ]
                )
                try:
                    inner = [
                        json.loads(c) if isinstance(c, str) else c
                        for c in content
                    ]
                    if inner and isinstance(inner[0], dict):
                        for k in inner[0]:
                            df[k] = [
                                (d or {}).get(k) for d in inner
                            ]
                    else:
                        df["content"] = content
                except Exception:
                    df["content"] = content
                return df
            for k in keys:
                df[k] = [u.get(k) for u in unpacked]
            return df
        except Exception:
            return df

    # ------------------------------------------------------------------
    # datasets (reference sdk.py:1289-1516)
    # ------------------------------------------------------------------

    def create_dataset(self) -> str:
        if self.backend == "remote":
            return self._remote_json("get", "create-dataset")["dataset_id"]
        return self.engine.datasets.create()

    def upload_to_dataset(
        self,
        dataset_id: str,
        file_paths: Union[str, List[str]],
        verbose: bool = True,
    ) -> List[str]:
        if isinstance(file_paths, str):
            file_paths = [file_paths]
        if self.backend == "remote":
            uploaded = []
            for p in file_paths:
                with open(p, "rb") as f:
                    self._remote_json(
                        "post",
                        "upload-to-dataset",
                        files={"file": f},
                        data={"dataset_id": dataset_id},
                    )
                uploaded.append(os.path.basename(p))
            return uploaded
        names = self.engine.datasets.upload(dataset_id, file_paths)
        if verbose:
            print(
                to_colored_text(
                    f"✔ Uploaded {len(names)} file(s) to {dataset_id}",
                    "success",
                )
            )
        return names

    def list_datasets(self) -> List[Dict[str, Any]]:
        if self.backend == "remote":
            return self._remote_json("post", "list-datasets")["datasets"]
        return self.engine.datasets.list_datasets()

    def list_dataset_files(self, dataset_id: str) -> List[str]:
        if self.backend == "remote":
            return self._remote_json(
                "post", "list-dataset-files", json={"dataset_id": dataset_id}
            )["files"]
        return self.engine.datasets.list_files(dataset_id)

    def download_from_dataset(
        self,
        dataset_id: str,
        file_names: Optional[Union[str, List[str]]] = None,
        output_path: Optional[str] = None,
    ) -> List[str]:
        if file_names is None:
            file_names = self.list_dataset_files(dataset_id)
        if isinstance(file_names, str):
            file_names = [file_names]
        out_dir = output_path or "."
        written = []
        for fname in file_names:
            if self.backend == "remote":
                resp = self.do_request(
                    "post",
                    "download-from-dataset",
                    json={"dataset_id": dataset_id, "file_name": fname},
                )
                resp.raise_for_status()
                dst = Path(out_dir) / fname
                dst.parent.mkdir(parents=True, exist_ok=True)
                dst.write_bytes(resp.content)
                written.append(str(dst))
            else:
                written.append(
                    str(
                        self.engine.datasets.download(
                            dataset_id, fname, out_dir
                        )
                    )
                )
        return written

    # ------------------------------------------------------------------
    # auth / quotas / cache
    # ------------------------------------------------------------------

    def try_authentication(
        self, api_key: Optional[str] = None
    ) -> Dict[str, Any]:
        if self.backend == "remote":
            key = api_key or self.api_key
            resp = self.do_request(
                "get",
                "try-authentication",
                headers={"Authorization": f"Key {key}"},
            )
            resp.raise_for_status()
            return resp.json()
        return self.engine.try_authentication()

    def get_quotas(self) -> List[Dict[str, int]]:
        if self.backend == "remote":
            return self._remote_json("get", "get-quotas")["quotas"]
        return self.engine.get_quotas()

    def get_fleet(self) -> Optional[Dict[str, Any]]:
        """Fleet router snapshot (fleet/remote backend pointed at a
        ``sutro fleet`` router): replica membership, breaker states,
        failover counters, and the fleet doctor verdict. None when the
        endpoint doesn't exist (single daemon / local backend)."""
        if self.backend != "remote":
            return None
        resp = self.do_request("get", "fleet")
        if resp.status_code == 404:
            return None
        resp.raise_for_status()
        return resp.json().get("fleet")

    def get_fleet_monitor(self) -> Optional[Dict[str, Any]]:
        """Fleet SLO monitor snapshot from a fleet router
        (OBSERVABILITY.md "Fleet observability"): fleet-wide windowed
        stats, rule states, alert events with exemplar trace ids, and
        the fleet doctor verdict. None when the endpoint doesn't exist
        (single daemon / local backend); raises ``KeyError`` when the
        router answers but the monitor is disabled."""
        if self.backend != "remote":
            return None
        resp = self.do_request("get", "fleet-monitor")
        if resp.status_code == 404:
            try:
                detail = resp.json().get("error", "")
            except ValueError:
                detail = ""
            if "disabled" in str(detail):
                raise KeyError(detail)
            return None
        resp.raise_for_status()
        return resp.json().get("fleet_monitor")

    def get_replay_log(self) -> Optional[List[Dict[str, Any]]]:
        """Replayable records drained from a fleet router's trace ring
        (``sutro replay record``). None when the endpoint doesn't
        exist (single daemon / local backend)."""
        if self.backend != "remote":
            return None
        resp = self.do_request("get", "replay-log")
        if resp.status_code == 404:
            return None
        resp.raise_for_status()
        return resp.json().get("records")

    def clear_job_results_cache(self) -> int:
        """Remove ~/.sutro/job-results (reference sdk.py:1640-1675)."""
        d = self._cache_dir()
        n = len(list(d.glob("*.parquet")))
        shutil.rmtree(d, ignore_errors=True)
        return n

    def show_job_results_cache(self) -> List[Dict[str, Any]]:
        d = self._cache_dir()
        out = []
        for f in sorted(d.glob("*.parquet")):
            out.append(
                {
                    "file": f.name,
                    "size": human_bytes(f.stat().st_size),
                }
            )
        return out
