"""Client protocol and job state machine.

TPU-native re-design of the reference's ``sutro/interfaces.py`` (see
/root/reference/sutro/interfaces.py:11-91): the ``JobStatus`` state machine and
the ``BaseSutroClient`` protocol that the task-template mixins type-check
against. States match the reference's lifecycle (terminal states per
interfaces.py:81-88) so user code observing job status ports over unchanged.
"""

from __future__ import annotations

from enum import Enum
from typing import Any, Dict, List, Optional, Protocol, Union, runtime_checkable


class JobStatus(str, Enum):
    """Lifecycle states of a batch-inference job.

    Mirrors the reference state machine (interfaces.py:69-91). In the TPU
    build these states are driven by the in-process engine scheduler rather
    than a remote service:

    - QUEUED:     accepted by the jobstore, waiting for an engine slot
    - STARTING:   weights loading / compile in flight
    - RUNNING:    rows being prefilled/decoded
    - SUCCEEDED:  all rows finished; results visible (invariant: results are
                  written to the jobstore *before* the state flips — see
                  engine/jobstore.py — which deletes the reference's
                  results-availability race, sdk.py:384-401)
    - FAILED:     terminal failure; ``failure_reason`` is populated
    - CANCELLING: cancel requested, engine draining
    - CANCELLED:  terminal cancel
    """

    QUEUED = "QUEUED"
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    CANCELLING = "CANCELLING"
    CANCELLED = "CANCELLED"
    # Local-engine extra: job record exists but its results were evicted.
    UNAVAILABLE = "UNAVAILABLE"

    def is_terminal(self) -> bool:
        """Terminal set matches the reference (interfaces.py:81-88)."""
        return self in (
            JobStatus.SUCCEEDED,
            JobStatus.FAILED,
            JobStatus.CANCELLING,
            JobStatus.CANCELLED,
        )

    def is_active(self) -> bool:
        return self in (JobStatus.QUEUED, JobStatus.STARTING, JobStatus.RUNNING)


@runtime_checkable
class BaseSutroClient(Protocol):
    """Structural type for the client core, used by template mixins.

    The template mixins (templates/*.py) are mixed into ``Sutro`` via MRO and
    call back into the client through this protocol (reference
    interfaces.py:11-66).
    """

    def infer(
        self,
        data: Any,
        model: str = "qwen-3-4b",
        column: Optional[Union[str, List[str]]] = None,
        output_column: str = "inference_result",
        job_priority: int = 0,
        output_schema: Optional[Any] = None,
        system_prompt: Optional[str] = None,
        name: Optional[str] = None,
        description: Optional[str] = None,
        dry_run: bool = False,
        stay_attached: Optional[bool] = None,
        truncate_rows: bool = True,
        random_seed_per_input: bool = False,
        sampling_params: Optional[Dict[str, Any]] = None,
    ) -> Any:
        ...

    def run_graph(
        self,
        data: Any,
        stages: List[Dict[str, Any]],
        model: str = "qwen-3-4b",
        column: Optional[Union[str, List[str]]] = None,
        output_column: str = "inference_result",
        job_priority: int = 0,
        name: Optional[str] = None,
        description: Optional[str] = None,
        dry_run: bool = False,
        stay_attached: Optional[bool] = None,
        truncate_rows: bool = True,
        sampling_params: Optional[Dict[str, Any]] = None,
        tenant: Optional[str] = None,
    ) -> Any:
        ...

    def await_job_completion(
        self,
        job_id: str,
        timeout: int = 7200,
        obtain_results: bool = True,
        output_column: str = "inference_result",
        unpack_json: bool = True,
        with_original_df: Optional[Any] = None,
    ) -> Any:
        ...

    def get_job_results(
        self,
        job_id: str,
        include_inputs: bool = False,
        include_cumulative_logprobs: bool = False,
        output_column: str = "inference_result",
        unpack_json: bool = True,
        with_original_df: Optional[Any] = None,
        disable_cache: bool = False,
    ) -> Any:
        ...
