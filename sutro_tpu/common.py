"""Data preparation, model registry, and terminal UX helpers.

TPU-native re-design of the reference's ``sutro/common.py``
(/root/reference/sutro/common.py:11-265). Differences from the reference:

- ``polars`` and ``yaspin`` are optional here (gated imports); pandas is the
  primary DataFrame type and a small built-in spinner replaces yaspin.
- The model catalog maps each public model name to an engine model key
  (family + size + variant) consumed by ``sutro_tpu.models.registry`` —
  in the reference the catalog is only a ``Literal`` for autocompletion
  (common.py:11-45) because execution is remote.
- The duplicate ``"llama-3.3-70b"`` literal (reference common.py:23-24,
  SURVEY §2.5) is intentionally not reproduced.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Literal, Optional, Type, Union

import pandas as pd

try:  # optional; the reference hard-requires polars, we degrade gracefully
    import polars as pl  # type: ignore

    HAS_POLARS = True
except Exception:  # pragma: no cover
    pl = None  # type: ignore
    HAS_POLARS = False

from colorama import Fore, Style
from pydantic import BaseModel
from tqdm.auto import tqdm

# ---------------------------------------------------------------------------
# Model registry
# ---------------------------------------------------------------------------

EmbeddingModelOptions = Literal[
    "qwen-3-embedding-0.6b",
    "qwen-3-embedding-6b",
    "qwen-3-embedding-8b",
]

# Public model names (autocompletion parity with reference common.py:20-45);
# `| str` keeps the escape hatch used for Functions.
ModelOptions = Union[
    Literal[
        "llama-3.2-3b",
        "llama-3.1-8b",
        "llama-3.3-70b",
        "qwen-3-0.6b",
        "qwen-3-4b",
        "qwen-3-8b",
        "qwen-3-14b",
        "qwen-3-32b",
        "qwen-3-30b-a3b",
        "qwen-3-235b-a22b",
        "qwen-3-4b-thinking",
        "qwen-3-14b-thinking",
        "qwen-3-32b-thinking",
        "qwen-3-235b-a22b-thinking",
        "qwen-3-30b-a3b-thinking",
        "gemma-3-4b-it",
        "gemma-3-12b-it",
        "gemma-3-27b-it",
        "gpt-oss-20b",
        "gpt-oss-120b",
        "qwen-3-embedding-0.6b",
        "qwen-3-embedding-6b",
        "qwen-3-embedding-8b",
    ],
    str,
]


def model_catalog() -> Dict[str, Dict[str, Any]]:
    """Public model name -> engine metadata.

    ``engine_key`` indexes ``sutro_tpu.models.registry.MODEL_CONFIGS``;
    ``thinking`` toggles reasoning-content output unpacking (reference
    sdk.py:1225-1234); ``embedding`` selects the pooled-embedding head path (last-token for Qwen3-Embedding).
    """
    cat: Dict[str, Dict[str, Any]] = {}

    def add(name: str, engine_key: str, **kw: Any) -> None:
        cat[name] = {"engine_key": engine_key, "thinking": False, "embedding": False, **kw}

    add("llama-3.2-3b", "llama-3.2-3b")
    add("llama-3.1-8b", "llama-3.1-8b")
    add("llama-3.3-70b", "llama-3.3-70b")
    add("qwen-3-0.6b", "qwen3-0.6b")
    add("qwen-3-4b", "qwen3-4b")
    add("qwen-3-8b", "qwen3-8b")
    add("qwen-3-14b", "qwen3-14b")
    add("qwen-3-32b", "qwen3-32b")
    add("qwen-3-30b-a3b", "qwen3-30b-a3b")
    add("qwen-3-235b-a22b", "qwen3-235b-a22b")
    for base in ["qwen-3-4b", "qwen-3-14b", "qwen-3-32b", "qwen-3-235b-a22b", "qwen-3-30b-a3b"]:
        add(base + "-thinking", cat[base]["engine_key"], thinking=True)
    add("gemma-3-4b-it", "gemma3-4b")
    add("gemma-3-12b-it", "gemma3-12b")
    add("gemma-3-27b-it", "gemma3-27b")
    add("gpt-oss-20b", "gpt-oss-20b")
    add("gpt-oss-120b", "gpt-oss-120b")
    add("qwen-3-embedding-0.6b", "qwen3-emb-0.6b", embedding=True)
    add("qwen-3-embedding-6b", "qwen3-emb-6b", embedding=True)
    add("qwen-3-embedding-8b", "qwen3-emb-8b", embedding=True)
    return cat


MODEL_CATALOG = model_catalog()

# ---------------------------------------------------------------------------
# Terminal UX
# ---------------------------------------------------------------------------

BASE_OUTPUT_COLOR = Fore.BLUE


def is_jupyter() -> bool:
    """Jupyter/non-tty detection (reference common.py:49-50)."""
    return not sys.stdout.isatty()


def make_clickable_link(url: str, text: Optional[str] = None) -> str:
    """OSC-8 clickable hyperlink with plain fallback (reference common.py:53-64)."""
    if is_jupyter():
        return url
    label = text or url
    return f"\033]8;;{url}\033\\{label}\033]8;;\033\\"


def to_colored_text(
    text: str, state: Optional[str] = None
) -> str:
    """Color text by state: success=green, fail=red, callout=magenta,
    default=blue (reference common.py:179-206)."""
    if state == "success":
        color = Fore.GREEN
    elif state in ("fail", "error"):
        color = Fore.RED
    elif state == "callout":
        color = Fore.MAGENTA
    else:
        color = BASE_OUTPUT_COLOR
    return f"{color}{text}{Style.RESET_ALL}"


def fancy_tqdm(
    total: int,
    desc: str = "Progress",
    color: str = "blue",
    style: int = 1,
    postfix: Optional[str] = None,
) -> tqdm:
    """Styled progress bar (reference common.py:209-265; the reference also
    duplicates this as a method at sdk.py:913-970 — we keep one copy)."""
    if style == 1:
        bar_format = (
            "{desc}: {percentage:3.0f}%|{bar}| {n_fmt}/{total_fmt} "
            "[{elapsed}<{remaining}, {rate_fmt}{postfix}]"
        )
    else:
        bar_format = "{l_bar}{bar}{r_bar}"
    return tqdm(
        total=total,
        desc=desc,
        colour=color,
        bar_format=bar_format,
        postfix=postfix,
        dynamic_ncols=True,
    )


class Spinner:
    """Minimal yaspin replacement (yaspin isn't in this environment).

    Context manager printing ``text`` once on entry and a state glyph on
    exit; exposes ``.text``, ``.ok()``, ``.fail()``, ``.stop()`` so call
    sites read like the reference's yaspin usage (e.g. sdk.py:229,
    1588-1601).
    """

    def __init__(self, text: str = "", color: Optional[str] = None):
        self.text = text
        self._done = False

    def __enter__(self) -> "Spinner":
        if self.text:
            print(to_colored_text(self.text), flush=True)
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    def write(self, msg: str) -> None:
        print(msg, flush=True)

    def ok(self, glyph: str = "✔") -> None:
        if not self._done:
            print(to_colored_text(f"{glyph} {self.text}", "success"), flush=True)
            self._done = True

    def fail(self, glyph: str = "✗") -> None:
        if not self._done:
            print(to_colored_text(f"{glyph} {self.text}", "fail"), flush=True)
            self._done = True

    def stop(self) -> None:
        self._done = True


spinner = Spinner

# ---------------------------------------------------------------------------
# Input data preparation
# ---------------------------------------------------------------------------


def do_dataframe_column_concatenation(
    df: Any, column: List[Any]
) -> List[str]:
    """Concatenate multiple columns (with literal separator strings) into one
    list of row strings (reference common.py:72-108).

    ``column`` is a list whose elements are either column names or literal
    separator strings, e.g. ``["title", ": ", "body"]``.
    """
    if HAS_POLARS and pl is not None and isinstance(df, pl.DataFrame):
        names = set(df.columns)
        exprs = [
            pl.col(c).cast(pl.Utf8) if c in names else pl.lit(str(c))
            for c in column
        ]
        return df.select(pl.concat_str(exprs).alias("__concat__"))["__concat__"].to_list()
    if isinstance(df, pd.DataFrame):
        names = set(df.columns)
        out = None
        for c in column:
            part = (
                df[c].astype(str)
                if c in names
                else pd.Series([str(c)] * len(df), index=df.index)
            )
            out = part if out is None else out + part
        return [] if out is None else out.tolist()
    raise ValueError(f"Unsupported dataframe type: {type(df)}")


def _column_to_list(df: Any, column: Union[str, List[Any]]) -> List[str]:
    if isinstance(column, list):
        return do_dataframe_column_concatenation(df, column)
    if HAS_POLARS and pl is not None and isinstance(df, pl.DataFrame):
        return [str(x) for x in df[column].to_list()]
    return [str(x) for x in df[column].tolist()]


def prepare_input_data(
    data: Any,
    column: Optional[Union[str, List[Any]]] = None,
) -> Union[List[str], str]:
    """Normalize user input into the engine's ``inputs`` payload.

    Accepts (reference common.py:111-162): a list of strings, a
    pandas/polars DataFrame (requires ``column``), a path to
    ``.csv``/``.parquet``/``.txt``, a ``dataset-<id>`` string (passed through
    for engine-side resolution), or an http(s) URL (passed through).
    Returns a list of row strings, or the untouched dataset-id/URL string.
    """
    if isinstance(data, str):
        if data.startswith("dataset-"):
            return data  # resolved by the engine's dataset store
        if data.startswith("http://") or data.startswith("https://"):
            return data
        lower = data.lower()
        if lower.endswith(".csv"):
            df = pd.read_csv(data)
            if column is None:
                raise ValueError("`column` is required when passing a CSV file")
            return _column_to_list(df, column)
        if lower.endswith(".parquet"):
            df = pd.read_parquet(data)
            if column is None:
                raise ValueError("`column` is required when passing a Parquet file")
            return _column_to_list(df, column)
        if lower.endswith(".txt"):
            with open(data) as f:
                return [line.rstrip("\n") for line in f if line.strip()]
        raise ValueError(
            f"Unsupported input: {data!r}. Expected a list of strings, a "
            "DataFrame, a .csv/.parquet/.txt path, a dataset-<id>, or a URL."
        )
    if isinstance(data, (list, tuple)):
        return [str(x) for x in data]
    if isinstance(data, pd.Series):
        return [str(x) for x in data.tolist()]
    if isinstance(data, pd.DataFrame) or (
        HAS_POLARS and pl is not None and isinstance(data, (pl.DataFrame,))
    ):
        if column is None:
            raise ValueError(
                "`column` must be specified when passing a DataFrame"
            )
        return _column_to_list(data, column)
    if HAS_POLARS and pl is not None and isinstance(data, pl.Series):
        return [str(x) for x in data.to_list()]
    raise ValueError(f"Unsupported input data type: {type(data)}")


def normalize_output_schema(
    output_schema: Union[Type[BaseModel], Dict[str, Any], None],
) -> Optional[Dict[str, Any]]:
    """Pydantic model class or dict -> JSON schema dict (reference
    common.py:165-176)."""
    if output_schema is None:
        return None
    if isinstance(output_schema, dict):
        return output_schema
    if isinstance(output_schema, type) and issubclass(output_schema, BaseModel):
        return output_schema.model_json_schema()
    raise ValueError(
        "output_schema must be a Pydantic BaseModel subclass or a JSON-schema dict, "
        f"got {type(output_schema)}"
    )


def human_bytes(n: float) -> str:
    for unit in ["B", "KB", "MB", "GB", "TB"]:
        if abs(n) < 1024.0:
            return f"{n:.2f} {unit}"
        n /= 1024.0
    return f"{n:.2f} PB"
