"""Per-request trace store: end-to-end timelines for tail forensics.

The flight recorder (:mod:`.spans`) is a process-wide ring — great for
"what was the engine doing", useless for "where did THIS request's
time go": its spans are batch-wide and the ring evicts under load. A
*trace* is the per-request view: every interactive request (and every
batch job) gets a ``trace_id`` propagated through the gateway, the
scheduler (queue wait, preemption suspend/resume, prefix hit/extend,
per-window accept), and the server's SSE flush loop; each leg lands as
a child span under that id. The store is a bounded ring of traces
(oldest evicted), each trace a bounded list of spans (overflow counted,
never grown) — a month-long daemon holds the last N requests' shapes,
never more.

Naming contract (graftlint ``trace-ctx-dropped``): the pass treats
``start_trace`` as an acquire and ``end_trace`` / ``Trace.end`` as the
release, so a held trace handle must be ended (or ownership-
transferred) on every exit path of the function that started it.
Call sites that start and end a trace in *different* functions key the
handoff by trace_id string, which the pass does not track — by design:
the string is the propagated context, the handle is a local resource.

dp-awareness: a coordinator job's trace carries the round-10 wire
trace context (``attrs["dp_trace"] = "<job>/r<round>"``) so a
cross-process timeline can be joined to the per-rank sections the
federation layer ingests.

Everything here is called behind ``telemetry.ENABLED`` checks at the
instrumented sites — the store itself stays allocation-free when the
switch is off because no caller reaches it (asserted by the op census
in benchmarks/profile_host_overhead.py --telemetry).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, List, Optional

#: traces kept (oldest evicted) — a request museum, not an archive
DEFAULT_TRACE_CAPACITY = 256
#: spans kept per trace; beyond it spans drop and ``dropped`` counts
MAX_SPANS_PER_TRACE = 512

SCHEMA_VERSION = 1


class Trace:
    """One request's (or job's) timeline. Single-writer-ish by
    construction — the gateway/server thread and the engine worker
    thread interleave appends, and ``list.append`` is GIL-atomic, so
    recording takes no lock; reads copy."""

    __slots__ = (
        "trace_id", "kind", "t0_mono", "created_unix", "attrs",
        "_spans", "dropped", "finished", "outcome",
    )

    def __init__(
        self,
        trace_id: str,
        kind: str,
        attrs: Optional[Dict[str, Any]] = None,
        *,
        t0_mono: Optional[float] = None,
        created_unix: Optional[float] = None,
    ) -> None:
        self.trace_id = trace_id
        self.kind = kind  # interactive | batch
        self.t0_mono = time.monotonic() if t0_mono is None else t0_mono
        self.created_unix = (
            time.time() if created_unix is None else created_unix
        )
        self.attrs: Dict[str, Any] = dict(attrs or ())
        # tuple-shaped spans, same rationale as the flight recorder:
        # (name, t0_rel_s, dur_s, attrs)
        self._spans: List[tuple] = []
        self.dropped = 0
        self.finished = False
        self.outcome: Optional[str] = None

    def add(
        self,
        name: str,
        t0_mono: float,
        dur_s: float,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Append one child span (start given on the process monotonic
        clock; stored relative to the trace start)."""
        if len(self._spans) >= MAX_SPANS_PER_TRACE:
            self.dropped += 1
            return
        self._spans.append(
            (name, t0_mono - self.t0_mono, dur_s, attrs)
        )

    def event(
        self, name: str, t_mono: Optional[float] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Zero-duration instant (preempt_suspend, prefix_hit, ...)."""
        self.add(
            name, time.monotonic() if t_mono is None else t_mono,
            0.0, attrs,
        )

    def end(self, outcome: str = "ok") -> None:
        self.finished = True
        self.outcome = outcome

    def to_doc(self) -> Dict[str, Any]:
        """The per-request timeline document (OBSERVABILITY.md
        "Forensics"): spans sorted by start offset, attrs preserved."""
        spans = []
        for name, t0, dur, attrs in sorted(
            list(self._spans), key=lambda s: (s[1], s[0])
        ):
            d: Dict[str, Any] = {
                "name": name,
                "t0_s": round(t0, 6),
                "dur_s": round(dur, 6),
            }
            if attrs:
                d["attrs"] = dict(attrs)
            spans.append(d)
        doc: Dict[str, Any] = {
            "version": SCHEMA_VERSION,
            "trace_id": self.trace_id,
            "kind": self.kind,
            "created_unix": self.created_unix,
            "finished": self.finished,
            "outcome": self.outcome,
            "dropped": self.dropped,
            "stages": sorted({s["name"] for s in spans}),
            "spans": spans,
        }
        if self.attrs:
            doc["attrs"] = dict(self.attrs)
        return doc


class TraceStore:
    """Bounded trace_id -> Trace ring (oldest evicted). The lock guards
    creation/eviction only; span appends go straight at the trace."""

    def __init__(self, capacity: int = DEFAULT_TRACE_CAPACITY) -> None:
        self.capacity = max(int(capacity), 8)
        self._lock = threading.Lock()
        self._traces: "collections.OrderedDict[str, Trace]" = (
            collections.OrderedDict()
        )

    def start_trace(
        self,
        trace_id: str,
        kind: str = "interactive",
        attrs: Optional[Dict[str, Any]] = None,
        **fixed: Any,
    ) -> Trace:
        """Create (or return the existing) trace for ``trace_id``.
        ``fixed`` forwards deterministic clocks (``t0_mono``,
        ``created_unix``) for golden tests."""
        with self._lock:
            tr = self._traces.get(trace_id)
            if tr is None:
                tr = Trace(trace_id, kind, attrs, **fixed)
                self._traces[trace_id] = tr
                while len(self._traces) > self.capacity:
                    self._traces.popitem(last=False)
            return tr

    def end_trace(self, trace_id: str, outcome: str = "ok") -> None:
        tr = self._traces.get(trace_id)
        if tr is not None:
            tr.end(outcome)

    def add(
        self,
        trace_id: str,
        name: str,
        t0_mono: float,
        dur_s: float,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Append a span by id — the fan-out form the scheduler's
        batch-wide sink uses (no handle threading through the loop)."""
        tr = self._traces.get(trace_id)
        if tr is not None:
            tr.add(name, t0_mono, dur_s, attrs)

    def event(
        self, trace_id: str, name: str,
        attrs: Optional[Dict[str, Any]] = None,
        t_mono: Optional[float] = None,
    ) -> None:
        tr = self._traces.get(trace_id)
        if tr is not None:
            tr.event(name, t_mono=t_mono, attrs=attrs)

    def get(self, trace_id: str) -> Optional[Trace]:
        return self._traces.get(trace_id)

    def doc(self, trace_id: str) -> Optional[Dict[str, Any]]:
        tr = self._traces.get(trace_id)
        return None if tr is None else tr.to_doc()

    def ids(self) -> List[str]:
        return list(self._traces)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
