"""Span tracer + bounded ring-buffer flight recorder.

Every engine stage (tokenize, constraint compile, prefill, decode
window, accept, flush, finalize, dp round) records a :class:`Span`:
a name, an optional owning job id, a start offset on the recorder's
monotonic timeline, a duration, and small free-form attrs. Spans land
in a fixed-capacity ring (``collections.deque(maxlen=...)``) — a
month-long daemon holds the last N spans, never more — and the ring is
the *flight recorder*: when a job FAILs (or on demand) the engine dumps
the job's slice of the timeline to
``$SUTRO_HOME/jobs/<job_id>/telemetry.json`` next to PR 3's
``failure_log[]``, answering "what was the engine doing when job X
died?" without a rerun.

Threading: ``deque.append`` with a maxlen is atomic under the GIL, so
recording takes no lock; snapshotting copies the ring (bounded) and
filters. Scheduler-level spans may be shared by several co-batched
jobs — those carry the live job ids in ``attrs["jobs"]`` and a
``job_id`` of None; the per-job filter matches either.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

DEFAULT_CAPACITY = 4096


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self._buf: "collections.deque" = collections.deque(
            maxlen=max(int(capacity), 16)
        )
        # epoch pair: spans are stored relative to the monotonic epoch;
        # the wall epoch lets dumps render absolute timestamps
        self.epoch_mono = time.monotonic()
        self.epoch_wall = time.time()
        self.dropped = 0  # ring evictions are implicit; this counts
        #                   records only when the ring was full
        self._full = False

    @property
    def capacity(self) -> int:
        return self._buf.maxlen or 0

    def record(
        self,
        name: str,
        job_id: Optional[str],
        t0_mono: float,
        dur_s: float,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Append one span. Tuple-shaped on purpose (no dataclass
        alloc on the hot path): (name, job_id, t0_rel, dur, attrs)."""
        if self._full:
            self.dropped += 1
        elif len(self._buf) + 1 >= (self._buf.maxlen or 0):
            self._full = True
        self._buf.append(
            (name, job_id, t0_mono - self.epoch_mono, dur_s, attrs)
        )

    class _SpanCtx:
        __slots__ = ("rec", "name", "job_id", "attrs", "t0")

        def __init__(self, rec, name, job_id, attrs):
            self.rec = rec
            self.name = name
            self.job_id = job_id
            self.attrs = attrs

        def __enter__(self):
            self.t0 = time.monotonic()
            return self

        def __exit__(self, et, ev, tb):
            t1 = time.monotonic()
            attrs = self.attrs
            if et is not None:
                attrs = dict(attrs or ())
                attrs["error"] = f"{et.__name__}: {ev}"
            self.rec.record(
                self.name, self.job_id, self.t0, t1 - self.t0, attrs
            )
            return False

    def span(
        self,
        name: str,
        job_id: Optional[str] = None,
        **attrs: Any,
    ) -> "FlightRecorder._SpanCtx":
        """Context manager recording one span (errors annotate the
        span and propagate)."""
        return self._SpanCtx(self, name, job_id, attrs or None)

    # -- reads ---------------------------------------------------------

    def _matches(self, entry, job_id: Optional[str]) -> bool:
        if job_id is None:
            return True
        if entry[1] == job_id:
            return True
        attrs = entry[4]
        return bool(attrs) and job_id in (attrs.get("jobs") or ())

    def snapshot(self, job_id: Optional[str] = None) -> List[Dict[str, Any]]:
        """Spans (oldest first) as dicts: name, job_id, t0_s (relative
        to the recorder epoch), dur_s, attrs. Filtered to one job when
        ``job_id`` is given (scheduler spans tagged with the job in
        ``attrs['jobs']`` count)."""
        out = []
        for entry in list(self._buf):
            if not self._matches(entry, job_id):
                continue
            name, jid, t0, dur, attrs = entry
            d: Dict[str, Any] = {
                "name": name,
                "job_id": jid,
                "t0_s": round(t0, 6),
                "dur_s": round(dur, 6),
            }
            if attrs:
                d["attrs"] = dict(attrs)
            out.append(d)
        return out

    def stages(self, job_id: Optional[str] = None) -> List[str]:
        """Distinct span names present (sorted)."""
        return sorted({s["name"] for s in self.snapshot(job_id)})

    def clear(self) -> None:
        self._buf.clear()
        self._full = False
        self.dropped = 0
        self.epoch_mono = time.monotonic()
        self.epoch_wall = time.time()


class JobCounters:
    """Per-job counter accumulator for exact reconciliation against job
    results (rows ok/quarantined/cancelled, tokens in/out, retries).

    These are NOT registry metrics: job ids are unbounded, so they stay
    out of the label space. Single-writer by construction — the engine
    worker thread (or the dp coordinator's serialized result path)
    owns a job's accumulator — so plain dict arithmetic is exact.

    ``attrs`` carries small non-numeric per-job facts that belong in
    the telemetry document but not in counters: the runner's device
    info (the doctor's roofline denominator), the active jax profiler
    trace path, the dp trace id."""

    __slots__ = ("job_id", "counters", "attrs")

    def __init__(self, job_id: str) -> None:
        self.job_id = job_id
        self.counters: Dict[str, float] = {}
        self.attrs: Dict[str, Any] = {}

    def add(self, key: str, n: float = 1.0) -> None:
        self.counters[key] = self.counters.get(key, 0.0) + n

    def set(self, key: str, v: float) -> None:
        self.counters[key] = float(v)

    def to_dict(self) -> Dict[str, Any]:
        return {
            k: (int(v) if v == int(v) else v)
            for k, v in sorted(self.counters.items())
        }


class JobTelemetryStore:
    """Bounded job_id -> JobCounters map (oldest evicted). The lock
    guards only creation/eviction; increments go straight at the
    accumulator."""

    def __init__(self, capacity: int = 256) -> None:
        self.capacity = max(int(capacity), 8)
        self._lock = threading.Lock()
        self._jobs: "collections.OrderedDict[str, JobCounters]" = (
            collections.OrderedDict()
        )

    def job(self, job_id: str) -> JobCounters:
        jc = self._jobs.get(job_id)
        if jc is not None:
            return jc
        with self._lock:
            jc = self._jobs.get(job_id)
            if jc is None:
                jc = JobCounters(job_id)
                self._jobs[job_id] = jc
                while len(self._jobs) > self.capacity:
                    self._jobs.popitem(last=False)
            return jc

    def peek(self, job_id: str) -> Optional[JobCounters]:
        return self._jobs.get(job_id)

    def drop(self, job_id: str) -> None:
        with self._lock:
            self._jobs.pop(job_id, None)

    def __iter__(self) -> Iterator[JobCounters]:
        return iter(list(self._jobs.values()))
