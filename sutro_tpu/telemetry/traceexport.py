"""Render traces (and whole-job flight records) as Chrome trace-event
JSON — the format Perfetto (https://ui.perfetto.dev) and
chrome://tracing load directly.

Two inputs, one output:

- a per-request trace document from :mod:`.traces` (``sutro trace
  <trace_id>``) — one process, one lane per stage family, so the
  admission→queue→prefill→decode→flush waterfall reads left to right;
- a whole-job telemetry document from :func:`telemetry.job_doc`
  (``sutro trace <job_id>``) — the flight recorder's spans for that
  job, same lane layout.

The rendering is pure and deterministic (sorted keys, stable lane
assignment, microsecond integers) so the export is golden-pinnable.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

# Lane (Chrome "tid") per span-name family, in waterfall order. Spans
# whose name has no family land in the overflow lane after these.
_LANES = (
    ("admit", ("admit_gateway", "admit")),
    ("queue", ("queue_wait",)),
    ("prefill", ("prefill", "prefix_hit", "prefix_extend")),
    (
        "decode",
        ("decode_window", "accept", "preempt_suspend", "resume"),
    ),
    ("stream", ("stream_flush", "first_token", "finish")),
)

_PID = 1


def _lane_of(name: str) -> int:
    for i, (_, members) in enumerate(_LANES):
        if name in members:
            return i
    return len(_LANES)


def _us(seconds: float) -> int:
    return int(round(seconds * 1e6))


def trace_to_chrome(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Per-request trace document -> Chrome trace-event JSON dict."""
    events: List[Dict[str, Any]] = [
        {
            "ph": "M",
            "pid": _PID,
            "tid": 0,
            "name": "process_name",
            "args": {
                "name": "{} {}".format(
                    doc.get("kind", "trace"), doc["trace_id"]
                )
            },
        }
    ]
    lanes_used = set()
    for span in doc.get("spans", ()):
        tid = _lane_of(span["name"])
        lanes_used.add(tid)
        ev: Dict[str, Any] = {
            "ph": "X",
            "pid": _PID,
            "tid": tid,
            "name": span["name"],
            "ts": _us(span["t0_s"]),
            # Perfetto renders dur=0 slices invisibly; give instants
            # one tick so suspend/hit markers stay clickable.
            "dur": max(_us(span["dur_s"]), 1),
        }
        if span.get("attrs"):
            ev["args"] = dict(span["attrs"])
        events.append(ev)
    for i, (lane_name, _) in enumerate(_LANES):
        if i in lanes_used:
            events.append(
                {
                    "ph": "M",
                    "pid": _PID,
                    "tid": i,
                    "name": "thread_name",
                    "args": {"name": lane_name},
                }
            )
    if len(_LANES) in lanes_used:
        events.append(
            {
                "ph": "M",
                "pid": _PID,
                "tid": len(_LANES),
                "name": "thread_name",
                "args": {"name": "other"},
            }
        )
    out: Dict[str, Any] = {
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": doc["trace_id"],
            "kind": doc.get("kind"),
            "outcome": doc.get("outcome"),
            "dropped": doc.get("dropped", 0),
        },
        "traceEvents": events,
    }
    if doc.get("attrs"):
        out["otherData"]["attrs"] = dict(doc["attrs"])
    return out


def job_doc_to_chrome(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Whole-job telemetry document (telemetry.job_doc) -> Chrome
    trace-event JSON: the flight-recorder spans become complete events
    in the same lane layout."""
    job_id = doc.get("job_id", "?")
    events: List[Dict[str, Any]] = [
        {
            "ph": "M",
            "pid": _PID,
            "tid": 0,
            "name": "process_name",
            "args": {"name": "job {}".format(job_id)},
        }
    ]
    lanes_used = set()
    for span in doc.get("spans", ()):
        name = span.get("name", "?")
        tid = _lane_of(name)
        lanes_used.add(tid)
        ev: Dict[str, Any] = {
            "ph": "X",
            "pid": _PID,
            "tid": tid,
            "name": name,
            "ts": _us(span.get("t0_s", 0.0)),
            "dur": max(_us(span.get("dur_s", 0.0)), 1),
        }
        if span.get("attrs"):
            ev["args"] = dict(span["attrs"])
        events.append(ev)
    for i, (lane_name, _) in enumerate(_LANES):
        if i in lanes_used:
            events.append(
                {
                    "ph": "M",
                    "pid": _PID,
                    "tid": i,
                    "name": "thread_name",
                    "args": {"name": lane_name},
                }
            )
    if len(_LANES) in lanes_used:
        events.append(
            {
                "ph": "M",
                "pid": _PID,
                "tid": len(_LANES),
                "name": "thread_name",
                "args": {"name": "other"},
            }
        )
    return {
        "displayTimeUnit": "ms",
        "otherData": {"job_id": job_id},
        "traceEvents": events,
    }


def render(chrome_doc: Dict[str, Any]) -> str:
    """Deterministic JSON text for files/goldens (sorted keys,
    2-space indent, trailing newline)."""
    return json.dumps(chrome_doc, indent=2, sort_keys=True) + "\n"


def largest_gap_s(doc: Dict[str, Any]) -> float:
    """Largest uncovered stretch between consecutive span starts in a
    per-request trace document — the acceptance criterion's
    "no gaps > one decode window" measure."""
    spans = doc.get("spans", ())
    if not spans:
        return 0.0
    covered_until = None
    worst = 0.0
    for span in spans:  # already sorted by t0_s
        t0 = span["t0_s"]
        t1 = t0 + span["dur_s"]
        if covered_until is None:
            covered_until = t1
            continue
        if t0 > covered_until:
            worst = max(worst, t0 - covered_until)
        covered_until = max(covered_until, t1)
    return worst
