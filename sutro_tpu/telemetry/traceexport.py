"""Render traces (and whole-job flight records) as Chrome trace-event
JSON — the format Perfetto (https://ui.perfetto.dev) and
chrome://tracing load directly.

Two inputs, one output:

- a per-request trace document from :mod:`.traces` (``sutro trace
  <trace_id>``) — one process, one lane per stage family, so the
  admission→queue→prefill→decode→flush waterfall reads left to right;
- a whole-job telemetry document from :func:`telemetry.job_doc`
  (``sutro trace <job_id>``) — the flight recorder's spans for that
  job, same lane layout.

The rendering is pure and deterministic (sorted keys, stable lane
assignment, microsecond integers) so the export is golden-pinnable.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

# Lane (Chrome "tid") per span-name family, in waterfall order. Spans
# whose name has no family land in the overflow lane after these.
_LANES = (
    ("admit", ("admit_gateway", "admit")),
    ("queue", ("queue_wait",)),
    ("prefill", ("prefill", "prefix_hit", "prefix_extend")),
    (
        "decode",
        ("decode_window", "accept", "preempt_suspend", "resume"),
    ),
    ("stream", ("stream_flush", "first_token", "finish")),
)

_PID = 1

# Router-process lanes for stitched fleet traces (fleet/router.py spans)
# — the front door's waterfall: pick -> probe -> connect -> first byte.
_ROUTER_LANES = (
    ("route", ("route_pick", "affinity_probe", "retry_failover")),
    ("upstream", ("upstream_connect", "first_byte")),
)


def _lane_of(name: str) -> int:
    for i, (_, members) in enumerate(_LANES):
        if name in members:
            return i
    return len(_LANES)


def _lane_in(name: str, lanes) -> int:
    for i, (_, members) in enumerate(lanes):
        if name in members:
            return i
    return len(lanes)


def _us(seconds: float) -> int:
    return int(round(seconds * 1e6))


def trace_to_chrome(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Per-request trace document -> Chrome trace-event JSON dict."""
    events: List[Dict[str, Any]] = [
        {
            "ph": "M",
            "pid": _PID,
            "tid": 0,
            "name": "process_name",
            "args": {
                "name": "{} {}".format(
                    doc.get("kind", "trace"), doc["trace_id"]
                )
            },
        }
    ]
    lanes_used = set()
    for span in doc.get("spans", ()):
        tid = _lane_of(span["name"])
        lanes_used.add(tid)
        ev: Dict[str, Any] = {
            "ph": "X",
            "pid": _PID,
            "tid": tid,
            "name": span["name"],
            "ts": _us(span["t0_s"]),
            # Perfetto renders dur=0 slices invisibly; give instants
            # one tick so suspend/hit markers stay clickable.
            "dur": max(_us(span["dur_s"]), 1),
        }
        if span.get("attrs"):
            ev["args"] = dict(span["attrs"])
        events.append(ev)
    for i, (lane_name, _) in enumerate(_LANES):
        if i in lanes_used:
            events.append(
                {
                    "ph": "M",
                    "pid": _PID,
                    "tid": i,
                    "name": "thread_name",
                    "args": {"name": lane_name},
                }
            )
    if len(_LANES) in lanes_used:
        events.append(
            {
                "ph": "M",
                "pid": _PID,
                "tid": len(_LANES),
                "name": "thread_name",
                "args": {"name": "other"},
            }
        )
    out: Dict[str, Any] = {
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": doc["trace_id"],
            "kind": doc.get("kind"),
            "outcome": doc.get("outcome"),
            "dropped": doc.get("dropped", 0),
        },
        "traceEvents": events,
    }
    if doc.get("attrs"):
        out["otherData"]["attrs"] = dict(doc["attrs"])
    return out


def job_doc_to_chrome(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Whole-job telemetry document (telemetry.job_doc) -> Chrome
    trace-event JSON: the flight-recorder spans become complete events
    in the same lane layout."""
    job_id = doc.get("job_id", "?")
    events: List[Dict[str, Any]] = [
        {
            "ph": "M",
            "pid": _PID,
            "tid": 0,
            "name": "process_name",
            "args": {"name": "job {}".format(job_id)},
        }
    ]
    lanes_used = set()
    for span in doc.get("spans", ()):
        name = span.get("name", "?")
        tid = _lane_of(name)
        lanes_used.add(tid)
        ev: Dict[str, Any] = {
            "ph": "X",
            "pid": _PID,
            "tid": tid,
            "name": name,
            "ts": _us(span.get("t0_s", 0.0)),
            "dur": max(_us(span.get("dur_s", 0.0)), 1),
        }
        if span.get("attrs"):
            ev["args"] = dict(span["attrs"])
        events.append(ev)
    for i, (lane_name, _) in enumerate(_LANES):
        if i in lanes_used:
            events.append(
                {
                    "ph": "M",
                    "pid": _PID,
                    "tid": i,
                    "name": "thread_name",
                    "args": {"name": lane_name},
                }
            )
    if len(_LANES) in lanes_used:
        events.append(
            {
                "ph": "M",
                "pid": _PID,
                "tid": len(_LANES),
                "name": "thread_name",
                "args": {"name": "other"},
            }
        )
    return {
        "displayTimeUnit": "ms",
        "otherData": {"job_id": job_id},
        "traceEvents": events,
    }


def stitched_to_chrome(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Stitched fleet trace document (fleet/obs.py ``stitch_trace``)
    -> Chrome trace-event JSON with one *process* lane group per
    participating process: the router is pid 1 with its own lane
    family (route/upstream), each replica gets the standard engine
    waterfall lanes under pid 2+. Every span's start offset is on the
    ROUTER's clock — the stitcher already re-anchored replica spans by
    wall-clock skew (round-10 ``ingest_remote`` convention), so the
    handoff reads left to right across process lanes in Perfetto."""
    events: List[Dict[str, Any]] = []
    for pidx, proc in enumerate(doc.get("processes", ())):
        pid = pidx + 1
        pdoc = proc.get("doc") or {}
        t_off = float(proc.get("t_off_s") or 0.0)
        lanes = _ROUTER_LANES if proc.get("role") == "router" else _LANES
        events.append(
            {
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "name": "process_name",
                "args": {"name": proc.get("process", f"p{pid}")},
            }
        )
        lanes_used = set()
        for span in pdoc.get("spans", ()):
            name = span.get("name", "?")
            tid = _lane_in(name, lanes)
            lanes_used.add(tid)
            ev: Dict[str, Any] = {
                "ph": "X",
                "pid": pid,
                "tid": tid,
                "name": name,
                "ts": _us(span.get("t0_s", 0.0) + t_off),
                "dur": max(_us(span.get("dur_s", 0.0)), 1),
            }
            if span.get("attrs"):
                ev["args"] = dict(span["attrs"])
            events.append(ev)
        for i, (lane_name, _) in enumerate(lanes):
            if i in lanes_used:
                events.append(
                    {
                        "ph": "M",
                        "pid": pid,
                        "tid": i,
                        "name": "thread_name",
                        "args": {"name": lane_name},
                    }
                )
        if len(lanes) in lanes_used:
            events.append(
                {
                    "ph": "M",
                    "pid": pid,
                    "tid": len(lanes),
                    "name": "thread_name",
                    "args": {"name": "other"},
                }
            )
    return {
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": doc.get("trace_id"),
            "kind": doc.get("kind", "fleet"),
            "processes": [
                p.get("process") for p in doc.get("processes", ())
            ],
        },
        "traceEvents": events,
    }


def stitched_spans(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Flatten a stitched fleet trace document into one merged span
    list on the router's clock, sorted by start — what the acceptance
    test walks to assert the cross-process handoff has no negative
    gaps after skew re-anchoring."""
    out: List[Dict[str, Any]] = []
    for proc in doc.get("processes", ()):
        pdoc = proc.get("doc") or {}
        t_off = float(proc.get("t_off_s") or 0.0)
        for span in pdoc.get("spans", ()):
            out.append(
                {
                    "name": span.get("name", "?"),
                    "t0_s": round(span.get("t0_s", 0.0) + t_off, 6),
                    "dur_s": span.get("dur_s", 0.0),
                    "process": proc.get("process"),
                }
            )
    out.sort(key=lambda s: (s["t0_s"], s["name"]))
    return out


def render(chrome_doc: Dict[str, Any]) -> str:
    """Deterministic JSON text for files/goldens (sorted keys,
    2-space indent, trailing newline)."""
    return json.dumps(chrome_doc, indent=2, sort_keys=True) + "\n"


def largest_gap_s(doc: Dict[str, Any]) -> float:
    """Largest uncovered stretch between consecutive span starts in a
    per-request trace document — the acceptance criterion's
    "no gaps > one decode window" measure."""
    spans = doc.get("spans", ())
    if not spans:
        return 0.0
    covered_until = None
    worst = 0.0
    for span in spans:  # already sorted by t0_s
        t0 = span["t0_s"]
        t1 = t0 + span["dur_s"]
        if covered_until is None:
            covered_until = t1
            continue
        if t0 > covered_until:
            worst = max(worst, t0 - covered_until)
        covered_until = max(covered_until, t1)
    return worst
