"""Lock-light metrics registry: counters, gauges, bounded histograms.

The reference SDK has no metrics at all — progress/usage accounting
lives behind the hosted service (SURVEY §0). The TPU-native engine
replaces that fleet, so it also needs the fleet's eyes: cheap,
always-on process metrics an operator can scrape.

Design constraints (the hot paths this serves are the scheduler's
decode loop and the jobstore's flush path):

- **Writes never contend.** Every writer thread accumulates into its
  own thread-local shard (a plain dict keyed by ``(metric, labels)``);
  an increment is a dict get/set — no lock, no atomics beyond the GIL.
  Readers aggregate across shards at collect time; shards of dead
  threads fold into a retired base so a daemon that spawns per-job
  threads stays bounded.
- **Fixed label cardinality.** Metrics declare their label names up
  front, and each metric admits at most ``max_series`` distinct label
  value tuples; overflow collapses into a single ``"_overflow"``
  series instead of growing without bound. Job ids and other unbounded
  identifiers therefore never become labels — per-job numbers live in
  the flight recorder's per-job counters (telemetry/__init__.py).
- **Bounded histogram buckets.** Fixed boundaries chosen at
  declaration; observation is a bisect + two adds.

Exporters: Prometheus text exposition (0.0.4) via
:meth:`MetricsRegistry.to_prometheus` and a JSON snapshot via
:meth:`MetricsRegistry.to_json`. Both produce deterministic ordering
(sorted by metric name, then label values) so goldens are stable.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

# default latency buckets (seconds): 100us .. ~100s, log-ish spacing —
# covers tokenize batches, decode windows, flushes and finalizes alike
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 100.0,
)

_OVERFLOW = ("_overflow",)


class _Shard:
    """One thread's private accumulators. Only its owner thread writes;
    readers only ever sum snapshots, so a torn read costs at most a
    momentarily-stale value, never corruption."""

    __slots__ = ("counters", "hists")

    def __init__(self) -> None:
        # (metric_name, label_values) -> float
        self.counters: Dict[Tuple[str, Tuple[str, ...]], float] = {}
        # (metric_name, label_values) -> [bucket_counts..., sum, count]
        self.hists: Dict[Tuple[str, Tuple[str, ...]], List[float]] = {}


class _Metric:
    """Common metric definition: name, kind, help, unit, label names."""

    def __init__(
        self,
        registry: "MetricsRegistry",
        name: str,
        kind: str,
        help_: str,
        labels: Sequence[str],
        unit: str,
        max_series: int,
    ) -> None:
        self.registry = registry
        self.name = name
        self.kind = kind
        self.help = help_
        self.label_names = tuple(labels)
        self.unit = unit
        self.max_series = max_series
        # label tuples this metric has admitted (reads are GIL-safe;
        # admission of a NEW tuple takes the registry lock)
        self._series: set = set()

    def _labelvals(self, labels: Tuple[str, ...]) -> Tuple[str, ...]:
        """Admit a label tuple under the cardinality cap (overflow
        collapses). Hot calls hit the membership test only."""
        if labels in self._series:
            return labels
        overflow = _OVERFLOW * len(self.label_names)
        with self.registry._lock:
            if labels in self._series:
                return labels
            if len(self._series) >= self.max_series:
                self._series.add(overflow)
                return overflow
            self._series.add(labels)
        return labels

    def _check(self, labels: Tuple[str, ...]) -> None:
        if len(labels) != len(self.label_names):
            raise ValueError(
                f"{self.name} takes labels {self.label_names}, "
                f"got {labels!r}"
            )


class Counter(_Metric):
    def inc(self, n: float = 1.0, *labels: str) -> None:
        lv = tuple(str(x) for x in labels)
        self._check(lv)
        lv = self._labelvals(lv)
        c = self.registry._shard().counters
        key = (self.name, lv)
        c[key] = c.get(key, 0.0) + n


class Gauge(_Metric):
    """Last-write-wins value. Stored registry-global (not sharded):
    a gauge is a statement about *now*, so per-thread accumulation
    would be meaningless. A plain dict assignment is GIL-atomic."""

    def set(self, value: float, *labels: str) -> None:
        lv = tuple(str(x) for x in labels)
        self._check(lv)
        lv = self._labelvals(lv)
        self.registry._gauges[(self.name, lv)] = float(value)


class Histogram(_Metric):
    def __init__(self, *args: Any, buckets: Sequence[float] = DEFAULT_BUCKETS,
                 **kw: Any) -> None:
        super().__init__(*args, **kw)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def observe(self, value: float, *labels: str) -> None:
        lv = tuple(str(x) for x in labels)
        self._check(lv)
        lv = self._labelvals(lv)
        h = self.registry._shard().hists
        key = (self.name, lv)
        acc = h.get(key)
        if acc is None:
            acc = h[key] = [0.0] * (len(self.buckets) + 3)
        # layout: [b0..bn, +Inf, sum, count]
        acc[bisect.bisect_left(self.buckets, value)] += 1.0
        acc[-2] += value
        acc[-1] += 1.0


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._gauges: Dict[Tuple[str, Tuple[str, ...]], float] = {}
        self._local = threading.local()
        # (thread, shard) pairs; dead threads' shards fold into _retired
        self._shards: List[Tuple[threading.Thread, _Shard]] = []
        self._retired = _Shard()

    # -- declaration ---------------------------------------------------

    def _declare(self, cls, name: str, help_: str, labels: Sequence[str],
                 unit: str, max_series: int, **kw: Any):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name} already declared as "
                        f"{existing.kind}"
                    )
                return existing
            m = cls(self, name, cls.__name__.lower(), help_, labels,
                    unit, max_series, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_: str = "",
                labels: Sequence[str] = (), unit: str = "",
                max_series: int = 64) -> Counter:
        return self._declare(Counter, name, help_, labels, unit,
                             max_series)

    def gauge(self, name: str, help_: str = "",
              labels: Sequence[str] = (), unit: str = "",
              max_series: int = 64) -> Gauge:
        return self._declare(Gauge, name, help_, labels, unit,
                             max_series)

    def histogram(self, name: str, help_: str = "",
                  labels: Sequence[str] = (), unit: str = "",
                  max_series: int = 64,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._declare(Histogram, name, help_, labels, unit,
                             max_series, buckets=buckets)

    # -- shards --------------------------------------------------------

    def _shard(self) -> _Shard:
        s = getattr(self._local, "shard", None)
        if s is None:
            s = _Shard()
            self._local.shard = s
            with self._lock:
                self._shards.append((threading.current_thread(), s))
        return s

    def _merge_shard(self, into: _Shard, s: _Shard) -> None:
        for k, v in list(s.counters.items()):
            into.counters[k] = into.counters.get(k, 0.0) + v
        for k, acc in list(s.hists.items()):
            base = into.hists.get(k)
            if base is None:
                into.hists[k] = list(acc)
            else:
                for i, v in enumerate(list(acc)):
                    if i < len(base):
                        base[i] += v

    def _aggregate(self) -> _Shard:
        """Sum every live shard over the retired base. Dead threads'
        shards fold into the retired base and drop from the live list
        (keeps a long-lived daemon's shard list bounded by its LIVE
        thread count)."""
        with self._lock:
            live = []
            for t, s in self._shards:
                if t.is_alive():
                    live.append((t, s))
                else:
                    self._merge_shard(self._retired, s)
            self._shards = live
            out = _Shard()
            self._merge_shard(out, self._retired)
            shards = [s for _, s in live]
        for s in shards:
            self._merge_shard(out, s)
        return out

    # -- collection / export -------------------------------------------

    def collect(self) -> Dict[str, Dict[str, Any]]:
        """Aggregated snapshot:
        ``{name: {type, help, unit, labels, series: {"a,b": value}}}``
        — histogram series values are
        ``{buckets: {le: n}, sum, count}``."""
        agg = self._aggregate()
        with self._lock:
            metrics = dict(self._metrics)
        gauges = dict(self._gauges)
        out: Dict[str, Dict[str, Any]] = {}
        for name in sorted(metrics):
            m = metrics[name]
            entry: Dict[str, Any] = {
                "type": m.kind,
                "help": m.help,
                "unit": m.unit,
                "labels": list(m.label_names),
                "series": {},
            }
            if isinstance(m, Gauge):
                src: Dict[Tuple[str, ...], Any] = {
                    lv: v for (n, lv), v in gauges.items() if n == name
                }
            elif isinstance(m, Histogram):
                src = {}
                for (n, lv), acc in agg.hists.items():
                    if n != name:
                        continue
                    les = [*m.buckets, math.inf]
                    src[lv] = {
                        "buckets": {
                            ("+Inf" if math.isinf(le) else repr(le)): int(
                                sum(acc[: i + 1])
                            )
                            for i, le in enumerate(les)
                        },
                        "sum": acc[-2],
                        "count": int(acc[-1]),
                    }
            else:
                src = {
                    lv: v
                    for (n, lv), v in agg.counters.items()
                    if n == name
                }
            for lv in sorted(src):
                entry["series"][",".join(lv)] = src[lv]
            out[name] = entry
        return out

    @staticmethod
    def _fmt_labels(names: Sequence[str], values: Sequence[str],
                    extra: Optional[Tuple[str, str]] = None) -> str:
        def esc(v: str) -> str:
            return (
                v.replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
            )

        pairs = list(zip(names, values))
        if extra is not None:
            pairs.append(extra)
        if not pairs:
            return ""
        body = ",".join(f'{k}="{esc(v)}"' for k, v in pairs)
        return "{" + body + "}"

    @staticmethod
    def _fmt_value(v: float) -> str:
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(float(v))

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        snap = self.collect()
        lines: List[str] = []
        for name, m in snap.items():
            lines.append(f"# HELP {name} {m['help']}")
            lines.append(f"# TYPE {name} {m['type']}")
            names = m["labels"]
            for key, val in m["series"].items():
                values = tuple(key.split(",")) if names else ()
                if m["type"] == "histogram":
                    for le, n in val["buckets"].items():
                        le_s = le if le == "+Inf" else self._fmt_value(
                            float(le)
                        )
                        lines.append(
                            f"{name}_bucket"
                            + self._fmt_labels(names, values,
                                               ("le", le_s))
                            + f" {n}"
                        )
                    lines.append(
                        f"{name}_sum"
                        + self._fmt_labels(names, values)
                        + f" {self._fmt_value(val['sum'])}"
                    )
                    lines.append(
                        f"{name}_count"
                        + self._fmt_labels(names, values)
                        + f" {val['count']}"
                    )
                else:
                    lines.append(
                        name
                        + self._fmt_labels(names, values)
                        + f" {self._fmt_value(val)}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self) -> Dict[str, Any]:
        return self.collect()

    def reset(self) -> None:
        """Test hook: drop every accumulated value (declarations stay).
        Not for production use — concurrent writers may keep shards the
        reset has already cleared."""
        with self._lock:
            self._retired = _Shard()
            self._shards = []
            self._gauges.clear()
            self._local = threading.local()
            for m in self._metrics.values():
                m._series = set()
