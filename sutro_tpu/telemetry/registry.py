"""Lock-light metrics registry: counters, gauges, bounded histograms.

The reference SDK has no metrics at all — progress/usage accounting
lives behind the hosted service (SURVEY §0). The TPU-native engine
replaces that fleet, so it also needs the fleet's eyes: cheap,
always-on process metrics an operator can scrape.

Design constraints (the hot paths this serves are the scheduler's
decode loop and the jobstore's flush path):

- **Writes never contend.** Every writer thread accumulates into its
  own thread-local shard (a plain dict keyed by ``(metric, labels)``);
  an increment is a dict get/set — no lock, no atomics beyond the GIL.
  Readers aggregate across shards at collect time; shards of dead
  threads fold into a retired base so a daemon that spawns per-job
  threads stays bounded.
- **Fixed label cardinality.** Metrics declare their label names up
  front, and each metric admits at most ``max_series`` distinct label
  value tuples; overflow collapses into a single ``"_overflow"``
  series instead of growing without bound. Job ids and other unbounded
  identifiers therefore never become labels — per-job numbers live in
  the flight recorder's per-job counters (telemetry/__init__.py).
- **Bounded histogram buckets.** Fixed boundaries chosen at
  declaration; observation is a bisect + two adds.

Exporters: Prometheus text exposition (0.0.4) via
:meth:`MetricsRegistry.to_prometheus` and a JSON snapshot via
:meth:`MetricsRegistry.to_json`. Both produce deterministic ordering
(sorted by metric name, then label values) so goldens are stable.

Federation (telemetry/distributed.py): a dp worker exports a compact
local snapshot (:meth:`MetricsRegistry.export_snapshot`), ships the
per-round difference (:func:`snapshot_delta`) over the dp channel, and
the coordinator folds it in with :meth:`MetricsRegistry.ingest_remote`.
Ingested series keep their metric identity but gain a trailing
``worker`` label (the coordinator's own series export as worker "0");
metrics with no remote contribution export exactly as before, so
single-process goldens are unaffected. Worker-label cardinality is
bounded like any label (overflow collapses into ``_overflow``).
"""

from __future__ import annotations

import bisect
import logging
import math
import threading
import time as _time
from typing import Any, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

# default latency buckets (seconds): 100us .. ~100s, log-ish spacing —
# covers tokenize batches, decode windows, flushes and finalizes alike
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 100.0,
)

_OVERFLOW = ("_overflow",)


class _Shard:
    """One thread's private accumulators. Only its owner thread writes;
    readers only ever sum snapshots, so a torn read costs at most a
    momentarily-stale value, never corruption."""

    __slots__ = ("counters", "hists")

    def __init__(self) -> None:
        # (metric_name, label_values) -> float
        self.counters: Dict[Tuple[str, Tuple[str, ...]], float] = {}
        # (metric_name, label_values) -> [bucket_counts..., sum, count]
        self.hists: Dict[Tuple[str, Tuple[str, ...]], List[float]] = {}


class _Metric:
    """Common metric definition: name, kind, help, unit, label names."""

    def __init__(
        self,
        registry: "MetricsRegistry",
        name: str,
        kind: str,
        help_: str,
        labels: Sequence[str],
        unit: str,
        max_series: int,
    ) -> None:
        self.registry = registry
        self.name = name
        self.kind = kind
        self.help = help_
        self.label_names = tuple(labels)
        self.unit = unit
        self.max_series = max_series
        # label tuples this metric has admitted (reads are GIL-safe;
        # admission of a NEW tuple takes the registry lock)
        self._series: set = set()

    def _labelvals(self, labels: Tuple[str, ...]) -> Tuple[str, ...]:
        """Admit a label tuple under the cardinality cap (overflow
        collapses). Hot calls hit the membership test only."""
        if labels in self._series:
            return labels
        overflow = _OVERFLOW * len(self.label_names)
        with self.registry._lock:
            if labels in self._series:
                return labels
            if len(self._series) >= self.max_series:
                self._series.add(overflow)
                return overflow
            self._series.add(labels)
        return labels

    def _check(self, labels: Tuple[str, ...]) -> None:
        if len(labels) != len(self.label_names):
            raise ValueError(
                f"{self.name} takes labels {self.label_names}, "
                f"got {labels!r}"
            )


class Counter(_Metric):
    def inc(self, n: float = 1.0, *labels: str) -> None:
        lv = tuple(str(x) for x in labels)
        self._check(lv)
        lv = self._labelvals(lv)
        c = self.registry._shard().counters
        key = (self.name, lv)
        c[key] = c.get(key, 0.0) + n


class Gauge(_Metric):
    """Last-write-wins value. Stored registry-global (not sharded):
    a gauge is a statement about *now*, so per-thread accumulation
    would be meaningless. A plain dict assignment is GIL-atomic."""

    def set(self, value: float, *labels: str) -> None:
        lv = tuple(str(x) for x in labels)
        self._check(lv)
        lv = self._labelvals(lv)
        self.registry._gauges[(self.name, lv)] = float(value)


class Histogram(_Metric):
    #: recency bias: a stored exemplar older than this loses its slot
    #: to ANY new observation in the bucket, even a smaller one
    EXEMPLAR_MAX_AGE_S = 60.0

    def __init__(self, *args: Any, buckets: Sequence[float] = DEFAULT_BUCKETS,
                 **kw: Any) -> None:
        super().__init__(*args, **kw)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # exemplars: (label_values, bucket_idx) ->
        #   (value, trace_id, attrs_or_None, unix_ts). One slot per
        # bucket per series — bounded by construction (buckets x
        # max_series). Stored registry-global, not in thread shards:
        # an exemplar must survive shard folding and read identically
        # from every concurrent scrape. Single dict assignment per
        # capture (GIL-atomic), no lock.
        self._exemplars: Dict[Tuple[Tuple[str, ...], int], tuple] = {}

    def observe(
        self,
        value: float,
        *labels: str,
        exemplar: Optional[str] = None,
        exemplar_attrs: Optional[Dict[str, str]] = None,
        _now: Optional[float] = None,
    ) -> None:
        lv = tuple(str(x) for x in labels)
        self._check(lv)
        lv = self._labelvals(lv)
        h = self.registry._shard().hists
        key = (self.name, lv)
        acc = h.get(key)
        if acc is None:
            acc = h[key] = [0.0] * (len(self.buckets) + 3)
        # layout: [b0..bn, +Inf, sum, count]
        idx = bisect.bisect_left(self.buckets, value)
        acc[idx] += 1.0
        acc[-2] += value
        acc[-1] += 1.0
        if exemplar is not None:
            self._capture_exemplar(
                lv, idx, value, exemplar, exemplar_attrs, _now
            )

    def _capture_exemplar(
        self,
        lv: Tuple[str, ...],
        idx: int,
        value: float,
        trace_id: str,
        attrs: Optional[Dict[str, str]],
        now: Optional[float],
    ) -> None:
        """Latency/recency-biased keep policy: within a bucket the
        slot goes to the LARGEST value seen recently — a stale holder
        (older than EXEMPLAR_MAX_AGE_S) yields to any newcomer, so a
        one-off spike from an hour ago cannot pin the slot forever."""
        ts = _time.time() if now is None else now
        cur = self._exemplars.get((lv, idx))
        if cur is not None:
            if value < cur[0] and (ts - cur[3]) < self.EXEMPLAR_MAX_AGE_S:
                return
        self._exemplars[(lv, idx)] = (
            float(value), str(trace_id),
            dict(attrs) if attrs else None, ts,
        )

    def exemplars_view(self) -> Dict[Tuple[str, ...], Dict[str, Dict]]:
        """Snapshot ``{label_values: {le_str: exemplar_dict}}`` where
        ``exemplar_dict`` is ``{value, trace_id, ts[, attrs]}`` —
        the JSON-snapshot shape, also what the exporter renders."""
        les = [*self.buckets, math.inf]
        out: Dict[Tuple[str, ...], Dict[str, Dict]] = {}
        for (lv, idx), (value, trace_id, attrs, ts) in sorted(
            self._exemplars.items()
        ):
            le = les[idx]
            le_s = "+Inf" if math.isinf(le) else repr(le)
            d: Dict[str, Any] = {
                "value": value, "trace_id": trace_id, "ts": ts,
            }
            if attrs:
                d["attrs"] = dict(attrs)
            out.setdefault(lv, {})[le_s] = d
        return out


class MetricsRegistry:
    #: worker-label cardinality cap for federation (ingest_remote):
    #: shards from more distinct workers collapse into "_overflow"
    MAX_WORKERS = 64

    def __init__(self, federation_label: str = "worker") -> None:
        # the trailing label federated series gain: "worker" for the dp
        # coordinator (the historical default, pinned by dp goldens),
        # "replica" for the fleet router's federated registry
        self.federation_label = federation_label
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._gauges: Dict[Tuple[str, Tuple[str, ...]], float] = {}
        self._local = threading.local()
        # (thread, shard) pairs; dead threads' shards fold into _retired
        self._shards: List[Tuple[threading.Thread, _Shard]] = []
        self._retired = _Shard()
        # federation: worker id -> accumulated remote series
        # (telemetry/distributed.py coordinator ingestion). Counters and
        # histograms ACCUMULATE across ingests (workers ship per-round
        # deltas); gauges are last-write-wins per worker.
        self._remote: Dict[str, Dict[str, Dict]] = {}

    # -- declaration ---------------------------------------------------

    def _declare(self, cls, name: str, help_: str, labels: Sequence[str],
                 unit: str, max_series: int, **kw: Any):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name} already declared as "
                        f"{existing.kind}"
                    )
                return existing
            m = cls(self, name, cls.__name__.lower(), help_, labels,
                    unit, max_series, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help_: str = "",
                labels: Sequence[str] = (), unit: str = "",
                max_series: int = 64) -> Counter:
        return self._declare(Counter, name, help_, labels, unit,
                             max_series)

    def gauge(self, name: str, help_: str = "",
              labels: Sequence[str] = (), unit: str = "",
              max_series: int = 64) -> Gauge:
        return self._declare(Gauge, name, help_, labels, unit,
                             max_series)

    def histogram(self, name: str, help_: str = "",
                  labels: Sequence[str] = (), unit: str = "",
                  max_series: int = 64,
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._declare(Histogram, name, help_, labels, unit,
                             max_series, buckets=buckets)

    # -- shards --------------------------------------------------------

    def _shard(self) -> _Shard:
        s = getattr(self._local, "shard", None)
        if s is None:
            s = _Shard()
            self._local.shard = s
            with self._lock:
                self._shards.append((threading.current_thread(), s))
        return s

    def _merge_shard(self, into: _Shard, s: _Shard) -> None:
        for k, v in list(s.counters.items()):
            into.counters[k] = into.counters.get(k, 0.0) + v
        for k, acc in list(s.hists.items()):
            base = into.hists.get(k)
            if base is None:
                into.hists[k] = list(acc)
            else:
                for i, v in enumerate(list(acc)):
                    if i < len(base):
                        base[i] += v

    def _aggregate(self) -> _Shard:
        """Sum every live shard over the retired base. Dead threads'
        shards fold into the retired base and drop from the live list
        (keeps a long-lived daemon's shard list bounded by its LIVE
        thread count)."""
        with self._lock:
            live = []
            for t, s in self._shards:
                if t.is_alive():
                    live.append((t, s))
                else:
                    self._merge_shard(self._retired, s)
            self._shards = live
            out = _Shard()
            self._merge_shard(out, self._retired)
            shards = [s for _, s in live]
        for s in shards:
            self._merge_shard(out, s)
        return out

    # -- collection / export -------------------------------------------

    @staticmethod
    def _hist_view(m: "Histogram", acc: Sequence[float]) -> Dict[str, Any]:
        les = [*m.buckets, math.inf]
        return {
            "buckets": {
                ("+Inf" if math.isinf(le) else repr(le)): int(
                    sum(acc[: i + 1])
                )
                for i, le in enumerate(les)
            },
            "sum": acc[-2],
            "count": int(acc[-1]),
        }

    def collect(self) -> Dict[str, Dict[str, Any]]:
        """Aggregated snapshot:
        ``{name: {type, help, unit, labels, series: {"a,b": value}}}``
        — histogram series values are
        ``{buckets: {le: n}, sum, count}``.

        When remote worker shards have been ingested (federation), any
        metric with a remote contribution gains a trailing ``worker``
        label: its local series carry worker "0", remote series carry
        their worker id. Metrics without remote data are unchanged."""
        agg = self._aggregate()
        with self._lock:
            metrics = dict(self._metrics)
            remote = {
                w: {
                    kind: dict(series)
                    for kind, series in shard.items()
                }
                for w, shard in self._remote.items()
            }
        gauges = dict(self._gauges)
        remote_names = {
            n
            for shard in remote.values()
            for series in shard.values()
            for (n, _lv) in series
        }
        out: Dict[str, Dict[str, Any]] = {}
        for name in sorted(metrics):
            m = metrics[name]
            federated = name in remote_names
            entry: Dict[str, Any] = {
                "type": m.kind,
                "help": m.help,
                "unit": m.unit,
                "labels": list(m.label_names)
                + ([self.federation_label] if federated else []),
                "series": {},
            }
            if isinstance(m, Gauge):
                src: Dict[Tuple[str, ...], Any] = {
                    lv: v for (n, lv), v in gauges.items() if n == name
                }
            elif isinstance(m, Histogram):
                src = {
                    lv: self._hist_view(m, acc)
                    for (n, lv), acc in agg.hists.items()
                    if n == name
                }
            else:
                src = {
                    lv: v
                    for (n, lv), v in agg.counters.items()
                    if n == name
                }
            if federated:
                src = {lv + ("0",): v for lv, v in src.items()}
                kind = (
                    "gauges" if isinstance(m, Gauge)
                    else "hists" if isinstance(m, Histogram)
                    else "counters"
                )
                for w in sorted(remote):
                    for (n, lv), v in remote[w].get(kind, {}).items():
                        if n != name:
                            continue
                        if isinstance(m, Histogram):
                            v = self._hist_view(m, v)
                        src[lv + (w,)] = v
            for lv in sorted(src):
                entry["series"][",".join(lv)] = src[lv]
            if isinstance(m, Histogram):
                ex = m.exemplars_view()
                if ex:
                    # key shape matches "series" keys for the local
                    # (non-federated) case; federation ships no
                    # exemplars — the exporter maps worker-"0" series
                    # back to these local keys
                    entry["exemplars"] = {
                        ",".join(lv): d for lv, d in sorted(ex.items())
                    }
            out[name] = entry
        return out

    def exemplars(self, name: str) -> List[Dict[str, Any]]:
        """Flat exemplar list for one histogram, largest value first —
        what the monitor embeds into an alert event (top trace ids)."""
        m = self._metrics.get(name)
        if not isinstance(m, Histogram):
            return []
        out: List[Dict[str, Any]] = []
        for lv, by_le in m.exemplars_view().items():
            for le_s, d in by_le.items():
                row = dict(d)
                row["le"] = le_s
                row["labels"] = list(lv)
                out.append(row)
        out.sort(key=lambda d: (-d["value"], d["trace_id"]))
        return out

    # -- federation (telemetry/distributed.py) -------------------------

    def export_snapshot(self) -> Dict[str, List]:
        """Compact JSON-able snapshot of this process's OWN series
        (remote ingested data excluded on purpose: a worker's export
        must never echo back shards it was federated). Shape:
        ``{"counters": [[name, [labels...], value], ...],
           "hists":    [[name, [labels...], [acc...]], ...],
           "gauges":   [[name, [labels...], value], ...]}``."""
        agg = self._aggregate()
        gauges = dict(self._gauges)
        return {
            "counters": [
                [n, list(lv), v]
                for (n, lv), v in sorted(agg.counters.items())
            ],
            "hists": [
                [n, list(lv), list(acc)]
                for (n, lv), acc in sorted(agg.hists.items())
            ],
            "gauges": [
                [n, list(lv), v] for (n, lv), v in sorted(gauges.items())
            ],
        }

    def ingest_remote(self, worker: str, shard: Dict[str, Any]) -> None:
        """Fold one remote shard (a worker's :func:`snapshot_delta`)
        into the federation store under ``worker``. Unknown metric
        names and malformed entries are skipped (wire-version drift must
        degrade, not raise); histogram entries whose accumulator length
        does not match this process's bucket schema are skipped too.
        Counter/histogram values ACCUMULATE across ingests; gauges are
        last-write-wins."""
        if not isinstance(shard, dict):
            return
        with self._lock:
            w = str(worker)
            if w not in self._remote and len(self._remote) >= self.MAX_WORKERS:
                w = "_overflow"
            rs = self._remote.setdefault(
                w, {"counters": {}, "hists": {}, "gauges": {}}
            )
            for kind in ("counters", "hists", "gauges"):
                for item in shard.get(kind) or ():
                    try:
                        name, lv, v = item
                        m = self._metrics.get(str(name))
                        if m is None:
                            continue
                        lv = tuple(str(x) for x in lv)
                        if len(lv) != len(m.label_names):
                            continue
                        key = (m.name, lv)
                        dst = rs[kind]
                        if kind == "hists":
                            if not isinstance(m, Histogram) or len(v) != (
                                len(m.buckets) + 3
                            ):
                                continue
                            base = dst.get(key)
                            if base is None:
                                dst[key] = [float(x) for x in v]
                            else:
                                for i, x in enumerate(v):
                                    base[i] += float(x)
                        elif kind == "counters":
                            if not isinstance(m, Counter):
                                continue
                            dst[key] = dst.get(key, 0.0) + float(v)
                        else:
                            if not isinstance(m, Gauge):
                                continue
                            dst[key] = float(v)
                    except (TypeError, ValueError) as e:
                        logger.debug(
                            "skipping malformed remote series %r: %s",
                            item, e,
                        )

    @staticmethod
    def _fmt_labels(names: Sequence[str], values: Sequence[str],
                    extra: Optional[Tuple[str, str]] = None) -> str:
        def esc(v: str) -> str:
            return (
                v.replace("\\", "\\\\")
                .replace('"', '\\"')
                .replace("\n", "\\n")
            )

        pairs = list(zip(names, values))
        if extra is not None:
            pairs.append(extra)
        if not pairs:
            return ""
        body = ",".join(f'{k}="{esc(v)}"' for k, v in pairs)
        return "{" + body + "}"

    @staticmethod
    def _fmt_value(v: float) -> str:
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(float(v))

    @classmethod
    def _fmt_exemplar(cls, ex: Dict[str, Any]) -> str:
        """OpenMetrics exemplar suffix for a ``_bucket`` line:
        `` # {trace_id="...",k="v"} value timestamp``."""
        names = ["trace_id"]
        values = [str(ex["trace_id"])]
        for k in sorted(ex.get("attrs") or ()):
            names.append(str(k))
            values.append(str(ex["attrs"][k]))
        return (
            " # "
            + cls._fmt_labels(names, values)
            + f" {cls._fmt_value(ex['value'])}"
            + f" {cls._fmt_value(float(ex['ts']))}"
        )

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        snap = self.collect()
        lines: List[str] = []
        for name, m in snap.items():
            lines.append(f"# HELP {name} {m['help']}")
            lines.append(f"# TYPE {name} {m['type']}")
            names = m["labels"]
            ex_map = m.get("exemplars") or {}
            for key, val in m["series"].items():
                values = tuple(key.split(",")) if names else ()
                if m["type"] == "histogram":
                    ex_series = ex_map.get(key)
                    if (
                        ex_series is None
                        and names
                        and names[-1] == self.federation_label
                        and values[-1:] == ("0",)
                    ):
                        # federated metric: exemplars live on the
                        # coordinator's own (worker "0") series
                        ex_series = ex_map.get(",".join(values[:-1]))
                    for le, n in val["buckets"].items():
                        le_s = le if le == "+Inf" else self._fmt_value(
                            float(le)
                        )
                        line = (
                            f"{name}_bucket"
                            + self._fmt_labels(names, values,
                                               ("le", le_s))
                            + f" {n}"
                        )
                        ex = (ex_series or {}).get(le)
                        if ex is not None:
                            line += self._fmt_exemplar(ex)
                        lines.append(line)
                    lines.append(
                        f"{name}_sum"
                        + self._fmt_labels(names, values)
                        + f" {self._fmt_value(val['sum'])}"
                    )
                    lines.append(
                        f"{name}_count"
                        + self._fmt_labels(names, values)
                        + f" {val['count']}"
                    )
                else:
                    lines.append(
                        name
                        + self._fmt_labels(names, values)
                        + f" {self._fmt_value(val)}"
                    )
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self) -> Dict[str, Any]:
        return self.collect()

    def reset(self) -> None:
        """Test hook: drop every accumulated value (declarations stay).
        Not for production use — concurrent writers may keep shards the
        reset has already cleared."""
        with self._lock:
            self._retired = _Shard()
            self._shards = []
            self._gauges.clear()
            self._remote.clear()
            self._local = threading.local()
            for m in self._metrics.values():
                m._series = set()
                if isinstance(m, Histogram):
                    m._exemplars = {}


def snapshot_delta(
    before: Dict[str, List], after: Dict[str, List]
) -> Dict[str, List]:
    """Difference of two :meth:`MetricsRegistry.export_snapshot` calls
    — what a dp worker ships per round. Counters/histograms subtract
    (series that did not move are dropped); gauges pass through as
    their CURRENT values (a gauge is a statement about now, a gauge
    delta is meaningless)."""

    def _index(snap, kind):
        return {
            (name, tuple(lv)): v
            for name, lv, v in (snap.get(kind) or ())
        }

    out: Dict[str, List] = {"counters": [], "hists": [], "gauges": []}
    base = _index(before, "counters")
    for (name, lv), v in sorted(_index(after, "counters").items()):
        d = v - base.get((name, lv), 0.0)
        if d > 0:
            out["counters"].append([name, list(lv), d])
    base = _index(before, "hists")
    for (name, lv), acc in sorted(_index(after, "hists").items()):
        b = base.get((name, lv))
        d = (
            list(acc)
            if b is None or len(b) != len(acc)
            else [x - y for x, y in zip(acc, b)]
        )
        if d and d[-1] > 0:  # count moved
            out["hists"].append([name, list(lv), d])
    out["gauges"] = [
        [name, list(lv), v]
        for (name, lv), v in sorted(_index(after, "gauges").items())
    ]
    return out
