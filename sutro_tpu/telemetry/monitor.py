"""Live SLO monitor: metrics history, alerts, streaming doctor verdicts.

The registry (:mod:`.registry`) answers "what are the totals NOW"; the
doctor (:mod:`.doctor`) answers "why was that job slow" AFTER it ends.
This module closes the gap in between: a low-overhead sampler thread
that periodically snapshots the registry via the existing federation
machinery (:meth:`MetricsRegistry.export_snapshot` /
:func:`snapshot_delta`) into a bounded ring of timestamped snapshots —
a real time series — and derives from it, every tick:

1. **Windowed rates and percentiles.** Counter deltas over the sliding
   window become rates (rows/s, tokens/s, quarantines/s, failure
   events/s); histogram deltas become windowed p50/p99 via bucket
   interpolation (interactive TTFT/ITL and every engine stage). The
   window sees only what moved INSIDE it, so a throughput collapse at
   row 5k of a 20k-row job shows up seconds later, not at finalize.
2. **Declarative SLO rules** per workload class (interactive TTFT/ITL,
   batch rows/s, quarantine rate, dp fleet size) evaluated with
   hysteresis (separate breach and clear levels) + debounce
   (consecutive-tick streaks) into structured alert events with a
   pending → firing → resolved lifecycle. An alert FIRING dumps the
   flight recorder next to every running job, exactly like a FAILED
   job does — the postmortem artifact exists while the incident is
   still live.
3. **Continuous doctor.** The bottleneck doctor re-runs over the
   flight recorder's sliding span window for every RUNNING job, so
   verdicts (``decode_below_roofline``, ``host_bound_admit``,
   ``interactive_starved``, ...) stream mid-job instead of post-mortem
   (each carries ``in_flight: true``).

Surfaces: ``GET /monitor`` (one consolidated document) and NDJSON
``GET /monitor/stream`` on the daemon (server.py), ``sdk.get_monitor``
and the ``sutro watch`` terminal dashboard (cli.py).

Overhead discipline: the monitor is constructed only when telemetry is
enabled AND ``SUTRO_MONITOR`` != 0, and its loop re-checks the package
``ENABLED`` switch every tick — with telemetry off the thread does one
attribute load + truth test per interval and NOTHING else (asserted by
the op-census leg in benchmarks/profile_host_overhead.py --monitor).
A tick never writes registry series except on alert state transitions,
so it cannot perturb the <2% telemetry budget it is measured under.
Fault site ``telemetry.monitor`` (engine/faults.py) covers the tick:
any injected raise degrades the monitor to disabled — a broken monitor
must never fail a job (tests/test_chaos.py).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .registry import snapshot_delta

logger = logging.getLogger(__name__)

MONITOR_VERSION = 1

#: default sampler cadence / sliding-window span / ring depth
DEFAULT_INTERVAL_S = 1.0
DEFAULT_WINDOW_S = 30.0
DEFAULT_HISTORY = 120

#: alert/event logs kept (oldest dropped first) — an incident trail,
#: not a metrics store, same bounding rationale as failure_log[]
EVENT_CAP = 128


def monitor_enabled() -> bool:
    """The monitor's own switch, subordinate to ``SUTRO_TELEMETRY``:
    the engine constructs a Monitor only when BOTH are on."""
    return os.environ.get("SUTRO_MONITOR", "1").lower() not in (
        "0", "false", "off",
    )


# ---------------------------------------------------------------------------
# SLO rules
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SLORule:
    """One declarative SLO clause (OBSERVABILITY.md "Live monitor").

    ``metric`` names a key of the per-tick window-stats document;
    a tick where the key is absent/None leaves the rule dormant (its
    streaks reset — a rule cannot fire on a workload that is not
    running). Breach is ``value <op> threshold``; hysteresis: once
    firing, the rule only starts resolving when the value clears the
    SEPARATE ``clear`` level (default: the threshold itself), so
    flapping at the threshold cannot produce fire/resolve churn;
    debounce: ``for_ticks`` consecutive breaching ticks arm
    pending → firing, ``clear_ticks`` consecutive cleared ticks
    resolve."""

    name: str
    metric: str
    op: str = ">"                       # ">" or "<"
    threshold: float = 0.0
    clear: Optional[float] = None       # hysteresis level (default: threshold)
    for_ticks: int = 2
    clear_ticks: int = 2
    workload: str = ""                  # interactive | batch | dp | engine
    severity: str = "warning"

    def breached(self, value: float) -> bool:
        return value > self.threshold if self.op == ">" else value < self.threshold

    def cleared(self, value: float) -> bool:
        lvl = self.threshold if self.clear is None else self.clear
        return value <= lvl if self.op == ">" else value >= lvl

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


#: the stock rule set — per workload class, as the ROADMAP's SLO
#: control plane names them. Thresholds mirror the engine's existing
#: constants where one exists (STARVED_TTFT_S for interactive TTFT).
DEFAULT_RULES: Tuple[SLORule, ...] = (
    SLORule(
        "interactive_ttft_p99", metric="ttft_p99_s", op=">",
        threshold=5.0, clear=2.5, workload="interactive",
        severity="critical",
    ),
    SLORule(
        "interactive_itl_p99", metric="itl_p99_s", op=">",
        threshold=1.0, clear=0.5, workload="interactive",
    ),
    SLORule(
        "batch_rows_stalled", metric="batch_rows_per_s", op="<",
        threshold=0.1, clear=0.5, for_ticks=3, clear_ticks=2,
        workload="batch",
    ),
    SLORule(
        "quarantine_rate", metric="quarantine_rate", op=">",
        threshold=0.05, clear=0.01, workload="batch",
    ),
    SLORule(
        "dp_fleet_shrunk", metric="dp_fleet_size", op="<",
        threshold=1.0, clear=1.0, workload="dp", severity="critical",
    ),
)


class _RuleState:
    """Per-rule evaluation state (sampler thread only)."""

    __slots__ = ("state", "breach_streak", "clear_streak", "fired_unix",
                 "value")

    def __init__(self) -> None:
        self.state = "ok"          # ok | pending | firing
        self.breach_streak = 0
        self.clear_streak = 0
        self.fired_unix: Optional[float] = None
        self.value: Optional[float] = None


# ---------------------------------------------------------------------------
# windowed percentile over histogram bucket deltas
# ---------------------------------------------------------------------------


def percentile_from_buckets(
    buckets: Sequence[float], acc: Sequence[float], q: float
) -> Optional[float]:
    """Linear-interpolated q-quantile from one histogram accumulator
    (layout ``[b0..bn, +Inf, sum, count]`` — registry.Histogram).
    None when the accumulator is empty. Values in the +Inf bucket clamp
    to the top finite boundary (the honest answer a bounded histogram
    can give; tests compare against brute force WITHIN bucket
    resolution)."""
    count = acc[-1]
    if count <= 0:
        return None
    target = q * count
    cum = 0.0
    lo = 0.0
    for i, le in enumerate(buckets):
        c = acc[i]
        if c > 0 and cum + c >= target:
            frac = (target - cum) / c
            return lo + (le - lo) * min(max(frac, 0.0), 1.0)
        cum += c
        lo = le
    return float(buckets[-1]) if buckets else None


# ---------------------------------------------------------------------------
# the monitor
# ---------------------------------------------------------------------------


class Monitor:
    """Background sampler + SLO evaluator + continuous doctor.

    Constructor wires, never imports: the engine passes callables so
    this module stays importable (and unit-testable) without an engine.

    - ``jobs_provider() -> [(job_id, status), ...]`` — the RUNNING jobs
      the continuous doctor diagnoses each tick;
    - ``alert_dump(job_id, alert) -> None`` — invoked once per firing
      alert per running job (the engine dumps the flight recorder next
      to the job, like FAILED already does). Best-effort: a dump error
      is logged and swallowed.
    """

    def __init__(
        self,
        *,
        interval_s: Optional[float] = None,
        window_s: Optional[float] = None,
        history: Optional[int] = None,
        rules: Optional[Sequence[SLORule]] = None,
        jobs_provider: Optional[Callable[[], List[Tuple[str, str]]]] = None,
        alert_dump: Optional[Callable[[str, Dict[str, Any]], None]] = None,
    ) -> None:
        env = os.environ
        self.interval_s = float(
            interval_s
            if interval_s is not None
            else env.get("SUTRO_MONITOR_INTERVAL", DEFAULT_INTERVAL_S)
        )
        self.window_s = float(
            window_s
            if window_s is not None
            else env.get("SUTRO_MONITOR_WINDOW", DEFAULT_WINDOW_S)
        )
        self.history = int(
            history
            if history is not None
            else env.get("SUTRO_MONITOR_HISTORY", DEFAULT_HISTORY)
        )
        self._rules = list(rules if rules is not None else DEFAULT_RULES)
        self._rule_state: Dict[str, _RuleState] = {
            r.name: _RuleState() for r in self._rules
        }
        self._jobs_provider = jobs_provider
        self._alert_dump = alert_dump
        # ring of (monotonic_ts, unix_ts, export_snapshot()) — the time
        # series every window computation subtracts across
        self._ring: deque = deque(maxlen=max(self.history, 2))
        self._events: deque = deque(maxlen=EVENT_CAP)
        # monotonic count of events ever appended to ``_events`` — the
        # deque drops old entries at EVENT_CAP, so stream cursors track
        # this counter instead of indexing into the ring
        self._events_seen = 0
        self._trail: deque = deque(maxlen=max(self.history, 2))
        self._verdicts: Dict[str, Dict[str, Any]] = {}
        self._stats: Dict[str, Any] = {}
        self._ticks = 0
        self._seq = 0  # stream cursor: bumps once per completed tick
        self._started_unix = time.time()
        self._failed: Optional[str] = None
        self._stop = threading.Event()
        self._wake = threading.Condition()
        self._lock = threading.Lock()  # guards published state
        self._thread: Optional[threading.Thread] = None
        # per-tick consumer hook (the control-plane autotuner): called
        # after each published tick with (stats, transitions, verdicts,
        # firing_rule_names). A hook crash unhooks it — the sampler
        # itself never degrades on a consumer's behalf.
        self.on_tick: Optional[
            Callable[
                [Dict[str, Any], List[Dict[str, Any]],
                 Optional[Dict[str, Dict[str, Any]]], List[str]],
                None,
            ]
        ] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "Monitor":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="sutro-monitor"
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        with self._wake:
            self._wake.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive() and self.failed is None

    @property
    def failed(self) -> Optional[str]:
        """The degradation reason once the sampler has given up (an
        injected or real tick error), else None."""
        with self._lock:
            return self._failed

    def set_rules(self, rules: Sequence[SLORule]) -> None:
        """Swap the rule set (tests / operator reconfiguration). Resets
        evaluation state — in-flight alerts resolve administratively."""
        with self._lock:
            self._rules = list(rules)
            self._rule_state = {r.name: _RuleState() for r in self._rules}

    # -- sampler loop --------------------------------------------------

    def _loop(self) -> None:
        from . import ENABLED as _unused  # noqa: F401 — import check only

        while not self._stop.is_set():
            from . import ENABLED

            if ENABLED:
                try:
                    self.tick()
                except Exception as e:  # noqa: BLE001 — the monitor
                    # degrades to disabled, it never takes a job down
                    with self._lock:
                        self._failed = f"{type(e).__name__}: {e}"
                    logger.warning(
                        "monitor sampler failed — degrading to "
                        "disabled: %s", e, exc_info=True,
                    )
                    with self._wake:
                        self._wake.notify_all()
                    return
            self._stop.wait(self.interval_s)

    def tick(self) -> None:
        """One sample: snapshot → window stats → rules → doctor. Public
        for tests and the op-census leg; the loop is just this on a
        timer. Raises propagate to the loop's degrade handler."""
        from . import REGISTRY
        from ..engine import faults

        if faults.ACTIVE is not None:
            faults.inject("telemetry.monitor")
        now_mono = time.monotonic()
        now_unix = time.time()
        snap = REGISTRY.export_snapshot()
        self._ring.append((now_mono, now_unix, snap))
        stats = self._window_stats()
        # rule state is shared with set_rules / snapshot_doc / stream:
        # advance the state machines and publish their transition events
        # under the same lock those readers take
        with self._lock:
            transitions = self._evaluate_rules(stats, now_unix)
            if transitions:
                self._events.extend(transitions)
                self._events_seen += len(transitions)
            firing = [
                name
                for name, s in self._rule_state.items()
                if s.state == "firing"
            ]
        verdicts = self._run_doctor()
        trail_entry = {
            "unix": round(now_unix, 3),
            "rates": stats.get("rates", {}),
            "gauges": stats.get("gauges", {}),
            "percentiles": stats.get("percentiles", {}),
            "alerts_firing": len(firing),
        }
        with self._lock:
            self._stats = stats
            if verdicts is not None:
                self._verdicts = verdicts
            self._trail.append(trail_entry)
            self._ticks += 1
            self._seq += 1
        with self._wake:
            self._wake.notify_all()
        # alert dumps OUTSIDE the lock: filesystem work must not block
        # /monitor readers
        for ev in transitions:
            if ev["state"] == "firing":
                self._dump_for_alert(ev)
        hook = self.on_tick
        if hook is not None:
            try:
                hook(stats, transitions, verdicts, firing)
            except Exception:  # noqa: BLE001 — a consumer crash must
                # not take the sampler down; the control plane has its
                # own degrade path, this is the backstop
                logger.warning(
                    "monitor on_tick hook failed — unhooking",
                    exc_info=True,
                )
                self.on_tick = None

    # -- window statistics ---------------------------------------------

    def _window_edges(self) -> Optional[Tuple[Tuple, Tuple]]:
        """(base, head) ring entries spanning the sliding window: head
        is the newest sample, base the oldest one still inside
        ``window_s`` (so the delta covers at most the window)."""
        if len(self._ring) < 2:
            return None
        head = self._ring[-1]
        cutoff = head[0] - self.window_s
        base = None
        for entry in self._ring:
            if entry[0] >= cutoff:
                base = entry
                break
        if base is None or base is head:
            base = self._ring[-2]
        return base, head

    @staticmethod
    def _counter_total(
        delta: Dict[str, List], name: str,
        label_filter: Optional[Callable[[List[str]], bool]] = None,
    ) -> float:
        return sum(
            v
            for n, lv, v in delta.get("counters") or ()
            if n == name and (label_filter is None or label_filter(lv))
        )

    def _hist_windows(
        self, delta: Dict[str, List]
    ) -> Dict[Tuple[str, Tuple[str, ...]], List[float]]:
        return {
            (n, tuple(lv)): acc
            for n, lv, acc in delta.get("hists") or ()
        }

    def _window_stats(self) -> Dict[str, Any]:
        """Derive the per-tick stats document from the ring. Keys here
        are the namespace SLO rules' ``metric`` fields resolve in."""
        from . import REGISTRY

        edges = self._window_edges()
        head = self._ring[-1]
        gauges = {
            n: (v if not lv else None)
            for n, lv, v in head[2].get("gauges") or ()
            if not lv
        }
        labeled_gauges: Dict[str, Dict[str, float]] = {}
        for n, lv, v in head[2].get("gauges") or ():
            if lv:
                labeled_gauges.setdefault(n, {})[",".join(lv)] = v
        stats: Dict[str, Any] = {
            "window_s": 0.0,
            "rates": {},
            "percentiles": {},
            "gauges": {},
            "tenants": {},
        }
        jobs_running = gauges.get("sutro_jobs_running")
        dp_fleet = gauges.get("sutro_dp_fleet_size")
        interactive_active = gauges.get("sutro_interactive_active")
        g: Dict[str, Any] = {}
        if jobs_running is not None:
            g["jobs_running"] = jobs_running
        if dp_fleet is not None:
            g["dp_fleet_size"] = dp_fleet
            # the rule is dormant until a dp round has reported a fleet
            if dp_fleet > 0 or (jobs_running or 0) > 0:
                stats["dp_fleet_size"] = dp_fleet
        if interactive_active is not None:
            g["interactive_active"] = interactive_active
        rps = labeled_gauges.get("sutro_rows_per_second") or {}
        if rps:
            g["rows_per_second"] = rps
        stats["gauges"] = g

        # tenant attribution: cumulative totals from the head snapshot
        # (tenant,outcome) / (tenant,direction) counters
        tenants: Dict[str, Dict[str, float]] = {}
        for n, lv, v in head[2].get("counters") or ():
            if n == "sutro_tenant_rows_total" and len(lv) == 2:
                t = tenants.setdefault(lv[0], {})
                t[f"rows_{lv[1]}"] = t.get(f"rows_{lv[1]}", 0.0) + v
            elif n == "sutro_tenant_tokens_total" and len(lv) == 2:
                t = tenants.setdefault(lv[0], {})
                t[f"tokens_{lv[1]}"] = t.get(f"tokens_{lv[1]}", 0.0) + v
            elif n == "sutro_tenant_requests_total" and len(lv) == 2:
                t = tenants.setdefault(lv[0], {})
                t[f"requests_{lv[1]}"] = (
                    t.get(f"requests_{lv[1]}", 0.0) + v
                )
        stats["tenants"] = tenants

        if edges is None:
            return stats
        base, head = edges
        dt = max(head[0] - base[0], 1e-6)
        delta = snapshot_delta(base[2], head[2])
        stats["window_s"] = round(dt, 3)

        rows = self._counter_total(delta, "sutro_rows_total")
        quarantined = self._counter_total(
            delta, "sutro_rows_total", lambda lv: lv[:1] == ["quarantined"]
        )
        tokens = self._counter_total(delta, "sutro_tokens_total")
        failures = self._counter_total(
            delta, "sutro_failure_events_total"
        )
        rates = {
            "rows_per_s": round(rows / dt, 4),
            "tokens_per_s": round(tokens / dt, 2),
            "quarantined_per_s": round(quarantined / dt, 4),
            "failure_events_per_s": round(failures / dt, 4),
        }
        stats["rates"] = rates
        if rows > 0:
            stats["quarantine_rate"] = round(quarantined / rows, 4)
        elif quarantined > 0:
            stats["quarantine_rate"] = 1.0
        # batch throughput only judged while batch jobs run — an idle
        # engine must not page anyone about 0 rows/s
        if (jobs_running or 0) > 0:
            stats["batch_rows_per_s"] = rates["rows_per_s"]

        # windowed percentiles from histogram deltas
        hists = self._hist_windows(delta)
        pcts: Dict[str, Any] = {}

        def grade(name: str, lv: Tuple[str, ...] = ()) -> Optional[Dict]:
            m = REGISTRY._metrics.get(name)
            acc = hists.get((name, lv))
            if m is None or acc is None:
                return None
            p50 = percentile_from_buckets(m.buckets, acc, 0.50)
            p99 = percentile_from_buckets(m.buckets, acc, 0.99)
            if p50 is None:
                return None
            return {
                "p50_s": round(p50, 6),
                "p99_s": round(p99, 6) if p99 is not None else None,
                "count": int(acc[-1]),
            }

        ttft = grade("sutro_interactive_ttft_seconds")
        if ttft:
            pcts["ttft"] = ttft
            stats["ttft_p50_s"] = ttft["p50_s"]
            stats["ttft_p99_s"] = ttft["p99_s"]
        itl = grade("sutro_interactive_itl_seconds")
        if itl:
            pcts["itl"] = itl
            stats["itl_p50_s"] = itl["p50_s"]
            stats["itl_p99_s"] = itl["p99_s"]
        stage_pcts: Dict[str, Any] = {}
        for (name, lv) in hists:
            if name == "sutro_stage_seconds" and len(lv) == 1:
                sg = grade(name, lv)
                if sg:
                    stage_pcts[lv[0]] = sg
        if stage_pcts:
            pcts["stages"] = stage_pcts
        stats["percentiles"] = pcts
        return stats

    # -- rule evaluation -----------------------------------------------

    def _lookup(self, stats: Dict[str, Any], metric: str) -> Optional[float]:
        v = stats.get(metric)
        if v is None:
            v = stats.get("rates", {}).get(metric)
        if v is None:
            v = stats.get("gauges", {}).get(metric)
        return float(v) if v is not None else None

    def _evaluate_rules(
        self, stats: Dict[str, Any], now_unix: float
    ) -> List[Dict[str, Any]]:
        """Advance every rule's hysteresis/debounce state machine one
        tick; returns the transition events for this tick. ``tick``
        calls this (and publishes the events) under ``self._lock``; the
        method itself must therefore never take the lock."""
        from . import ALERTS_TOTAL, ENABLED

        out: List[Dict[str, Any]] = []
        for rule in self._rules:
            st = self._rule_state[rule.name]
            value = self._lookup(stats, rule.metric)
            st.value = value
            if value is None:
                # dormant: the workload is not running — hold a firing
                # alert (no data is not evidence of recovery), disarm a
                # pending one
                st.breach_streak = 0
                if st.state == "pending":
                    st.state = "ok"
                continue
            if rule.breached(value):
                st.breach_streak += 1
                st.clear_streak = 0
                if st.state == "ok":
                    st.state = "pending"
                if (
                    st.state == "pending"
                    and st.breach_streak >= rule.for_ticks
                ):
                    st.state = "firing"
                    st.fired_unix = now_unix
                    ev = self._event(rule, "firing", value, now_unix)
                    out.append(ev)
                    if ENABLED:
                        ALERTS_TOTAL.inc(1.0, rule.name, "firing")
            elif rule.cleared(value):
                st.clear_streak += 1
                st.breach_streak = 0
                if st.state == "pending":
                    st.state = "ok"
                elif (
                    st.state == "firing"
                    and st.clear_streak >= rule.clear_ticks
                ):
                    st.state = "ok"
                    ev = self._event(rule, "resolved", value, now_unix)
                    ev["fired_unix"] = st.fired_unix
                    st.fired_unix = None
                    out.append(ev)
                    if ENABLED:
                        ALERTS_TOTAL.inc(1.0, rule.name, "resolved")
            else:
                # hysteresis band (between clear and threshold): hold
                # the current state, reset both streaks — flapping at
                # the threshold produces exactly one fire/resolve pair
                st.breach_streak = 0
                st.clear_streak = 0
        return out

    #: alert metric -> registry histogram carrying its exemplars; a
    #: firing alert embeds the worst captured trace ids so `sutro
    #: trace <id>` jumps straight from the page to the forensic trace
    _EXEMPLAR_SOURCE: Dict[str, str] = {
        "ttft_p99_s": "sutro_interactive_ttft_seconds",
        "itl_p99_s": "sutro_interactive_itl_seconds",
    }
    _EXEMPLAR_TOP = 3

    def _event(
        self, rule: SLORule, state: str, value: float, now_unix: float
    ) -> Dict[str, Any]:
        ev = {
            "rule": rule.name,
            "state": state,
            "severity": rule.severity,
            "workload": rule.workload,
            "metric": rule.metric,
            "op": rule.op,
            "threshold": rule.threshold,
            "value": round(value, 6),
            "unix": round(now_unix, 3),
        }
        if state == "firing":
            ids = self._exemplar_trace_ids(rule.metric)
            if ids:
                ev["exemplar_trace_ids"] = ids
        return ev

    def _exemplar_trace_ids(self, metric: str) -> List[str]:
        """Worst (highest-value) exemplar trace ids for the histogram
        backing ``metric``, deduplicated, worst first."""
        hist = self._EXEMPLAR_SOURCE.get(metric)
        if hist is None:
            return []
        from . import REGISTRY

        out: List[str] = []
        for ex in REGISTRY.exemplars(hist):
            tid = ex.get("trace_id")
            if tid and tid not in out:
                out.append(tid)
            if len(out) >= self._EXEMPLAR_TOP:
                break
        return out

    def _dump_for_alert(self, ev: Dict[str, Any]) -> None:
        """A firing alert persists the flight recorder next to every
        RUNNING job — the same artifact a FAILED job leaves, produced
        while the incident is live. Best-effort by contract."""
        if self._alert_dump is None or self._jobs_provider is None:
            return
        try:
            jobs = self._jobs_provider()
        except Exception:  # noqa: BLE001 — provider errors degrade
            logger.warning("monitor jobs_provider failed", exc_info=True)
            return
        for job_id, _status in jobs:
            try:
                self._alert_dump(job_id, ev)
            except Exception:  # noqa: BLE001 — dumps are best-effort
                logger.warning(
                    "alert dump failed for %s", job_id, exc_info=True
                )

    # -- continuous doctor ---------------------------------------------

    def _run_doctor(self) -> Optional[Dict[str, Dict[str, Any]]]:
        """Diagnose every RUNNING job over the flight recorder's live
        span window. Returns the fresh verdict map, or None when there
        is no provider (unit-test monitors)."""
        if self._jobs_provider is None:
            return None
        from . import job_doc
        from .doctor import diagnose

        try:
            jobs = self._jobs_provider()
        except Exception:  # noqa: BLE001
            logger.warning("monitor jobs_provider failed", exc_info=True)
            return None
        out: Dict[str, Dict[str, Any]] = {}
        for job_id, status in jobs:
            try:
                diag = diagnose(
                    job_doc(job_id), status=status, in_flight=True
                )
                out[job_id] = {
                    "verdict": diag["verdict"],
                    "in_flight": True,
                    "partial": diag.get("partial", False),
                    "evidence": diag.get("evidence", [])[:4],
                    "spans": diag.get("totals", {}).get("spans", 0),
                }
            except Exception:  # noqa: BLE001 — one sick job must not
                # blind the monitor to the others
                logger.warning(
                    "live doctor failed for %s", job_id, exc_info=True
                )
        return out

    # -- published documents -------------------------------------------

    def snapshot_doc(self) -> Dict[str, Any]:
        """The ``GET /monitor`` payload (OBSERVABILITY.md schema)."""
        with self._lock:
            stats = dict(self._stats)
            events = list(self._events)
            trail = list(self._trail)
            verdicts = dict(self._verdicts)
            rule_view = [
                {
                    **r.to_dict(),
                    "state": self._rule_state[r.name].state,
                    "value": self._rule_state[r.name].value,
                    "fired_unix": self._rule_state[r.name].fired_unix,
                }
                for r in self._rules
            ]
            ticks = self._ticks
            failed = self._failed
        active = [r for r in rule_view if r["state"] == "firing"]
        t = self._thread
        return {
            "version": MONITOR_VERSION,
            "enabled": True,
            "running": t is not None and t.is_alive() and failed is None,
            "degraded": failed,
            "interval_s": self.interval_s,
            "window_s": self.window_s,
            "ticks": ticks,
            "started_unix": round(self._started_unix, 3),
            "stats": stats,
            "rules": rule_view,
            "alerts": {"active": active, "events": events},
            "verdicts": verdicts,
            "history": trail,
        }

    def stream(
        self, max_ticks: Optional[int] = None, timeout_s: float = 30.0
    ):
        """Yield one compact NDJSON-able record per completed tick (the
        ``GET /monitor/stream`` body). Ends after ``max_ticks`` records,
        on monitor stop/degrade, or when no tick lands for
        ``timeout_s``."""
        sent = 0
        last_seq = -1
        # cursor over the monotonic event counter, not the deque index:
        # once the ring saturates at EVENT_CAP older entries shift out,
        # so a positional cursor would replay or skip events
        last_seen = 0
        while max_ticks is None or sent < max_ticks:
            deadline = time.monotonic() + timeout_s
            with self._wake:
                while True:
                    with self._lock:
                        seq = self._seq
                        failed = self._failed
                    if seq != last_seq:
                        break
                    if (
                        self._stop.is_set()
                        or failed is not None
                        or time.monotonic() >= deadline
                    ):
                        return
                    self._wake.wait(0.25)
            with self._lock:
                last_seq = self._seq
                stats = dict(self._stats)
                verdicts = dict(self._verdicts)
                events = list(self._events)
                n_new = min(len(events), self._events_seen - last_seen)
                new_events = events[len(events) - n_new:] if n_new else []
                last_seen = self._events_seen
                firing = [
                    r.name
                    for r in self._rules
                    if self._rule_state[r.name].state == "firing"
                ]
            yield {
                "t": "tick",
                "seq": last_seq,
                "unix": round(time.time(), 3),
                "rates": stats.get("rates", {}),
                "percentiles": stats.get("percentiles", {}),
                "gauges": stats.get("gauges", {}),
                "tenants": stats.get("tenants", {}),
                "alerts_firing": firing,
                "alert_events": new_events,
                "verdicts": verdicts,
            }
            sent += 1
