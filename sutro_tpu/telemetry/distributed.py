"""Distributed telemetry: cross-worker trace propagation + federation.

The dp tier (engine/dphost.py) runs one LocalEngine per pod slice;
before this module the coordinator's telemetry ended at its own
process boundary — worker spans, metrics and stage timings never
crossed the wire, so "why was this dp job slow" had no answer. Two
proven shapes, adapted to the dp NDJSON channel:

1. **Trace propagation** (Dapper-style): the coordinator stamps a
   versioned trace context into the ``resume`` frame of every round
   (:func:`trace_context`); each worker rank opens its round under
   that context (:class:`WorkerTelemetry`) and ships a bounded
   telemetry shard back piggybacked on its terminal ``done``/``err``
   frame — its job-filtered span timeline, exact per-job counters,
   and its registry's per-round delta.
2. **Federation** (Monarch-style regional-collect/global-aggregate):
   the coordinator ingests each shard (:class:`DistributedTelemetry`)
   — spans land in the per-job section store (merged into the job
   telemetry document by round and rank), registry deltas fold into
   the live registry under a trailing ``worker`` label
   (``MetricsRegistry.ingest_remote``), so ``GET /metrics``, ``sutro
   telemetry`` and ``sdk.get_metrics_text()`` expose fleet series
   whose per-metric sum is the pod total.

Wire compatibility: every frame addition is a NEW optional key on an
existing frame type, guarded by ``WIRE_VERSION``. An old worker
ignores the ``tele`` key in ``resume`` and ships nothing; an old
coordinator ignores the ``tele`` key in ``done`` — either way the
round completes and the job telemetry document simply reports partial
data (the doctor names the silent ranks). A version-mismatched shard
is dropped with a log line, never an error.

Size discipline: a shipped shard is bounded — at most
``SUTRO_TELEMETRY_SHIP_SPANS`` spans (default 512, newest kept, the
drop count travels with the shard) and a registry delta whose series
count is already capped by the registry's fixed label cardinality.
Everything is inert when ``SUTRO_TELEMETRY=0`` — the dp channel then
carries byte-identical frames to the pre-telemetry protocol.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .registry import snapshot_delta

logger = logging.getLogger(__name__)

#: dp telemetry frame schema version. Bump on incompatible changes to
#: the ``tele`` payloads; receivers drop shards from other versions
#: (graceful degradation, OBSERVABILITY.md "Distributed telemetry").
WIRE_VERSION = 1

#: spans shipped per worker shard (newest kept); the registry delta is
#: bounded by the catalog's fixed cardinality, spans need their own cap
MAX_SHIP_SPANS = int(os.environ.get("SUTRO_TELEMETRY_SHIP_SPANS", "512"))


def _tel():
    # late import: this module is imported from telemetry/__init__.py,
    # so the package singletons are resolved at call time, not load time
    import sutro_tpu.telemetry as tel

    return tel


def trace_context(job_id: str, round_no: int) -> Optional[Dict[str, Any]]:
    """The coordinator's trace context for one dp round — stamped into
    the ``resume`` frame so workers open their round under the same
    trace. None when telemetry is disabled (the frame then carries no
    ``tele`` key at all: zero wire overhead off)."""
    tel = _tel()
    if not tel.ENABLED:
        return None
    return {
        "v": WIRE_VERSION,
        "trace": f"{job_id}/r{int(round_no)}",
        "job": job_id,
        "round": int(round_no),
        "epoch_unix": tel.RECORDER.epoch_wall,
    }


class WorkerTelemetry:
    """Rank>0 side of one dp round: opened with the coordinator's trace
    context, closed into a bounded shard piggybacked on ``done``/
    ``err``. Constructed per round by the engine's dp dispatch with the
    WORKER-LOCAL job id (job ids are per-process; the trace id is the
    cross-process identity)."""

    def __init__(
        self,
        job_id: str,
        rank: int,
        *,
        registry: Any = None,
        recorder: Any = None,
        jobs: Any = None,
    ) -> None:
        tel = _tel()
        self.job_id = job_id
        self.rank = int(rank)
        self._registry = registry if registry is not None else tel.REGISTRY
        self._recorder = recorder if recorder is not None else tel.RECORDER
        self._jobs = jobs if jobs is not None else tel.JOBS
        self._ctx: Optional[Dict[str, Any]] = None
        self._base: Optional[Dict[str, List]] = None
        self._t0 = 0.0

    def begin(self, ctx: Any) -> bool:
        """Open the round under the coordinator's trace context (the
        ``tele`` value of the resume frame). Returns False — and stays
        inert — when telemetry is off, the coordinator sent no context
        (old frame), or the wire version does not match."""
        tel = _tel()
        if not tel.ENABLED or not isinstance(ctx, dict):
            return False
        if ctx.get("v") != WIRE_VERSION:
            logger.info(
                "dropping dp trace context with wire version %r "
                "(this build speaks v%d)", ctx.get("v"), WIRE_VERSION,
            )
            return False
        self._ctx = dict(ctx)
        self._base = self._registry.export_snapshot()
        self._t0 = time.monotonic()
        return True

    def payload(self) -> Optional[Dict[str, Any]]:
        """The bounded shard to piggyback on the worker's terminal
        frame, or None when the round was never opened (ships nothing
        — the coordinator reports partial data)."""
        tel = _tel()
        if self._ctx is None or not tel.ENABLED:
            return None
        # the round envelope span lands BEFORE the snapshot so the
        # shipped timeline carries its own boundary marker
        self._recorder.record(
            "dp_round", self.job_id, self._t0,
            time.monotonic() - self._t0,
            {"trace": self._ctx.get("trace"), "rank": self.rank},
        )
        spans = self._recorder.snapshot(self.job_id)
        dropped = 0
        if len(spans) > MAX_SHIP_SPANS:
            dropped = len(spans) - MAX_SHIP_SPANS
            spans = spans[-MAX_SHIP_SPANS:]
        jc = self._jobs.peek(self.job_id)
        return {
            "v": WIRE_VERSION,
            "trace": self._ctx.get("trace"),
            "round": int(self._ctx.get("round", 0)),
            "rank": self.rank,
            "epoch_unix": self._recorder.epoch_wall,
            "spans": spans,
            "spans_dropped": dropped,
            "counters": jc.to_dict() if jc is not None else {},
            "attrs": dict(jc.attrs) if jc is not None and jc.attrs else {},
            "registry": snapshot_delta(
                self._base, self._registry.export_snapshot()
            ),
        }


class DistributedTelemetry:
    """Coordinator-side store of ingested worker shards, keyed job ->
    (round, rank). Bounded like the other telemetry stores: oldest job
    evicted past ``capacity``, at most ``max_sections`` shards per job
    (a pathological reconnect storm cannot grow one job's document
    without bound). Also the per-job dp round counter — rounds number
    coordinator dispatches, so a resumed job's sections merge by round
    instead of overwriting."""

    def __init__(
        self,
        capacity: int = 256,
        max_sections: int = 128,
        *,
        registry: Any = None,
    ) -> None:
        self.capacity = max(int(capacity), 8)
        self.max_sections = max(int(max_sections), 4)
        self._registry = registry  # None -> the live telemetry.REGISTRY
        self._lock = threading.Lock()
        self._jobs: "collections.OrderedDict[str, Dict[str, Any]]" = (
            collections.OrderedDict()
        )

    def _job(self, job_id: str) -> Dict[str, Any]:
        st = self._jobs.get(job_id)
        if st is None:
            st = self._jobs[job_id] = {"round": 0, "sections": {}}
            while len(self._jobs) > self.capacity:
                self._jobs.popitem(last=False)
        return st

    def next_round(self, job_id: str) -> int:
        """Allocate the next dp round number for a job (1-based)."""
        with self._lock:
            st = self._job(job_id)
            st["round"] += 1
            return st["round"]

    def ingest(self, job_id: str, rank: int, payload: Any) -> bool:
        """Fold one worker shard into the job's section store and the
        live registry (worker-labelled federation). Malformed or
        version-mismatched shards are dropped with a log line — wire
        drift degrades to partial data, never to a failed round."""
        tel = _tel()
        if not tel.ENABLED or not isinstance(payload, dict):
            return False
        if payload.get("v") != WIRE_VERSION:
            logger.info(
                "dropping telemetry shard from rank %s: wire version "
                "%r != %d", rank, payload.get("v"), WIRE_VERSION,
            )
            return False
        try:
            rank = int(payload.get("rank", rank))
            round_no = int(payload.get("round", 0))
            # re-anchor worker span offsets onto the coordinator's
            # timeline: worker wall = worker epoch + t0; coordinator
            # offset = worker wall - coordinator epoch. Cross-host
            # clock skew shifts a whole rank's section, never its
            # internal ordering (merge rules in OBSERVABILITY.md).
            t_off = float(payload.get("epoch_unix", 0.0)) - float(
                tel.RECORDER.epoch_wall
            )
            spans = []
            for s in payload.get("spans") or ():
                if not isinstance(s, dict) or "name" not in s:
                    continue
                d = dict(s)
                d["t0_coord_s"] = round(float(s.get("t0_s", 0.0)) + t_off, 6)
                spans.append(d)
            section = {
                "rank": rank,
                "round": round_no,
                "trace": payload.get("trace"),
                "epoch_unix": payload.get("epoch_unix"),
                "clock_offset_s": round(t_off, 6),
                "spans": spans,
                "spans_dropped": int(payload.get("spans_dropped", 0)),
                "counters": dict(payload.get("counters") or {}),
                "attrs": dict(payload.get("attrs") or {}),
            }
        except (TypeError, ValueError) as e:
            logger.warning(
                "dropping malformed telemetry shard from rank %s: %s",
                rank, e,
            )
            return False
        with self._lock:
            st = self._job(job_id)
            if len(st["sections"]) >= self.max_sections and (
                round_no, rank
            ) not in st["sections"]:
                logger.warning(
                    "job %s telemetry section cap (%d) reached; "
                    "dropping shard round=%d rank=%d",
                    job_id, self.max_sections, round_no, rank,
                )
                return False
            st["sections"][(round_no, rank)] = section
        registry = self._registry if self._registry is not None else tel.REGISTRY
        registry.ingest_remote(str(rank), payload.get("registry") or {})
        tel.DP_EVENTS_TOTAL.inc(1.0, "tele_shard")
        return True

    def sections(self, job_id: str) -> List[Dict[str, Any]]:
        """The job's ingested worker sections, ordered (round, rank)."""
        with self._lock:
            st = self._jobs.get(job_id)
            if st is None:
                return []
            return [
                dict(st["sections"][k]) for k in sorted(st["sections"])
            ]

    def drop(self, job_id: str) -> None:
        with self._lock:
            self._jobs.pop(job_id, None)

    def clear(self) -> None:
        with self._lock:
            self._jobs.clear()


#: coordinator-side singleton (mirrors REGISTRY/RECORDER/JOBS)
REMOTE = DistributedTelemetry(
    capacity=int(os.environ.get("SUTRO_TELEMETRY_JOBS", 256))
)
