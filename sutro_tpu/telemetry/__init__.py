"""Engine telemetry: metrics registry, span tracing, flight recorder.

The operator surface the hosted reference keeps server-side (SURVEY §0:
progress accounting and quota enforcement live behind api.sutro.sh),
rebuilt for the TPU-native engine. Three pillars:

1. **Metrics registry** (:mod:`.registry`) — lock-light counters,
   gauges and bounded histograms with thread-local write shards, fixed
   label cardinality, and Prometheus-text / JSON exporters. Scraped via
   ``GET /metrics`` on the engine daemon (server.py) or ``sutro
   telemetry`` on the CLI.
2. **Span tracer + flight recorder** (:mod:`.spans`) — per-stage
   timings (tokenize, constraint compile, prefill, decode window,
   accept, flush, finalize, dp round) in a bounded ring buffer, dumped
   to ``$SUTRO_HOME/jobs/<job_id>/telemetry.json`` when a job FAILs
   (pairing with the job record's ``failure_log[]``) and on demand.
3. **Per-job counters** — exact rows/tokens accumulators outside the
   label space (job ids are unbounded), reconciled against job results.

The catalog of engine metrics lives here (OBSERVABILITY.md documents
names/labels/units). Everything is guarded by one module-global switch:
``SUTRO_TELEMETRY=0`` (or :func:`set_enabled`) turns instrumentation
off, and call sites pay a single attribute load + truth test — the
same zero-overhead-when-off pattern as engine/faults.py ``ACTIVE``.
"""

from __future__ import annotations

import json
import logging
import os
import time
from pathlib import Path
from typing import Any, Dict, Optional

from .registry import DEFAULT_BUCKETS, MetricsRegistry
from .spans import (
    DEFAULT_CAPACITY,
    FlightRecorder,
    JobCounters,
    JobTelemetryStore,
)
from .traces import DEFAULT_TRACE_CAPACITY, TraceStore

logger = logging.getLogger(__name__)

__all__ = [
    "REGISTRY",
    "RECORDER",
    "JOBS",
    "TRACES",
    "distributed",
    "monitor",
    "traces",
    "traceexport",
    "enabled",
    "set_enabled",
    "stage_observe",
    "job",
    "job_doc",
    "dump_job",
    "load_job_dump",
    "MetricsRegistry",
    "FlightRecorder",
    "JobCounters",
    "JobTelemetryStore",
    "TraceStore",
]

# -- the one enable switch ---------------------------------------------

ENABLED: bool = os.environ.get("SUTRO_TELEMETRY", "1").lower() not in (
    "0", "false", "off",
)


def enabled() -> bool:
    return ENABLED


def set_enabled(on: bool) -> bool:
    """Flip instrumentation globally (tests / the overhead profiler).
    Components that latch the switch at construction (the scheduler's
    timer sink) pick it up on their next construction."""
    global ENABLED
    ENABLED = bool(on)
    return ENABLED


# -- singletons --------------------------------------------------------

REGISTRY = MetricsRegistry()
RECORDER = FlightRecorder(
    capacity=int(os.environ.get("SUTRO_TELEMETRY_SPANS", DEFAULT_CAPACITY))
)
JOBS = JobTelemetryStore(
    capacity=int(os.environ.get("SUTRO_TELEMETRY_JOBS", 256))
)
TRACES = TraceStore(
    capacity=int(
        os.environ.get("SUTRO_TELEMETRY_TRACES", DEFAULT_TRACE_CAPACITY)
    )
)

# -- engine metric catalog (documented in OBSERVABILITY.md) ------------

STAGE_SECONDS = REGISTRY.histogram(
    "sutro_stage_seconds",
    "Engine stage latency (tokenize, constraint_compile, prefill, "
    "decode_window, admit, accept, flush, finalize, dp_round, embed)",
    labels=("stage",),
    unit="seconds",
    max_series=16,
)
ROWS_TOTAL = REGISTRY.counter(
    "sutro_rows_total",
    "Result rows emitted by terminal outcome",
    labels=("outcome",),  # ok | quarantined | cancelled
    max_series=8,
)
STAGE_ROWS_TOTAL = REGISTRY.counter(
    "sutro_stage_rows_total",
    "Stage-graph rows completed per stage (engine/stagegraph.py); "
    "labelled by the submit payload's stage name",
    labels=("stage",),
    unit="rows",
    max_series=32,
)
TOKENS_TOTAL = REGISTRY.counter(
    "sutro_tokens_total",
    "Tokens processed by direction (accounted at job finalize)",
    labels=("direction",),  # in | out
    unit="tokens",
)
JOBS_TOTAL = REGISTRY.counter(
    "sutro_jobs_total",
    "Jobs reaching a terminal status",
    labels=("status",),  # succeeded | failed | cancelled
    max_series=8,
)
ROW_EVENTS_TOTAL = REGISTRY.counter(
    "sutro_failure_events_total",
    "failure_log[] events appended (row_retry, row_quarantined, "
    "io_retry, torn_chunk_quarantined, job_failed, ...)",
    labels=("event",),
    max_series=16,
)
FAULTS_INJECTED_TOTAL = REGISTRY.counter(
    "sutro_faults_injected_total",
    "Deterministic fault-plan injections fired, by site",
    labels=("site",),
    max_series=32,
)
IO_RETRIES_TOTAL = REGISTRY.counter(
    "sutro_io_retries_total",
    "Transient-I/O retry attempts (engine/faults.retry_transient)",
    labels=("what",),
    max_series=16,
)
TOKENIZE_ROWS_TOTAL = REGISTRY.counter(
    "sutro_tokenize_rows_total",
    "Prompt rows tokenized through encode_chat_batch",
    unit="rows",
)
DP_EVENTS_TOTAL = REGISTRY.counter(
    "sutro_dp_events_total",
    "Data-parallel coordinator events",
    # reconnect | stall | fault_forwarded | reject | join | requeue |
    # reshard | steal | drain | dup_result | resume_port_busy
    labels=("kind",),
    max_series=16,
)
DP_FLEET_SIZE = REGISTRY.gauge(
    "sutro_dp_fleet_size",
    "Live dp ranks (running or idle-parked) in the coordinator's "
    "current elastic round, coordinator included",
    unit="ranks",
)
DP_REQUEUED_ROWS_TOTAL = REGISTRY.counter(
    "sutro_dp_requeued_rows_total",
    "Rows returned to the pending pool after a rank died, stalled, "
    "tore a frame, drained (preemption), or never connected",
    unit="rows",
)
DP_STOLEN_ROWS_TOTAL = REGISTRY.counter(
    "sutro_dp_stolen_rows_total",
    "Straggler tail rows dual-assigned to an idle rank "
    "(first result wins; duplicates dropped by row id)",
    unit="rows",
)
TOKENS_PER_SECOND = REGISTRY.gauge(
    "sutro_tokens_per_second",
    "Most recent total token throughput reported by a running job",
    unit="tokens/s",
)
ROWS_PER_SECOND = REGISTRY.gauge(
    "sutro_rows_per_second",
    "Most recent row completion rate by workload "
    "(generate, embed, dp, interactive — dp is the coordinator's "
    "pod-merged rate; interactive is the serving tier's request rate)",
    labels=("workload",),
    unit="rows/s",
    max_series=8,
)
# -- interactive serving tier (serving/gateway.py, OBSERVABILITY.md) ----
TTFT_SECONDS = REGISTRY.histogram(
    "sutro_interactive_ttft_seconds",
    "Interactive request time-to-first-token (admission wait + prefill "
    "+ first decode), measured from gateway submit",
    unit="seconds",
)
ITL_SECONDS = REGISTRY.histogram(
    "sutro_interactive_itl_seconds",
    "Interactive inter-token latency (gap between consecutive streamed "
    "tokens of one request)",
    unit="seconds",
)
INTERACTIVE_REQUESTS_TOTAL = REGISTRY.counter(
    "sutro_interactive_requests_total",
    "Interactive serving requests by terminal outcome",
    labels=("outcome",),  # ok | cancelled | error | rejected
    max_series=8,
)
INTERACTIVE_ACTIVE = REGISTRY.gauge(
    "sutro_interactive_active",
    "Interactive requests currently admitted or streaming",
)
INTERACTIVE_PREEMPTIONS_TOTAL = REGISTRY.counter(
    "sutro_interactive_preemptions_total",
    "Batch rows suspended to admit an interactive request inside the "
    "interactive_slots budget (the row re-admits row-granularly)",
)
TOKENS_PER_SECOND_PER_CHIP = REGISTRY.gauge(
    "sutro_tokens_per_second_per_chip",
    "Most recent per-chip token throughput (Throughput estimator)",
    unit="tokens/s",
)
JOBS_RUNNING = REGISTRY.gauge(
    "sutro_jobs_running",
    "Generation/embedding jobs currently executing in this process",
)
SPANS_DROPPED = REGISTRY.gauge(
    "sutro_flight_recorder_dropped",
    "Spans evicted from the flight-recorder ring since process start",
)
# -- tenant attribution + live monitor (telemetry/monitor.py) -----------
# Tenant series ride the registry's ordinary cardinality admission: the
# tenant label value space is capped at TENANT_MAX_SERIES and overflow
# collapses into the standard ("_overflow", ...) series — an abusive
# tenant-id generator cannot grow the scrape unboundedly.
TENANT_MAX_SERIES = int(os.environ.get("SUTRO_TENANT_MAX_SERIES", 32))
TENANT_REQUESTS_TOTAL = REGISTRY.counter(
    "sutro_tenant_requests_total",
    "Submissions by tenant and kind (batch job submits and interactive "
    "requests)",
    labels=("tenant", "kind"),  # kind: batch | interactive
    max_series=TENANT_MAX_SERIES,
)
TENANT_ROWS_TOTAL = REGISTRY.counter(
    "sutro_tenant_rows_total",
    "Result rows attributed to a tenant at job terminal status",
    labels=("tenant", "outcome"),  # ok | quarantined
    max_series=TENANT_MAX_SERIES,
    unit="rows",
)
TENANT_TOKENS_TOTAL = REGISTRY.counter(
    "sutro_tenant_tokens_total",
    "Tokens attributed to a tenant at job terminal status",
    labels=("tenant", "direction"),  # in | out
    max_series=TENANT_MAX_SERIES,
    unit="tokens",
)
ALERTS_TOTAL = REGISTRY.counter(
    "sutro_monitor_alerts_total",
    "SLO alert lifecycle transitions emitted by the live monitor",
    labels=("rule", "state"),  # state: firing | resolved
    max_series=32,
)
ADMISSION_REJECTIONS_TOTAL = REGISTRY.counter(
    "sutro_admission_rejections_total",
    "Submits rejected by the control plane's per-tenant token buckets",
    labels=("tenant",),
    max_series=TENANT_MAX_SERIES,
)
PREEMPTIONS_TOTAL = REGISTRY.counter(
    "sutro_preemptions_total",
    "Decode rows suspended by the priority ladder "
    "(labels are the preemptor's and victim's job_priority)",
    labels=("from", "to"),
    unit="rows",
    max_series=32,
)
AUTOTUNE_ADJUSTMENTS_TOTAL = REGISTRY.counter(
    "sutro_autotune_adjustments_total",
    "Live engine-config adjustments applied by the control-plane "
    "autotuner",
    labels=("knob",),
    max_series=16,
)
PREFIX_STORE_HITS_TOTAL = REGISTRY.counter(
    "sutro_prefix_store_hits_total",
    "Radix prefix-store lookups that matched at least one KV page",
)
PREFIX_STORE_MISSES_TOTAL = REGISTRY.counter(
    "sutro_prefix_store_misses_total",
    "Radix prefix-store lookups that matched nothing",
)
PREFIX_STORE_EVICTIONS_TOTAL = REGISTRY.counter(
    "sutro_prefix_store_evictions_total",
    "Unpinned prefix-store pages evicted under allocation pressure",
    unit="pages",
)
PREFIX_STORE_TOKENS_SAVED_TOTAL = REGISTRY.counter(
    "sutro_prefix_store_prefill_tokens_saved_total",
    "Prefill tokens skipped because their KV was already resident in "
    "the prefix store",
    unit="tokens",
)
# -- tiered paged-KV pool (engine/kvtier.py, OBSERVABILITY.md) ----------
KV_TIER_PAGES = REGISTRY.gauge(
    "sutro_kv_tier_pages",
    "KV pages resident per below-HBM tier (host = int8 page payloads "
    "in pinned RAM, disk = npz bundles under sutro_home()/kvtier)",
    labels=("tier",),  # host | disk
    unit="pages",
    max_series=4,
)
KV_MIGRATIONS_TOTAL = REGISTRY.counter(
    "sutro_kv_migrations_total",
    "Tier-hop page migrations by direction (demote = device->host, "
    "promote = host/disk->device, disk_write/disk_read = host<->disk)",
    labels=("dir",),  # demote | promote | disk_write | disk_read
    max_series=8,
)
KV_RESUMES_TOTAL = REGISTRY.counter(
    "sutro_kv_resumes_total",
    "Preempted-row resumes by mechanism: 'upload' re-admits from a "
    "hibernated host/disk payload (page-upload, no prefill); "
    "'reprefill' regenerates from scratch (tier miss / torn promotion)",
    labels=("kind",),  # upload | reprefill
    unit="rows",
    max_series=4,
)
FLEET_REPLICAS = REGISTRY.gauge(
    "sutro_fleet_replicas",
    "Fleet router replica census by state (healthy = breaker closed + "
    "ready + not draining; open/half_open = breaker tripped; draining "
    "= alive, refusing new work)",
    labels=("state",),  # healthy | open | half_open | draining
    max_series=8,
)
FLEET_FAILOVERS_TOTAL = REGISTRY.counter(
    "sutro_fleet_failovers_total",
    "Requests/jobs moved off a failed replica: 'batch' = jobstore "
    "resume_job re-submission after a replica death mid-job, "
    "'interactive' = transparent pre-first-token retry on another "
    "replica, 'stream_error' = post-first-token structured mid-stream "
    "error returned to the client (no transparent retry possible)",
    labels=("kind",),  # batch | interactive | stream_error
    max_series=8,
)
FLEET_ROUTED_PREFIX_HITS_TOTAL = REGISTRY.counter(
    "sutro_fleet_routed_prefix_hits_total",
    "Interactive requests routed to a replica reporting > 0 warm "
    "prefix tokens (the SGLang-style cache-aware routing win)",
)
FLEET_ROUTE_SECONDS = REGISTRY.histogram(
    "sutro_fleet_route_seconds",
    "Router time from request arrival to the routing decision landing "
    "on a replica (candidate scoring + affinity probe + upstream "
    "connect, retries included); exemplars carry the router trace id",
    labels=("kind",),  # interactive | batch
    unit="seconds",
    max_series=8,
)

# Span names the engine emits — OBSERVABILITY.md's span schema section
# and tests key off this tuple, so additions land in one place.
STAGES = (
    "tokenize",
    "constraint_compile",
    "admit",
    "prefill",
    "decode_window",
    "accept",
    "flush",
    "finalize",
    "dp_round",
    "embed",
    # tiered-KV migration hops (engine/kvtier.py): device<->host page
    # payload moves on the scheduler thread (disk writes happen on the
    # migration worker and surface as kv_demote queue time only)
    "kv_demote",
    "kv_promote",
)


def stage_observe(
    stage: str, dur_s: float, exemplar: Optional[str] = None
) -> None:
    """One engine stage latency sample into the registry histogram
    (the flight-recorder span is the caller's concern — spans carry
    job identity, the histogram does not). ``exemplar`` optionally
    pins a trace id to the sample's bucket (forensics). Internally
    gated: callers on hot paths may invoke it bare and still honor
    the kill switch."""
    if not ENABLED:
        return
    STAGE_SECONDS.observe(dur_s, stage, exemplar=exemplar)


def job(job_id: str) -> JobCounters:
    return JOBS.job(job_id)


# -- per-job document / flight-recorder dump ---------------------------

# v2: adds per-job "attrs" (device info, profile trace path) and, for
# dp coordinator jobs, "workers" — the ingested per-rank sections
# (telemetry/distributed.py), merged by (round, rank)
SCHEMA_VERSION = 2


def job_doc(job_id: str) -> Dict[str, Any]:
    """Assemble the per-job telemetry document from live state: the
    job's span timeline (flight recorder) + its exact counters, plus —
    on a dp coordinator — every ingested worker section (the merged
    cross-process timeline the doctor analyzes)."""
    jc = JOBS.peek(job_id)
    spans = RECORDER.snapshot(job_id)
    doc: Dict[str, Any] = {
        "version": SCHEMA_VERSION,
        "job_id": job_id,
        "dumped_at": time.strftime(
            "%Y-%m-%dT%H:%M:%S%z", time.localtime()
        ),
        "recorder": {
            "capacity": RECORDER.capacity,
            "dropped": RECORDER.dropped,
            "epoch_unix": RECORDER.epoch_wall,
        },
        "counters": jc.to_dict() if jc is not None else {},
        "stages": sorted({s["name"] for s in spans}),
        "spans": spans,
    }
    if jc is not None and jc.attrs:
        doc["attrs"] = dict(jc.attrs)
    workers = distributed.REMOTE.sections(job_id)
    if workers:
        doc["workers"] = workers
        doc["stages"] = sorted(
            set(doc["stages"])
            | {
                s["name"]
                for w in workers
                for s in w.get("spans", ())
            }
        )
    return doc


def dump_job(job_dir: Path, job_id: str) -> Optional[Dict[str, Any]]:
    """Write ``telemetry.json`` into the job directory (atomic rename,
    jobstore convention). Best-effort: recording a postmortem must
    never become a new failure. Returns the doc (or None on failure/
    disabled)."""
    if not ENABLED:
        return None
    try:
        doc = job_doc(job_id)
        SPANS_DROPPED.set(RECORDER.dropped)
        path = Path(job_dir) / "telemetry.json"
        tmp = path.with_suffix(".json.tmp")
        tmp.write_text(json.dumps(doc, indent=2))
        tmp.replace(path)
        return doc
    except Exception:
        logger.warning(
            "telemetry dump failed for %s", job_id, exc_info=True
        )
        return None


def load_job_dump(job_dir: Path) -> Optional[Dict[str, Any]]:
    path = Path(job_dir) / "telemetry.json"
    if not path.exists():
        return None
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError) as e:
        logger.warning("unreadable telemetry.json in %s: %s", job_dir, e)
        return None


def reset_for_tests() -> None:
    """Drop accumulated registry/recorder/job state (declarations
    stay). Tests only."""
    REGISTRY.reset()
    RECORDER.clear()
    TRACES.clear()
    for jc in JOBS:
        JOBS.drop(jc.job_id)
    distributed.REMOTE.clear()


# imported last: distributed.py / monitor.py resolve the package
# singletons above lazily at call time, so the bottom imports only
# publish the names
from . import distributed  # noqa: E402
from . import monitor  # noqa: E402
from . import traceexport  # noqa: E402
