"""Job bottleneck doctor: name WHY a job was slow, with evidence.

Input is the merged per-job telemetry document (``telemetry.job_doc``
— local flight-recorder timeline plus, for dp coordinator jobs, the
ingested per-worker sections from telemetry/distributed.py). Output is
a deterministic diagnosis document (golden-pinned by
tests/test_doctor.py):

- **per-process stage attribution** — wall time split across engine
  stages for the coordinator ("rank0") and every worker rank that
  shipped telemetry;
- **roofline grades** — decode windows carry ``batch``/``steps``/
  ``avg_ctx`` attrs (scheduler) and the job's attrs carry the runner's
  device info, so each window's attempted token rate grades against
  the chip's HBM roofline (engine/roofline.py) and prefill spans grade
  as MFU;
- **one named verdict** from a fixed taxonomy, most-specific first:

  ========================  ============================================
  verdict                   meaning
  ========================  ============================================
  ``insufficient_data``     no spans anywhere (telemetry off / evicted)
  ``warming_up``            in-flight job, no spans landed yet — a
                            partial-data marker, not a failure
  ``straggler_worker``      one rank's wall >= 1.5x the median of the
                            others — the pod waits on that slice
  ``io_bound``              flush+finalize dominate both compute and
                            the rest of the host pipeline
  ``host_bound_admit``      host-side admission work (tokenize,
                            constraint compile, accept) exceeds device
                            time — the chip starves behind the host
  ``decode_below_roofline``  device-bound but the median decode window
                            runs under 40% of the HBM roofline
  ``healthy``               none of the above
  ========================  ============================================

Partial data degrades, never fails: a dp world with silent ranks (old
workers, telemetry disabled there) is diagnosed from what arrived and
flagged ``partial`` with the missing ranks named in the evidence.

Pure analysis on purpose — no engine imports beyond the dependency-free
roofline table — so the doctor runs identically on a live engine, a
persisted ``telemetry.json``, or a synthetic document in tests.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..engine import roofline

DOCTOR_VERSION = 1

#: stages whose duration is device dispatch/fetch (the chip working)
DEVICE_STAGES = ("prefill", "decode_window", "admit", "embed")
#: host-side pipeline stages (the chip idle or overlapped)
HOST_STAGES = ("tokenize", "constraint_compile", "accept", "flush",
               "finalize", "kv_demote", "kv_promote")
#: I/O subset of the host stages (jobstore writes)
IO_STAGES = ("flush", "finalize")
#: round envelopes — excluded from attribution (they CONTAIN stages)
ENVELOPE_STAGES = ("dp_round",)

#: the verdict taxonomy, in priority order (OBSERVABILITY.md "Doctor")
VERDICTS = (
    "insufficient_data",
    "warming_up",
    "interactive_starved",
    "stage_starved",
    "straggler_worker",
    "io_bound",
    "host_bound_admit",
    "kv_pressure",
    "resume_bound",
    "decode_below_roofline",
    "healthy",
)

#: gateway TTFT threshold mirrored here for the evidence line
#: (serving/gateway.py STARVED_TTFT_S stamps attrs["interactive"])
INTERACTIVE_STARVED_TTFT_S = 5.0

#: a stage-graph stage that spent more than this fraction of the job's
#: wall waiting for its FIRST upstream row is starved (the streaming
#: handoff degenerated into a barrier — engine/stagegraph.py stamps
#: attrs["stages"][name]["starved_s"])
STAGE_STARVED_FRAC = 0.5

#: a decode window under this fraction of the HBM roofline is "below"
ROOFLINE_OK_PCT = 40.0
#: a rank this much slower than the median of the others is a straggler
STRAGGLER_RATIO = 1.5


def _attribution(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Wall/stage attribution for ONE process's span list (its own
    timeline — offsets are process-relative, so no cross-host clock
    enters here)."""
    stages: Dict[str, Dict[str, float]] = {}
    t_lo, t_hi = float("inf"), float("-inf")
    worked = 0
    for s in spans:
        name = s.get("name")
        if name in ENVELOPE_STAGES:
            # envelopes CONTAIN stages — and the coordinator's
            # dp_round spans the whole pod round including its wait on
            # workers, so counting it toward wall would make rank0
            # "slowest" by construction
            continue
        dur = float(s.get("dur_s", 0.0))
        t0 = float(s.get("t0_s", 0.0))
        t_lo = min(t_lo, t0)
        t_hi = max(t_hi, t0 + dur)
        worked += 1
        e = stages.setdefault(name, {"count": 0, "total_s": 0.0})
        e["count"] += 1
        e["total_s"] += dur
    for e in stages.values():
        e["total_s"] = round(e["total_s"], 6)
    wall = max(t_hi - t_lo, 0.0) if worked else 0.0

    def _sum(names: Tuple[str, ...]) -> float:
        return round(
            sum(stages.get(n, {}).get("total_s", 0.0) for n in names), 6
        )

    return {
        "spans": len(spans),
        "wall_s": round(wall, 6),
        "device_s": _sum(DEVICE_STAGES),
        "host_s": _sum(HOST_STAGES),
        "io_s": _sum(IO_STAGES),
        "stages": {k: stages[k] for k in sorted(stages)},
    }


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n == 0:
        return 0.0
    if n % 2:
        return s[n // 2]
    return (s[n // 2 - 1] + s[n // 2]) / 2.0


def _grade_roofline(
    spans: List[Dict[str, Any]],
    device: Optional[Dict[str, Any]],
    counters: Dict[str, Any],
) -> Optional[Dict[str, Any]]:
    """Grade one process's device windows against its chip roofline.
    None when the process shipped no device info; a ``reason`` entry
    when the device kind has no public spec (CPU, emulators) — grades
    are omitted, never fabricated (engine/roofline.py contract)."""
    if not isinstance(device, dict):
        return None
    kind = str(device.get("device_kind") or "")
    if roofline.hw_specs(kind) is None:
        return {"device_kind": kind, "graded_windows": 0,
                "reason": f"no roofline spec for device kind {kind!r}"}
    n_dev = max(int(device.get("n_devices", 1)), 1)
    # fallback context depth when a window lacks avg_ctx: prompt plus
    # half the generated tail, from the job's exact counters
    rows = float(
        counters.get("rows_ok", 0)
        + counters.get("rows_quarantined", 0)
        + counters.get("rows_cancelled", 0)
    )
    ctx_fallback = None
    if rows > 0:
        ctx_fallback = (
            float(counters.get("input_tokens", 0))
            + float(counters.get("output_tokens", 0)) / 2.0
        ) / rows
    decode_pcts: List[float] = []
    mfus: List[float] = []
    for s in spans:
        attrs = s.get("attrs") or {}
        dur = float(s.get("dur_s", 0.0))
        if dur <= 0:
            continue
        if s.get("name") == "decode_window" and attrs.get("batch"):
            batch = int(attrs["batch"])
            steps = int(attrs.get("steps", 1))
            avg_ctx = attrs.get("avg_ctx", ctx_fallback)
            if avg_ctx is None:
                continue
            bps = roofline.decode_bytes_per_step(
                param_bytes=int(device.get("param_bytes", 0)),
                batch=batch,
                avg_ctx=float(avg_ctx),
                num_layers=int(device.get("num_layers", 0)),
                kv_heads=int(device.get("kv_heads", 0)),
                head_dim=int(device.get("head_dim", 0)),
                kv_dtype_bytes=int(device.get("kv_dtype_bytes", 2)),
            )
            g = roofline.grade_decode(
                batch * steps / dur / n_dev,
                batch=batch,
                bytes_per_step=bps,
                device_kind=kind,
            )
            if g.get("pct_hbm_roofline") is not None:
                decode_pcts.append(float(g["pct_hbm_roofline"]))
        elif s.get("name") == "prefill" and attrs.get("tokens"):
            g = roofline.grade_prefill(
                float(attrs["tokens"]) / dur / n_dev,
                n_params=int(device.get("n_params", 0)),
                device_kind=kind,
            )
            if g.get("mfu_prefill") is not None:
                mfus.append(float(g["mfu_prefill"]))
    out: Dict[str, Any] = {
        "device_kind": kind,
        "graded_windows": len(decode_pcts),
    }
    if decode_pcts:
        out["decode_pct_hbm_median"] = round(_median(decode_pcts), 1)
        out["decode_pct_hbm_best"] = round(max(decode_pcts), 1)
    if mfus:
        out["mfu_prefill_median"] = round(_median(mfus), 1)
    return out


#: statuses past which a job can no longer gain spans
_TERMINAL_STATUSES = ("SUCCEEDED", "FAILED", "CANCELLED")

# -- per-request verdicts (forensics traces, telemetry/traces.py) ------

#: the per-request taxonomy, in priority order
REQUEST_VERDICTS = (
    "insufficient_data",
    "queue_wait_bound",
    "preemption_bound",
    "stream_flush_bound",
    "healthy",
)

#: a leg must cover at least this fraction of the request wall to be
#: "bound" by it (queue wait uses the stricter QUEUE_BOUND_FRACTION)
REQUEST_BOUND_FRACTION = 0.25
QUEUE_BOUND_FRACTION = 0.4

#: stages that are the request actually computing (device + host work)
_REQUEST_COMPUTE = ("prefill", "decode_window", "admit", "accept")


def diagnose_request(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Grade ONE request's trace document (telemetry/traces.py) into a
    per-request verdict: where did THIS request's wall time go —
    admission queue, preemption stalls, stream flush, or honest
    compute. Pure analysis, same contract as :func:`diagnose`: runs
    identically on a live trace, a served ``GET /trace/{id}``'s source
    document, or a synthetic one in tests."""
    spans = list(doc.get("spans") or ())
    trace_id = doc.get("trace_id")
    out: Dict[str, Any] = {
        "version": DOCTOR_VERSION,
        "trace_id": trace_id,
        "kind": doc.get("kind"),
        "outcome": doc.get("outcome"),
    }
    if not spans:
        out["verdict"] = "insufficient_data"
        out["evidence"] = [
            "no spans in this trace (telemetry disabled mid-request, "
            "or the trace ring evicted it)"
        ]
        out["legs"] = {}
        return out

    t_lo = min(float(s["t0_s"]) for s in spans)
    t_hi = max(float(s["t0_s"]) + float(s["dur_s"]) for s in spans)
    wall = max(t_hi - t_lo, 1e-9)

    def _leg(*names: str) -> float:
        return sum(
            float(s["dur_s"]) for s in spans if s["name"] in names
        )

    queue_s = _leg("queue_wait")
    compute_s = _leg(*_REQUEST_COMPUTE)
    flush_s = _leg("stream_flush")
    # suspend -> resume stall per preempted row: pair each
    # preempt_suspend with the NEXT resume carrying the same row_id
    suspends: Dict[int, float] = {}
    preempt_stall_s = 0.0
    n_preempt = 0
    for s in spans:
        a = s.get("attrs") or {}
        if s["name"] == "preempt_suspend":
            n_preempt += 1
            rid = a.get("row_id")
            if rid is not None and rid not in suspends:
                suspends[int(rid)] = float(s["t0_s"])
        elif s["name"] == "resume":
            rid = a.get("row_id")
            t0 = suspends.pop(int(rid), None) if rid is not None else None
            if t0 is not None:
                preempt_stall_s += max(float(s["t0_s"]) - t0, 0.0)
    # a suspend never resumed stalls through the end of the trace
    for t0 in suspends.values():
        preempt_stall_s += max(t_hi - t0, 0.0)

    legs = {
        "wall_s": round(wall, 6),
        "queue_s": round(queue_s, 6),
        "compute_s": round(compute_s, 6),
        "flush_s": round(flush_s, 6),
        "preempt_stall_s": round(preempt_stall_s, 6),
        "preemptions": n_preempt,
    }
    evidence: List[str] = []
    verdict: Optional[str] = None
    if queue_s > compute_s and queue_s >= QUEUE_BOUND_FRACTION * wall:
        verdict = "queue_wait_bound"
        evidence.append(
            f"admission queue wait {queue_s:.3f}s covers "
            f"{100.0 * queue_s / wall:.0f}% of the request wall "
            f"{wall:.3f}s and exceeds compute {compute_s:.3f}s: the "
            "request waited for a session slot, not for the chip"
        )
    elif (
        n_preempt
        and preempt_stall_s > max(queue_s, flush_s)
        and preempt_stall_s >= REQUEST_BOUND_FRACTION * wall
    ):
        verdict = "preemption_bound"
        evidence.append(
            f"{n_preempt} preemption(s) stalled this request "
            f"{preempt_stall_s:.3f}s of its {wall:.3f}s wall "
            "(suspended rows re-admitted row-granularly and "
            "regenerated): lower co-tenant priority pressure or raise "
            "interactive_slots headroom"
        )
    elif flush_s > compute_s and flush_s >= REQUEST_BOUND_FRACTION * wall:
        verdict = "stream_flush_bound"
        evidence.append(
            f"SSE flush {flush_s:.3f}s exceeds compute {compute_s:.3f}s "
            f"({100.0 * flush_s / wall:.0f}% of wall): the consumer "
            "(client socket) is the bottleneck, not the engine"
        )
    if verdict is None:
        verdict = "healthy"
        evidence.append(
            f"compute {compute_s:.3f}s dominates queue {queue_s:.3f}s, "
            f"flush {flush_s:.3f}s and preemption stalls "
            f"{preempt_stall_s:.3f}s over a {wall:.3f}s wall"
        )
    if n_preempt and verdict != "preemption_bound":
        evidence.append(
            f"{n_preempt} preemption(s) observed "
            f"(total stall {preempt_stall_s:.3f}s)"
        )
    out["verdict"] = verdict
    out["evidence"] = evidence
    out["legs"] = legs
    return out


def diagnose(
    doc: Dict[str, Any],
    *,
    status: Optional[str] = None,
    num_rows: Optional[int] = None,
    in_flight: bool = False,
) -> Dict[str, Any]:
    """Analyze one merged job telemetry document into a diagnosis with
    a named bottleneck verdict (see module docstring for the taxonomy)
    and human-readable evidence lines.

    ``in_flight`` marks a diagnosis over a RUNNING job's live span
    window (the monitor's continuous doctor, or ``sutro doctor`` on a
    job that hasn't terminated). It is also derived from a non-terminal
    ``status``. In flight, zero spans are expected early — the verdict
    is ``warming_up`` (a partial-data marker), never the alarming
    ``insufficient_data``; with spans present the normal verdict is
    produced but flagged partial, since attribution covers only what
    has executed so far."""
    if status is not None and str(status).upper() not in _TERMINAL_STATUSES:
        in_flight = True
    job_id = doc.get("job_id")
    counters = doc.get("counters") or {}
    attrs = doc.get("attrs") or {}

    # -- assemble per-process span lists (merged by round per rank) ----
    procs: Dict[str, Dict[str, Any]] = {
        "rank0": {
            "spans": list(doc.get("spans") or ()),
            "counters": counters,
            "device": attrs.get("device"),
        }
    }
    world = None
    for s in procs["rank0"]["spans"]:
        a = s.get("attrs") or {}
        if s.get("name") == "dp_round" and a.get("world"):
            world = int(a["world"])
    present_ranks = set()
    for w in doc.get("workers") or ():
        rank = w.get("rank")
        present_ranks.add(rank)
        name = f"rank{rank}"
        p = procs.setdefault(
            name, {"spans": [], "counters": {}, "device": None}
        )
        p["spans"].extend(w.get("spans") or ())
        if w.get("counters"):
            p["counters"] = w["counters"]
        dev = (w.get("attrs") or {}).get("device")
        if dev:
            p["device"] = dev

    processes: Dict[str, Dict[str, Any]] = {}
    for name in sorted(procs):
        p = procs[name]
        att = _attribution(p["spans"])
        rl = _grade_roofline(p["spans"], p["device"], p["counters"])
        if rl is not None:
            att["roofline"] = rl
        processes[name] = att

    missing_ranks = (
        sorted(r for r in range(1, world) if r not in present_ranks)
        if world
        else []
    )

    # -- evidence + verdict --------------------------------------------
    evidence: List[str] = []
    verdict: Optional[str] = None

    if missing_ranks:
        evidence.append(
            "partial data: no telemetry shard from rank(s) "
            + ", ".join(str(r) for r in missing_ranks)
            + f" of a world of {world} (old worker or telemetry "
            "disabled there)"
        )

    total_spans = sum(a["spans"] for a in processes.values())
    if total_spans == 0:
        if in_flight:
            verdict = "warming_up"
            evidence.append(
                "job is still in flight and no spans have landed in "
                "the live window yet — partial data, retry shortly"
            )
        else:
            verdict = "insufficient_data"
            evidence.append(
                "no spans recorded for this job (telemetry disabled, "
                "or the flight recorder evicted its window)"
            )
    elif in_flight:
        evidence.append(
            "live verdict over the flight recorder's current span "
            "window — the job is still running, so attribution covers "
            "only work executed so far"
        )

    # interactive starvation: the serving gateway stamps per-request
    # latency aggregates onto co-resident batch jobs' attrs — starved
    # requests mean the latency tier is losing to this batch traffic
    ia = attrs.get("interactive") or {}
    if verdict is None and ia.get("starved"):
        verdict = "interactive_starved"
        evidence.append(
            f"{ia['starved']} of {ia.get('requests', ia['starved'])} "
            "interactive request(s) sharing this job's decode window "
            f"waited over {INTERACTIVE_STARVED_TTFT_S:.0f}s for a "
            "first token (max TTFT "
            f"{ia.get('ttft_max_s', 0.0):.1f}s): raise "
            "EngineConfig.interactive_slots or lower the batch load"
        )
    elif ia.get("requests"):
        evidence.append(
            f"{ia['requests']} interactive request(s) co-scheduled "
            f"with this job (max TTFT {ia.get('ttft_max_s', 0.0):.1f}s"
            + (
                f"; {ia['preempted_rows']} batch row(s) preempted and "
                "re-admitted"
                if ia.get("preempted_rows")
                else ""
            )
            + ")"
        )

    # stage starvation (stage-graph jobs): a downstream stage that sat
    # idle waiting for its first upstream row for most of the job's
    # wall — the streaming handoff degenerated into a full-stage
    # barrier (upstream too slow, feed cadence too coarse, or a host
    # stage blocking the chain)
    sg = attrs.get("stages") or {}
    if verdict is None and sg:
        wall = max(
            (s.get("done_s") or 0.0 for s in sg.values()), default=0.0
        )
        starved = [
            (n, s.get("starved_s") or 0.0)
            for n, s in sg.items()
            if wall > 0
            and (s.get("starved_s") or 0.0) >= STAGE_STARVED_FRAC * wall
        ]
        if starved:
            verdict = "stage_starved"
            worst = max(starved, key=lambda kv: kv[1])
            evidence.append(
                f"stage {worst[0]!r} waited {worst[1]:.3f}s for its "
                f"first upstream row ({100 * worst[1] / wall:.0f}% of "
                f"the {wall:.3f}s stage-graph wall, threshold "
                f"{STAGE_STARVED_FRAC:.0%}): upstream decode dominates "
                "the DAG — lower SUTRO_STAGE_FEED_EVERY, shrink the "
                "upstream stage's max_new_tokens, or split the graph"
            )

    # straggler: a rank whose wall dwarfs the median of the others
    walls = {
        n: a["wall_s"] for n, a in processes.items() if a["spans"]
    }
    if verdict is None and len(walls) >= 2:
        slowest = max(sorted(walls), key=lambda n: walls[n])
        rest = _median([v for n, v in walls.items() if n != slowest])
        if rest > 0 and walls[slowest] >= STRAGGLER_RATIO * rest:
            verdict = "straggler_worker"
            evidence.append(
                f"{slowest} wall {walls[slowest]:.3f}s vs median "
                f"{rest:.3f}s of the other process(es) "
                f"(>= {STRAGGLER_RATIO}x): the pod waits on that slice"
            )

    device_s = round(
        sum(a["device_s"] for a in processes.values()), 6
    )
    host_s = round(sum(a["host_s"] for a in processes.values()), 6)
    io_s = round(sum(a["io_s"] for a in processes.values()), 6)
    admit_s = round(host_s - io_s, 6)  # tokenize+constraint+accept

    if verdict is None and io_s > device_s and io_s > admit_s:
        verdict = "io_bound"
        evidence.append(
            f"flush+finalize {io_s:.3f}s exceed device time "
            f"{device_s:.3f}s and the rest of the host pipeline "
            f"{admit_s:.3f}s: the jobstore I/O path is the bottleneck"
        )
    if verdict is None and admit_s > device_s:
        top = ""
        top_s = -1.0
        for a in processes.values():
            for st in ("tokenize", "constraint_compile", "accept"):
                v = a["stages"].get(st, {}).get("total_s", 0.0)
                if v > top_s:
                    top, top_s = st, v
        verdict = "host_bound_admit"
        evidence.append(
            f"host admission pipeline {admit_s:.3f}s exceeds device "
            f"time {device_s:.3f}s (largest: {top} {top_s:.3f}s): the "
            "chip starves behind the host"
        )

    # tiered-KV pool health (engine/kvtier.py stamps attrs["kv_tier"]
    # at job end): migration time competing with device time means the
    # pool is thrashing between tiers; preempted rows that mostly
    # RE-PREFILL instead of resuming by page-upload mean the host/disk
    # tiers are losing the state they exist to keep
    kvt = attrs.get("kv_tier") or {}
    if kvt:
        migrate_s = round(
            sum(
                a["stages"].get(st, {}).get("total_s", 0.0)
                for a in processes.values()
                for st in ("kv_demote", "kv_promote")
            ),
            6,
        )
        if (
            verdict is None
            and device_s > 0
            and migrate_s > 0.25 * device_s
        ):
            verdict = "kv_pressure"
            evidence.append(
                f"tier migrations spent {migrate_s:.3f}s against "
                f"{device_s:.3f}s of device time "
                f"({kvt.get('demotes', 0)} demotion(s), "
                f"{kvt.get('promotes', 0)} promotion(s)): the paged "
                "pool is thrashing across tiers — grow the HBM pool, "
                "raise kv_tier_host_pages, or lower resident sessions"
            )
        reup = kvt.get("resumes_upload", 0)
        repre = kvt.get("resumes_reprefill", 0)
        if verdict is None and repre > reup and repre > 0:
            verdict = "resume_bound"
            evidence.append(
                f"{repre} preempted row(s) re-prefilled from scratch "
                f"vs {reup} resumed by page-upload: hibernated state "
                "is falling out of the host/disk tiers before resume "
                "(raise kv_tier_host_pages or enable kv_tier_disk)"
            )
        elif reup or repre:
            evidence.append(
                f"kv tiers: {reup} page-upload resume(s), {repre} "
                f"re-prefill(s), {kvt.get('demotes', 0)} demotion(s), "
                f"{kvt.get('promotes', 0)} promotion(s)"
            )

    if verdict is None:
        pcts = [
            a["roofline"]["decode_pct_hbm_median"]
            for a in processes.values()
            if a.get("roofline", {}).get("decode_pct_hbm_median")
            is not None
        ]
        if pcts and _median(pcts) < ROOFLINE_OK_PCT:
            verdict = "decode_below_roofline"
            evidence.append(
                f"median decode window at {_median(pcts):.1f}% of the "
                f"HBM roofline (< {ROOFLINE_OK_PCT:.0f}%): decode is "
                "device-bound but far from the memory-bandwidth bound "
                "(batch too small, context too short, or kernel "
                "inefficiency)"
            )

    if verdict is None:
        verdict = "healthy"
        evidence.append(
            f"device time {device_s:.3f}s dominates host time "
            f"{host_s:.3f}s and no process stands out"
        )

    q = counters.get("rows_quarantined", 0)
    if q:
        evidence.append(
            f"{q} row(s) quarantined — see the job's failure_log for "
            "per-row causes"
        )

    # elastic dp fleet summary (engine/api stamps attrs["dp_fleet"]
    # at round end): steals corroborate — or pre-empt — a straggler
    # verdict, requeues explain wall-time spent re-running rows
    fleet = attrs.get("dp_fleet") or {}
    stolen = fleet.get("stolen_rows", 0)
    if stolen:
        evidence.append(
            f"{stolen} row(s) stolen from straggling rank(s) by idle "
            "ranks (first result won; "
            f"{fleet.get('duplicate_results_dropped', 0)} duplicate "
            "result(s) dropped) — the fleet masked a straggler"
        )
    requeued = fleet.get("requeued_rows", 0)
    if requeued:
        lost = fleet.get("lost_ranks") or []
        drained = fleet.get("drained_ranks") or []
        detail = []
        if lost:
            detail.append(
                "lost rank(s) " + ", ".join(str(r) for r in lost)
            )
        if drained:
            detail.append(
                "preemption-drained rank(s) "
                + ", ".join(str(r) for r in drained)
            )
        evidence.append(
            f"{requeued} row(s) requeued and re-run elsewhere"
            + (" (" + "; ".join(detail) + ")" if detail else "")
            + " — wall time includes the re-execution"
        )
    late = fleet.get("late_joiners") or []
    if late:
        evidence.append(
            "rank(s) " + ", ".join(str(r) for r in late)
            + " joined the round late and absorbed re-sharded rows"
        )

    # radix prefix store (engine/prefixstore.py): api stamps
    # attrs["prefix"] with saved-vs-paid shell prefill tokens. A fully
    # cold shell on a warm-capable engine is evidence (a repeat of this
    # job would hit), not a verdict — prefill may still be cheap
    # relative to decode.
    pa = attrs.get("prefix") or {}
    saved = pa.get("saved_tokens", 0)
    paid = pa.get("paid_tokens", 0)
    if saved:
        evidence.append(
            f"prefix store: {saved} shell prefill token(s) skipped "
            f"(warm KV reused; {paid} paid for the novel tail)"
        )
    elif paid:
        evidence.append(
            f"prefix_cold: {paid} shared-prefix token(s) prefilled "
            "with zero store hits — first job for this shell (repeats "
            "will reuse its KV), or the store evicted it under "
            "allocation pressure (sutro_prefix_store_evictions_total)"
        )

    return {
        "version": DOCTOR_VERSION,
        "job_id": job_id,
        "status": status,
        "num_rows": num_rows,
        "verdict": verdict,
        "evidence": evidence,
        "in_flight": in_flight,
        "partial": bool(missing_ranks) or in_flight,
        "missing_ranks": missing_ranks,
        "world": world,
        "processes": processes,
        "totals": {
            "spans": total_spans,
            "device_s": device_s,
            "host_s": host_s,
            "io_s": io_s,
        },
    }


# -- fleet-level diagnosis (fleet router /fleet + `sutro fleet status`) --

FLEET_VERDICTS = (
    "no_healthy_replicas",
    "replica_flapping",
    "fleet_degraded",
    "healthy",
)


def diagnose_fleet(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Grade a fleet membership snapshot (fleet/membership.py
    ``snapshot()``, optionally with router counters merged in) into a
    fleet-level verdict. Pure analysis, same contract as
    :func:`diagnose`: runs identically on a live router's snapshot or
    a synthetic one in tests.

    Priority order: a fleet with zero routable replicas is an outage
    regardless of why; a flapping replica (breaker cycling — probe
    flakiness, overload, or a crash loop) outranks a plainly-open one
    because it poisons routing decisions on every transition; any open
    breaker with capacity remaining is degraded-but-serving.
    """
    replicas = list(doc.get("replicas") or ())
    n_healthy = int(doc.get("n_healthy") or 0)
    evidence: List[str] = []

    flapping = [
        r.get("rid")
        for r in replicas
        if int(r.get("transitions_in_window") or 0) >= 3
    ]
    broken = [
        r.get("rid")
        for r in replicas
        if r.get("state") in ("open", "half_open")
    ]
    draining = [r.get("rid") for r in replicas if r.get("draining")]

    if not replicas or n_healthy == 0:
        verdict = "no_healthy_replicas"
        evidence.append(
            f"0 of {len(replicas)} replica(s) routable — every request "
            "is refused at the front door (check replica processes and "
            "probe reachability)"
        )
    elif flapping:
        verdict = "replica_flapping"
        evidence.append(
            f"replica(s) {sorted(flapping)} crossed >= 3 breaker "
            "transitions inside the flap window — probe flakiness, "
            "overload, or a crash loop; routing churns on every flip"
        )
    elif broken or draining:
        verdict = "fleet_degraded"
        if broken:
            evidence.append(
                f"breaker open on {sorted(broken)}; fleet serving on "
                f"{n_healthy}/{len(replicas)} replica(s)"
            )
        if draining:
            evidence.append(
                f"replica(s) {sorted(draining)} draining (SIGTERM "
                "shutdown in progress) — excluded from routing while "
                "in-flight work finishes"
            )
    else:
        verdict = "healthy"
        evidence.append(
            f"all {len(replicas)} replica(s) routable"
        )

    failovers = doc.get("failovers") or {}
    if isinstance(failovers, dict) and any(failovers.values()):
        evidence.append(
            "failovers so far: "
            + ", ".join(
                f"{k}={v}" for k, v in sorted(failovers.items()) if v
            )
        )

    return {
        "version": DOCTOR_VERSION,
        "verdict": verdict,
        "evidence": evidence,
        "n_replicas": len(replicas),
        "n_healthy": n_healthy,
        "flapping": sorted(flapping),
        "open": sorted(broken),
        "draining": sorted(draining),
    }
