"""Kill-switch zero-op pass.

Every optional subsystem is env-gated (``SUTRO_TELEMETRY``,
``SUTRO_MONITOR``, ``SUTRO_CONTROL``, ``SUTRO_PREFIX_STORE``,
``SUTRO_FAULT_PLAN``) with a documented contract: switch off means
*zero added work* on the hot path — not "cheap", zero. The benchmarks
assert the aggregate budget; this pass catches individual regressions
statically by taint-walking from the flag read:

1. Seed taint at every ``os.environ.get("SUTRO_*")`` read: the
   assigned global (``ENABLED``), the enclosing function
   (``enabled()``, ``_enabled()``), attribute latches assigned from
   tainted values (``self._tel_on = telemetry.enabled()``), and so on
   to a fixpoint across the package.
2. Any side-effecting call into a gated subsystem (telemetry metric
   writes — ``.inc``/``.set``/``.observe``/``stage_observe`` — and
   fault-plan ``inject``/``fire``) made outside the subsystem's own
   package must be *dominated* by a check of a tainted symbol: an
   enclosing ``if``/ternary mentioning the taint, a preceding tainted
   guard clause that terminates, or an internal guard at the top of the
   resolved callee (wrappers like ``_count_outcome`` that begin with
   ``if telemetry.ENABLED:``).

Rule: ``killswitch-ungated``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import FunctionInfo, ModuleInfo, PackageIndex, dotted
from .core import Finding

FLAG_ENVS = (
    "SUTRO_TELEMETRY",
    "SUTRO_MONITOR",
    "SUTRO_CONTROL",
    "SUTRO_PREFIX_STORE",
    "SUTRO_FAULT_PLAN",
)

_METRIC_OPS = ("inc", "set", "observe")


def _env_flag_read(call: ast.Call, mod: ModuleInfo) -> Optional[str]:
    t = mod.expand(dotted(call.func) or "")
    if t not in ("os.environ.get", "os.getenv", "environ.get"):
        return None
    if call.args and isinstance(call.args[0], ast.Constant):
        v = call.args[0].value
        if isinstance(v, str) and v in FLAG_ENVS:
            return v
    return None


def _tainted_tails(node: ast.AST, taints: Set[str]) -> bool:
    """Does any Name id or Attribute tail under ``node`` hit the taint
    set?"""
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in taints:
            return True
        if isinstance(n, ast.Attribute) and n.attr in taints:
            return True
    return False


def _taint_expr(node: ast.AST, taints: Set[str]) -> bool:
    """Tight propagation grammar: the value must *be* a flag
    expression, not merely mention one somewhere — names/attr tails in
    the taint set, calls to tainted functions, and boolean/compare/
    conditional compositions of those with constants. This is what
    keeps ordinary data flow out of the taint set."""
    if isinstance(node, ast.Name):
        return node.id in taints
    if isinstance(node, ast.Attribute):
        return node.attr in taints
    if isinstance(node, ast.Call):
        t = dotted(node.func)
        return t is not None and t.rsplit(".", 1)[-1] in taints
    if isinstance(node, ast.BoolOp):
        return any(_taint_expr(v, taints) for v in node.values)
    if isinstance(node, ast.UnaryOp):
        return _taint_expr(node.operand, taints)
    if isinstance(node, ast.Compare):
        return _taint_expr(node.left, taints) or any(
            _taint_expr(c, taints) for c in node.comparators
        )
    if isinstance(node, ast.IfExp):
        return (
            _taint_expr(node.test, taints)
            or _taint_expr(node.body, taints)
            or _taint_expr(node.orelse, taints)
        )
    return False


def discover_taints(index: PackageIndex) -> Set[str]:
    taints: Set[str] = set()
    # seeds: (a) module-level names assigned straight from an env-flag
    # read; (b) functions whose body reads an env flag
    for mod in index.modules.values():
        for func in mod.functions.values():
            for n in ast.walk(func.node):
                if isinstance(n, ast.Call) and _env_flag_read(n, mod):
                    taints.add(func.qualname.split(".")[-1])
                    break
        for n in mod.tree.body:
            if isinstance(n, (ast.Assign, ast.AnnAssign)):
                value = n.value
                if value is not None and any(
                    isinstance(c, ast.Call) and _env_flag_read(c, mod)
                    for c in ast.walk(value)
                ):
                    taints.update(_targets_of(n))
    # fixpoint propagation
    for _ in range(4):
        grew = False

        def add(name: str) -> None:
            nonlocal grew
            if name and name not in taints:
                taints.add(name)
                grew = True

        for mod in index.modules.values():
            # module-level latches assigned from taint expressions
            for n in mod.tree.body:
                if isinstance(n, (ast.Assign, ast.AnnAssign)):
                    if n.value is not None and _taint_expr(n.value, taints):
                        for t in _targets_of(n):
                            add(t)
            for func in mod.functions.values():
                fname = func.qualname.split(".")[-1]
                has_global = {
                    g
                    for s in ast.walk(func.node)
                    if isinstance(s, ast.Global)
                    for g in s.names
                }
                for n in ast.walk(func.node):
                    if isinstance(n, (ast.Assign, ast.AnnAssign)):
                        if n.value is None or not _taint_expr(
                            n.value, taints
                        ):
                            continue
                        # attribute latches (``self._tel_on = …``) and
                        # mutated globals propagate; plain locals stay
                        # function-scoped
                        tgts = (
                            n.targets
                            if isinstance(n, ast.Assign)
                            else [n.target]
                        )
                        for tg in tgts:
                            if isinstance(tg, ast.Attribute):
                                add(tg.attr)
                            elif (
                                isinstance(tg, ast.Name)
                                and tg.id in has_global
                            ):
                                add(tg.id)
                    elif (
                        isinstance(n, ast.Return)
                        and n.value is not None
                        and _taint_expr(n.value, taints)
                    ):
                        add(fname)
                # globals mutated inside a function that a tainted
                # function calls (``configure()`` -> ``install()`` ->
                # ``ACTIVE``): the installed value is the flag
                if has_global and fname not in taints:
                    for other in mod.functions.values():
                        oname = other.qualname.split(".")[-1]
                        if oname not in taints:
                            continue
                        called = {
                            (dotted(c.func) or "").rsplit(".", 1)[-1]
                            for c in ast.walk(other.node)
                            if isinstance(c, ast.Call)
                        }
                        if fname in called:
                            for g in has_global:
                                add(g)
                            break
        if not grew:
            break
    return taints


def _targets_of(n) -> List[str]:
    out = []
    targets = n.targets if isinstance(n, ast.Assign) else [n.target]
    for t in targets:
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, ast.Attribute):
            out.append(t.attr)
    return out


def _terminates(stmts: List[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


def _op_of(call: ast.Call, mod: ModuleInfo) -> Optional[Tuple[str, str]]:
    """(switch, op-key) for side-effecting gated-subsystem calls."""
    t = mod.expand(dotted(call.func) or "")
    if not t:
        return None
    if ".telemetry." in t or t.startswith("telemetry."):
        last = t.rsplit(".", 1)[-1]
        if last in _METRIC_OPS:
            parts = t.split(".")
            metric = parts[-2] if len(parts) >= 2 else last
            # registry/admin plumbing isn't a hot-path metric write
            if metric.isupper():
                return ("telemetry", f"{metric}.{last}")
        if last == "stage_observe":
            return ("telemetry", "stage_observe")
    if t.endswith((".faults.inject", ".faults.fire")) or t in (
        "faults.inject",
        "faults.fire",
    ):
        return ("faults", t.rsplit(".", 1)[-1])
    return None


def _home_of(mod: ModuleInfo) -> Set[str]:
    """Switches whose implementation lives in this module (exempt)."""
    parts = mod.name.split(".")
    out: Set[str] = set()
    if "telemetry" in parts:
        out.add("telemetry")
    if parts[-1] == "faults":
        out.add("faults")
    return out


def _local_taints(func_node, taints: Set[str]) -> Set[str]:
    """Names assigned from tainted values inside one function
    (``plan = ACTIVE``)."""
    local = set()
    for _ in range(2):
        for n in ast.walk(func_node):
            if isinstance(n, (ast.Assign, ast.AnnAssign)) and n.value is not None:
                if _taint_expr(n.value, taints | local):
                    local.update(_targets_of(n))
    return local


def _has_internal_gate(func: FunctionInfo, taints: Set[str]) -> bool:
    body = getattr(func.node, "body", [])
    scope = taints | _local_taints(func.node, taints)
    for stmt in body[:8]:
        if isinstance(stmt, ast.If) and _tainted_tails(stmt.test, scope):
            return True
    return False


def gated_functions(index: PackageIndex, taints: Set[str]) -> Set[str]:
    """Bare names of functions that gate themselves on a flag near the
    top. Computed to a fixpoint so gating composes through wrappers:
    ``fire()`` checks ``ACTIVE`` directly, ``inject()`` checks the
    value it got back from ``fire()`` — both are zero-op when the
    switch is off, so calls to either need no caller-side gate."""
    gated: Set[str] = set()
    for _ in range(3):
        grew = False
        for mod in index.modules.values():
            for func in mod.functions.values():
                fname = func.qualname.split(".")[-1]
                if fname in gated:
                    continue
                if _has_internal_gate(func, taints | gated):
                    gated.add(fname)
                    grew = True
        if not grew:
            break
    return gated


class _Checker:
    def __init__(
        self, index: PackageIndex, taints: Set[str], gated: Set[str]
    ):
        self.index = index
        self.taints = taints
        self.gated = gated
        self.findings: List[Finding] = []

    def check_function(self, func: FunctionInfo) -> None:
        homes = _home_of(func.module)
        # closure scope: a flag latched in an enclosing function
        # (``tel_on = telemetry.enabled()``) gates its nested callbacks
        basis = self.taints | self.gated
        scope = set(basis)
        f: Optional[FunctionInfo] = func
        while f is not None:
            scope |= _local_taints(f.node, basis)
            f = f.parent
        self._walk(func, func.node.body, gated=False, scope=scope, homes=homes)

    def _walk(self, func, stmts, gated: bool, scope, homes) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs are scanned as their own functions
            if isinstance(stmt, ast.If):
                test_tainted = _tainted_tails(stmt.test, scope)
                self._walk(
                    func, stmt.body, gated or test_tainted, scope, homes
                )
                self._walk(func, stmt.orelse, gated, scope, homes)
                if test_tainted and _terminates(stmt.body) and not stmt.orelse:
                    gated = True  # tainted guard clause covers the rest
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self._scan_exprs(func, [getattr(stmt, "iter", None) or stmt.test], gated, scope, homes)
                self._walk(func, stmt.body, gated, scope, homes)
                self._walk(func, stmt.orelse, gated, scope, homes)
                continue
            if isinstance(stmt, ast.Try):
                self._walk(func, stmt.body, gated, scope, homes)
                for h in stmt.handlers:
                    self._walk(func, h.body, gated, scope, homes)
                self._walk(func, stmt.orelse, gated, scope, homes)
                self._walk(func, stmt.finalbody, gated, scope, homes)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                self._scan_exprs(
                    func,
                    [i.context_expr for i in stmt.items],
                    gated,
                    scope,
                    homes,
                )
                self._walk(func, stmt.body, gated, scope, homes)
                continue
            self._scan_exprs(func, [stmt], gated, scope, homes)

    def _scan_exprs(self, func, nodes, gated: bool, scope, homes) -> None:
        for root in nodes:
            if root is None:
                continue
            # an expression-level taint mention (ternary, ``and``
            # short-circuit, latched kwarg) gates its own statement
            stmt_gated = gated or _tainted_tails(root, scope)
            if stmt_gated:
                continue
            for n in ast.walk(root):
                if not isinstance(n, ast.Call):
                    continue
                op = _op_of(n, func.module)
                if op is None or op[0] in homes:
                    continue
                _, target = self.index.resolve_call(func, n)
                if target is not None and (
                    target.qualname.split(".")[-1] in self.gated
                    or _has_internal_gate(target, self.taints)
                ):
                    continue
                switch, opkey = op
                self.findings.append(
                    Finding(
                        rule="killswitch-ungated",
                        path=func.module.path,
                        line=n.lineno,
                        message=f"side-effecting {switch} call "
                        f"({opkey}) not gated behind the {switch} "
                        "kill switch — switch-off must mean zero work "
                        "on this path",
                        symbol=func.label,
                        key=f"{switch}:{opkey}",
                    )
                )


def run(index: PackageIndex) -> List[Finding]:
    taints = discover_taints(index)
    gated = gated_functions(index, taints)
    checker = _Checker(index, taints, gated)
    for mod in index.modules.values():
        for func in mod.functions.values():
            checker.check_function(func)
    return checker.findings
