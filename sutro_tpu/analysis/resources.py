"""Resource-lifecycle pass: path-sensitive acquire/release checking.

The engine's correctness-critical resources are refcounted or pooled:
KV pages (PageAllocator ``alloc``/``reserve`` vs ``free``), prefix-store
pins (``lookup_pin`` vs ``release``), stream channels (``StreamChannel``
vs ``finish``/``fail``/``cancel``), sockets (``create_connection`` /
``accept`` vs ``close``), worker threads (ctor vs ``join``), and
interactive slots (``take_slot`` vs ``return_slot``). Losing a release
on one path is silent corruption — a pinned prefix that never unpins
starves eviction; a double ``free`` hands the same page to two rows.

Rules:

- ``resource-leak`` — an acquire whose resource is still held when a
  path leaves the function. Explicit exits (``return``/``raise``/
  implicit end) always count. Implicit exception edges (a call on the
  path may raise) count only when the function releases that resource
  kind somewhere — a function that never releases is assumed to be
  transferring ownership, not leaking.
- ``resource-double-release`` — the same variable released twice on one
  path without an intervening re-acquire, for kinds where the second
  release corrupts state (page free-lists, pin refcounts).

Ownership transfer ends tracking: returning/yielding the variable,
passing it as a call argument (``self.reg[k] = ch`` style stores and
``lst.append(t)`` both route through this), assigning it onto an
attribute, capturing it in a nested def, or entering it as a context
manager. ``var = None`` and rebinds end tracking too, as do
``is None`` refinements on the branch where the variable is None.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import (
    EXIT_EXCEPTION,
    EXIT_FALLTHROUGH,
    EXIT_RAISE,
    EXIT_RETURN,
    FlowWalker,
    FunctionInfo,
    PackageIndex,
    calls_in,
    dotted,
    names_in,
)
from .core import Finding


@dataclasses.dataclass(frozen=True)
class Kind:
    name: str
    # acquire: ``var = recv.suffix(...)`` / ``var = exact(...)`` /
    # ``var = Ctor(...)``; ``recv.acquire_arg(var)`` adopts ``var``.
    acquire_suffix: Tuple[str, ...] = ()
    acquire_exact: Tuple[str, ...] = ()
    ctor_suffix: Tuple[str, ...] = ()
    acquire_arg: Tuple[str, ...] = ()
    # release: ``var.method()`` / ``anything.arg_suffix(var)``
    release_method: Tuple[str, ...] = ()
    release_arg: Tuple[str, ...] = ()
    unsafe_double: bool = False
    release_hint: str = "release"


KINDS: Tuple[Kind, ...] = (
    Kind(
        name="kv-pages",
        acquire_suffix=(".alloc", ".alloc_pages"),
        acquire_arg=(".reserve",),
        # ``.promote``/``.reserve_pages`` are the KV-tier ownership
        # transfers (scheduler._promote_prefix/_release): pages handed
        # to the prefix store or re-adopted by the native allocator
        # count as released — a later free of the same var is the
        # double-free the tiering paths must never perform
        release_arg=(".free", ".free_pages", ".promote",
                     ".reserve_pages"),
        unsafe_double=True,
        release_hint="free()",
    ),
    Kind(
        name="prefix-pin",
        acquire_suffix=(".lookup_pin",),
        release_arg=(".release",),
        unsafe_double=True,
        release_hint="release()",
    ),
    Kind(
        name="stream-channel",
        ctor_suffix=("StreamChannel",),
        release_method=(".finish", ".fail", ".cancel", ".close"),
        release_hint="finish()/fail()/cancel()",
    ),
    Kind(
        name="socket",
        acquire_exact=("socket.create_connection", "socket.create_server"),
        acquire_suffix=(".accept",),
        release_method=(".close",),
        release_arg=("_hard_close",),
        release_hint="close()",
    ),
    Kind(
        name="thread",
        ctor_suffix=("threading.Thread",),
        release_method=(".join",),
        release_hint="join()",
    ),
    Kind(
        name="interactive-slot",
        acquire_suffix=(".take_slot",),
        release_arg=(".return_slot", ".release_slot"),
        unsafe_double=True,
        release_hint="return_slot()",
    ),
)


@dataclasses.dataclass(frozen=True)
class _Rec:
    kind: Kind
    line: int
    released: bool = False


@dataclasses.dataclass
class _CallFacts:
    node: ast.Call
    text: str  # import-expanded dotted text ("" if not dotted)
    arg_names: Tuple[str, ...]  # direct Name args (incl. Starred, kwargs)


@dataclasses.dataclass
class _StmtFacts:
    calls: List[_CallFacts]
    # Assign-shaped facts: (target_name, acquire_kind_or_None)
    binds: List[Tuple[str, Optional[Kind]]]
    captured: Set[str]  # names referenced inside nested defs/lambdas
    stored: Set[str] = dataclasses.field(default_factory=set)
    # names assigned onto an attribute/subscript (``self.x = var``,
    # ``reg[k] = var``) — ownership transfers to the container


def _direct_arg_names(call: ast.Call) -> Tuple[str, ...]:
    out = []
    for a in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(a, ast.Starred):
            a = a.value
        if isinstance(a, ast.Name):
            out.append(a.id)
    return tuple(out)


def _acquire_kind(
    text: str,
    call: Optional[ast.Call] = None,
    kinds: Tuple[Kind, ...] = KINDS,
) -> Optional[Kind]:
    if not text:
        return None
    for k in kinds:
        if text in k.acquire_exact:
            return k
        if any(text.endswith(s) for s in k.acquire_suffix):
            return k
        if any(
            text == c or text.endswith(f".{c}") for c in k.ctor_suffix
        ):
            # daemon threads are fire-and-forget by design — no join
            # is owed, so they're not a tracked acquisition
            if k.name == "thread" and call is not None and any(
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in call.keywords
            ):
                return None
            return k
    return None


class _ResourceWalker(FlowWalker):
    def __init__(self, pass_: "_ResourcePass", func: FunctionInfo):
        self.p = pass_
        self.func = func
        self.findings: List[Finding] = []
        self._emitted: Set[Tuple[str, str, int, str]] = set()
        # kinds this function releases somewhere: only those get
        # implicit exception-edge leak findings
        self.owned_kinds: Set[str] = set()
        for call in calls_in(func.node, skip_nested=False):
            text = func.module.expand(dotted(call.func) or "")
            for k in pass_.kinds:
                if any(
                    text.endswith(s) for s in k.release_method + k.release_arg
                ):
                    self.owned_kinds.add(k.name)

    # -- state plumbing ------------------------------------------------
    def initial_state(self):
        return {}

    def copy_state(self, state):
        return dict(state)

    def state_key(self, state):
        return tuple(
            sorted((v, r.kind.name, r.line, r.released) for v, r in state.items())
        )

    # -- per-statement facts (cached across paths) ----------------------
    def _facts(self, stmt) -> _StmtFacts:
        cached = self.p.stmt_facts.get(id(stmt))
        if cached is not None:
            return cached
        expand = self.func.module.expand
        # compound statements execute their bodies through the walker;
        # only header expressions run "at" this statement
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            call_roots: List[ast.AST] = [stmt.iter]
        elif isinstance(stmt, ast.While):
            call_roots = [stmt.test]
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            call_roots = [i.context_expr for i in stmt.items]
        elif isinstance(stmt, ast.ExceptHandler):
            call_roots = []
        elif isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            call_roots = []
        else:
            call_roots = [stmt]
        calls = [
            _CallFacts(
                node=c,
                text=expand(dotted(c.func) or ""),
                arg_names=_direct_arg_names(c),
            )
            for root in call_roots
            for c in calls_in(root)
        ]
        binds: List[Tuple[str, Optional[Kind]]] = []
        captured: Set[str] = set()
        stored: Set[str] = set()
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = stmt.value
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            kind = None
            if isinstance(value, ast.Call):
                kind = _acquire_kind(
                    expand(dotted(value.func) or ""), value,
                    kinds=self.p.kinds,
                )
            for t in targets:
                if isinstance(t, ast.Name):
                    binds.append((t.id, kind))
                elif isinstance(t, ast.Tuple) and t.elts:
                    # ``conn, addr = sock.accept()``: the resource is
                    # the first element; the rest are plain rebinds
                    for i, e in enumerate(t.elts):
                        if isinstance(e, ast.Name):
                            binds.append((e.id, kind if i == 0 else None))
                elif isinstance(t, (ast.Attribute, ast.Subscript)):
                    if value is not None:
                        stored |= {
                            n.id
                            for n in ast.walk(value)
                            if isinstance(n, ast.Name)
                        }
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            for n in ast.walk(stmt.target):
                if isinstance(n, ast.Name):
                    binds.append((n.id, None))
        elif isinstance(stmt, ast.ExceptHandler):
            if stmt.name:
                binds.append((stmt.name, None))
        elif isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            captured = names_in(stmt)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if isinstance(item.optional_vars, ast.Name):
                    binds.append((item.optional_vars.id, None))
        facts = _StmtFacts(
            calls=calls, binds=binds, captured=captured, stored=stored
        )
        self.p.stmt_facts[id(stmt)] = facts
        return facts

    def _classify_call(self, state, cf: _CallFacts):
        """Returns ('release', var, kind) / ('acquire_arg', var, kind) /
        ('double', var, kind) / None for one call vs current state."""
        for var, rec in state.items():
            k = rec.kind
            if any(cf.text == f"{var}{m}" for m in k.release_method) or (
                any(cf.text.endswith(s) for s in k.release_arg)
                and var in cf.arg_names
            ):
                return ("double" if rec.released else "release", var, k)
        for k in self.p.kinds:
            if any(cf.text.endswith(s) for s in k.acquire_arg):
                for name in cf.arg_names:
                    if name not in state:
                        return ("acquire_arg", name, k)
        return None

    # -- FlowWalker hooks ----------------------------------------------
    def on_stmt(self, state, stmt) -> None:
        facts = self._facts(stmt)
        if facts.captured:
            for var in [v for v in state if v in facts.captured]:
                del state[var]  # closure capture = escape
            return
        for cf in facts.calls:
            action = self._classify_call(state, cf)
            if action is not None:
                verb, var, kind = action
                if verb == "release":
                    state[var] = dataclasses.replace(
                        state[var], released=True
                    )
                elif verb == "double":
                    if kind.unsafe_double:
                        self._emit(
                            self.p.double_rule,
                            cf.node.lineno,
                            f"{kind.name}:{var}",
                            f"`{var}` ({kind.name}) is released twice on "
                            f"one path (first release already happened); "
                            f"a second {kind.release_hint} corrupts the "
                            "refcount/free-list",
                        )
                elif verb == "acquire_arg":
                    state[var] = _Rec(kind=kind, line=cf.node.lineno)
                continue
            # ownership transfer: a tracked var passed as a direct
            # argument to any other call escapes
            for var in [v for v in state if v in cf.arg_names]:
                del state[var]
        for var in [v for v in state if v in facts.stored]:
            del state[var]  # stored into a container/attribute = escape
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                t = dotted(item.context_expr)
                if t is not None and t in state:
                    # ``with sock:`` — the context manager releases
                    state[t] = dataclasses.replace(state[t], released=True)
        for var, kind in facts.binds:
            if var in state:
                del state[var]  # rebind / ``var = None`` ends tracking
            if kind is not None:
                state[var] = _Rec(kind=kind, line=stmt.lineno)
        # yields transfer control with the value escaping to the caller
        if isinstance(stmt, ast.Expr) and isinstance(
            stmt.value, (ast.Yield, ast.YieldFrom)
        ):
            for var in [v for v in state if v in names_in(stmt.value)]:
                del state[var]

    def stmt_may_raise(self, state, stmt) -> bool:
        if not any(
            not r.released and r.kind.name in self.owned_kinds
            for r in state.values()
        ):
            return False
        facts = self._facts(stmt)
        risky = [
            cf for cf in facts.calls if self._classify_call(state, cf) is None
        ]
        return bool(risky)

    def assume(self, state, test, truth: bool):
        self._refine(state, test, truth)
        return state

    def _refine(self, state, test, truth: bool) -> None:
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            self._refine(state, test.operand, not truth)
            return
        if isinstance(test, ast.BoolOp):
            if isinstance(test.op, ast.And) and truth:
                for v in test.values:
                    self._refine(state, v, True)
            elif isinstance(test.op, ast.Or) and not truth:
                for v in test.values:
                    self._refine(state, v, False)
            return
        var_is_none: Optional[Tuple[str, bool]] = None
        if (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and isinstance(test.left, ast.Name)
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
        ):
            if isinstance(test.ops[0], ast.Is):
                var_is_none = (test.left.id, truth)
            elif isinstance(test.ops[0], ast.IsNot):
                var_is_none = (test.left.id, not truth)
        elif isinstance(test, ast.Name):
            var_is_none = (test.id, not truth)  # falsy ~ absent
        if var_is_none is not None:
            var, is_none = var_is_none
            if is_none and var in state:
                del state[var]  # on this branch the acquire didn't stick

    def on_exit(self, state, kind: str, node) -> None:
        held = {
            v: r
            for v, r in state.items()
            if not r.released
        }
        if not held:
            return
        if kind in (EXIT_RETURN, EXIT_FALLTHROUGH):
            escaping = (
                names_in(node.value)
                if isinstance(node, ast.Return) and node.value is not None
                else set()
            )
            for var, rec in held.items():
                if var in escaping:
                    continue
                where = (
                    "an early return"
                    if kind == EXIT_RETURN
                    else "the end of the function"
                )
                self._leak(var, rec, where, node)
        elif kind == EXIT_RAISE:
            for var, rec in held.items():
                self._leak(var, rec, "a raise", node)
        elif kind == EXIT_EXCEPTION:
            # a raising statement that itself passes the var to a
            # callee counts as ownership transfer — the callee may have
            # stored it before raising (the final-handoff ctor pattern)
            passed: Set[str] = set()
            if node is not None:
                for cf in self._facts(node).calls:
                    passed.update(cf.arg_names)
            for var, rec in held.items():
                if var in passed:
                    continue
                if rec.kind.name in self.owned_kinds:
                    self._leak(var, rec, "an unhandled exception path", node)

    def _leak(self, var: str, rec: _Rec, where: str, node) -> None:
        at = getattr(node, "lineno", rec.line)
        self._emit(
            self.p.leak_rule,
            rec.line,
            f"{rec.kind.name}:{var}",
            f"`{var}` ({rec.kind.name}) acquired here escapes via {where} "
            f"(line {at}) without the paired {rec.kind.release_hint}",
        )

    def _emit(self, rule: str, line: int, key: str, msg: str) -> None:
        sig = (rule, self.func.label, line, key)
        if sig in self._emitted:
            return
        self._emitted.add(sig)
        self.findings.append(
            Finding(
                rule=rule,
                path=self.func.module.path,
                line=line,
                message=msg,
                symbol=self.func.label,
                key=key,
            )
        )


class _ResourcePass:
    """Parameterized acquire/release engine: the resource pass proper
    runs it over :data:`KINDS`; sibling passes (``tracectx``) reuse the
    whole path-sensitive machinery with their own kind table and rule
    names."""

    def __init__(
        self,
        index: PackageIndex,
        *,
        kinds: Tuple[Kind, ...] = KINDS,
        leak_rule: str = "resource-leak",
        double_rule: str = "resource-double-release",
    ):
        self.index = index
        self.kinds = kinds
        self.leak_rule = leak_rule
        self.double_rule = double_rule
        self.stmt_facts: Dict[int, _StmtFacts] = {}

    def run(self) -> List[Finding]:
        out: List[Finding] = []
        for mod in self.index.modules.values():
            for func in mod.functions.values():
                w = _ResourceWalker(self, func)
                w.run(list(func.node.body))
                out.extend(w.findings)
        return out


def run(index: PackageIndex) -> List[Finding]:
    return _ResourcePass(index).run()
