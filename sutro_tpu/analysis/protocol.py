"""Wire-protocol additivity pass for the dp/elastic frame schema.

The dp coordinator/worker protocol (``engine/dphost.py``) is strictly
additive: old coordinators must parse frames from new workers and vice
versa across a resume boundary, so frame keys are only ever *added* —
removing or renaming one is a cross-version outage. This pass extracts
the send-side frame-key sets straight from the AST (dict literals with
a constant ``"t"`` discriminator, plus later ``msg["key"] = ...``
subscript augments on the same variable) and checks them against the
checked-in ``analysis/wire_schema.json``:

- ``wire-key-removed`` — a frame type or key present in the schema is
  no longer produced by any sender. Adding frames/keys is fine (run
  ``make lint-schema`` to fold them into the schema).
- ``wire-strict-parse`` — a recv path that rejects unknown keys or
  asserts an exact frame shape (``set(m) == {...}`` guards, or a raise
  on unrecognized keys while iterating the frame). Parsers must ignore
  what they don't understand.

Wire modules are recognized structurally: they define a ``_send``
function or their module name contains ``dphost``.
"""

from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import Dict, List, Optional, Set

from .callgraph import ModuleInfo, PackageIndex, dotted
from .core import Finding

DEFAULT_SCHEMA_PATH = Path(__file__).resolve().parent / "wire_schema.json"


def is_wire_module(mod: ModuleInfo) -> bool:
    return "dphost" in mod.name.rsplit(".", 1)[-1] or "_send" in mod.functions


def _literal_frame(node: ast.Dict) -> Optional[Dict[str, Set[str]]]:
    """``{"t": "res", ...}`` -> {"res": {const keys}}; None otherwise."""
    t_val: Optional[str] = None
    keys: Set[str] = set()
    for k, v in zip(node.keys, node.values):
        if k is None:  # **spread — dynamic extras are fine (additive)
            continue
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            continue
        keys.add(k.value)
        if k.value == "t" and isinstance(v, ast.Constant) and isinstance(
            v.value, str
        ):
            t_val = v.value
    if t_val is None:
        return None
    return {t_val: keys}


def extract_frames(index: PackageIndex) -> Dict[str, Set[str]]:
    """Union of send-side frame keys per frame type across all wire
    modules."""
    frames: Dict[str, Set[str]] = {}
    for mod in index.modules.values():
        if not is_wire_module(mod):
            continue
        # pass 1: dict literals carrying a constant "t"; remember which
        # variable (if any) each literal is assigned to, per function
        var_frame: Dict[int, Dict[str, str]] = {}  # id(scope) -> var -> t
        scopes = [mod.tree] + [f.node for f in mod.functions.values()]
        for scope in scopes:
            local = var_frame.setdefault(id(scope), {})
            for node in ast.walk(scope):
                if isinstance(node, ast.Dict):
                    lf = _literal_frame(node)
                    if lf:
                        for t, keys in lf.items():
                            frames.setdefault(t, set()).update(keys)
                for tgt_name, value in _assign_pairs(node):
                    if isinstance(value, ast.Dict):
                        lf = _literal_frame(value)
                        if lf:
                            local[tgt_name] = next(iter(lf))
        # pass 2: ``var["key"] = ...`` augments on frame-carrying vars
        for scope in scopes:
            local = var_frame.get(id(scope), {})
            if not local:
                continue
            for node in ast.walk(scope):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)
                ):
                    sub = node.targets[0]
                    if (
                        isinstance(sub.value, ast.Name)
                        and sub.value.id in local
                        and isinstance(sub.slice, ast.Constant)
                        and isinstance(sub.slice.value, str)
                    ):
                        frames.setdefault(local[sub.value.id], set()).add(
                            sub.slice.value
                        )
    return frames


def _assign_pairs(node: ast.AST):
    if isinstance(node, ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Name):
                yield t.id, node.value
    elif isinstance(node, ast.AnnAssign) and node.value is not None:
        if isinstance(node.target, ast.Name):
            yield node.target.id, node.value


def schema_as_json(frames: Dict[str, Set[str]]) -> Dict:
    return {
        "version": 1,
        "frames": {t: sorted(keys) for t, keys in sorted(frames.items())},
    }


def load_schema(path: Path = DEFAULT_SCHEMA_PATH) -> Optional[Dict]:
    if not path.exists():
        return None
    return json.loads(path.read_text(encoding="utf-8"))


def write_schema(
    index: PackageIndex, path: Path = DEFAULT_SCHEMA_PATH
) -> Dict:
    doc = schema_as_json(extract_frames(index))
    path.write_text(json.dumps(doc, indent=2) + "\n", encoding="utf-8")
    return doc


def _dict_shape_expr(node: ast.AST) -> bool:
    """``set(m)`` / ``sorted(m)`` / ``m.keys()`` / ``len(m)``-style
    frame-shape expressions."""
    if isinstance(node, ast.Call):
        t = dotted(node.func)
        if t in ("set", "sorted", "frozenset") and node.args:
            return isinstance(node.args[0], ast.Name)
        if t is not None and t.endswith(".keys"):
            return True
    return False


def _is_literal_collection(node: ast.AST) -> bool:
    return isinstance(
        node, (ast.Set, ast.List, ast.Tuple, ast.Dict, ast.Constant)
    )


def _strict_parse_findings(mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for func in mod.functions.values():
        for node in ast.walk(func.node):
            if isinstance(node, ast.Compare) and len(node.ops) == 1:
                if isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
                    l, r = node.left, node.comparators[0]
                    if (
                        _dict_shape_expr(l)
                        and _is_literal_collection(r)
                        or _dict_shape_expr(r)
                        and _is_literal_collection(l)
                    ):
                        out.append(
                            Finding(
                                rule="wire-strict-parse",
                                path=mod.path,
                                line=node.lineno,
                                message="frame shape compared against a "
                                "literal — parsers must tolerate unknown "
                                "keys (additive protocol)",
                                symbol=func.label,
                                key="shape-eq",
                            )
                        )
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if not _iterates_mapping(node):
                    continue
                for sub in ast.walk(node):
                    if (
                        isinstance(sub, ast.If)
                        and _is_notin_literal(sub.test)
                        and any(
                            isinstance(s, ast.Raise) for s in sub.body
                        )
                    ):
                        out.append(
                            Finding(
                                rule="wire-strict-parse",
                                path=mod.path,
                                line=sub.lineno,
                                message="raising on unrecognized frame "
                                "keys — parsers must ignore what they "
                                "don't understand (additive protocol)",
                                symbol=func.label,
                                key="unknown-key-raise",
                            )
                        )
    return out


def _iterates_mapping(node) -> bool:
    it = node.iter
    if isinstance(it, ast.Name):
        return True
    t = dotted(it.func) if isinstance(it, ast.Call) else None
    return t is not None and t.endswith(".keys")


def _is_notin_literal(test: ast.AST) -> bool:
    return (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], ast.NotIn)
        and _is_literal_collection(test.comparators[0])
    )


def run(
    index: PackageIndex, schema: Optional[Dict] = None
) -> List[Finding]:
    if schema is None:
        schema = load_schema()
    out: List[Finding] = []
    wire_mods = [m for m in index.modules.values() if is_wire_module(m)]
    if schema is not None and wire_mods:
        frames = extract_frames(index)
        anchor = wire_mods[0]
        for t, keys in sorted(schema.get("frames", {}).items()):
            have = frames.get(t)
            if have is None:
                out.append(
                    Finding(
                        rule="wire-key-removed",
                        path=anchor.path,
                        line=1,
                        message=f'frame type "{t}" is in wire_schema.json '
                        "but no sender produces it anymore — wire frames "
                        "are strictly additive",
                        symbol=anchor.name,
                        key=f"{t}",
                    )
                )
                continue
            for key in sorted(set(keys) - have):
                out.append(
                    Finding(
                        rule="wire-key-removed",
                        path=anchor.path,
                        line=1,
                        message=f'key "{key}" of frame "{t}" is in '
                        "wire_schema.json but no sender emits it anymore "
                        "— wire keys are strictly additive",
                        symbol=anchor.name,
                        key=f"{t}.{key}",
                    )
                )
    for mod in wire_mods:
        out.extend(_strict_parse_findings(mod))
    return out
