"""Trace-context pass: a started trace span must be ended on every path.

The forensics trace store (``telemetry/traces.py``) hands out ``Trace``
handles from ``start_trace``; a handle that is never ``end()``-ed (or
``end_trace``-d by id) leaves the trace permanently unfinished — it
still renders, but the doctor grades it ``insufficient_data``-adjacent
and the ring holds a request that looks in-flight forever. The naming
contract (documented on :mod:`..telemetry.traces`) makes this a
resource-lifecycle problem:

- acquire: ``tr = <anything>.start_trace(...)`` — *binding* the handle
  takes ownership of ending it in this function;
- release: ``tr.end(...)`` or ``<store>.end_trace(tr)``.

Sites that start and end a trace in *different* functions (the gateway
starts, ``finish()`` ends) use a BARE ``start_trace(...)`` call and key
the handoff by the trace_id string — the pass tracks bound handles
only, so cross-function propagation is clean by design.

Rule: ``trace-ctx-dropped``. The engine is the parameterized
acquire/release walker from :mod:`.resources` — same escape rules
(arg-pass, attribute/subscript store, closure capture, rebind, yield,
``is None`` refinement), same implicit-exception-edge gating (only
functions that end a trace somewhere get exception-path findings).

Fleet sub-pass (same rule, ``fleet-fwd:`` keys): over ``fleet/``
modules only, a function that binds a trace id from ``trace_begin``
(``tid = obs.trace_begin(...)`` — fleet/obs.py's router-ring variant,
which returns an ID STRING, not a handle, so the resource walker's
end() discipline doesn't apply) AND talks upstream must forward the id
— as a ``trace_id=`` keyword/argument to the upstream helper, or by
writing the ``X-Sutro-Trace`` header itself. A handler that opens a
router trace but relays without the header silently loses the replica
half of every cross-process stitch: the request still works, the
``GET /trace/{id}`` timeline just degrades to router-spans-only, which
is exactly the kind of quiet observability rot a linter should catch.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from .callgraph import PackageIndex, dotted
from .core import Finding
from .resources import Kind, _ResourcePass

TRACE_KINDS: Tuple[Kind, ...] = (
    Kind(
        name="trace-ctx",
        acquire_suffix=(".start_trace",),
        release_method=(".end",),
        release_arg=(".end_trace",),
        release_hint="end()/end_trace()",
    ),
)

#: a callee whose dotted text contains one of these talks to a replica
#: on behalf of the traced request (fleet/router.py `_upstream`)
_UPSTREAM_MARKERS = ("upstream",)
#: the wire header the id must travel in (frames/OBSERVABILITY.md)
_TRACE_HEADER = "X-Sutro-Trace"


def _fleet_forward_findings(index: PackageIndex) -> List[Finding]:
    out: List[Finding] = []
    for mod in index.modules.values():
        if "fleet/" not in mod.path:
            continue
        for func in mod.functions.values():
            node = func.node
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue
            # bound-name -> line of the trace_begin assignment; only
            # calls lexically in THIS function (nested defs are their
            # own FunctionInfo and get their own walk)
            begun: dict = {}
            upstream_calls: List[ast.Call] = []
            forwarded: set = set()
            own_nodes = [
                n
                for n in ast.walk(node)
                if not isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef)
                )
                or n is node
            ]
            for n in own_nodes:
                if isinstance(n, ast.Assign) and isinstance(
                    n.value, ast.Call
                ):
                    callee = dotted(n.value.func) or ""
                    if callee.split(".")[-1] == "trace_begin":
                        for tgt in n.targets:
                            if isinstance(tgt, ast.Name):
                                begun[tgt.id] = n.lineno
                if isinstance(n, ast.Call):
                    callee = dotted(n.func) or ""
                    if any(
                        m in callee.lower() for m in _UPSTREAM_MARKERS
                    ):
                        upstream_calls.append(n)
                    # forwarded via trace_id= keyword on ANY call (the
                    # upstream helper, a wrapped sender, gateway.submit)
                    for kw in n.keywords:
                        if kw.arg == "trace_id" and isinstance(
                            kw.value, ast.Name
                        ):
                            forwarded.add(kw.value.id)
                # forwarded by hand: headers["X-Sutro-Trace"] = tid
                if (
                    isinstance(n, ast.Assign)
                    and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Subscript)
                    and isinstance(n.value, ast.Name)
                ):
                    sl = n.targets[0].slice
                    if (
                        isinstance(sl, ast.Constant)
                        and sl.value == _TRACE_HEADER
                    ):
                        forwarded.add(n.value.id)
            if not upstream_calls:
                continue
            # positional pass into an upstream call also forwards
            for call in upstream_calls:
                for arg in call.args:
                    if isinstance(arg, ast.Name) and arg.id in begun:
                        forwarded.add(arg.id)
            for name, line in sorted(begun.items()):
                if name in forwarded:
                    continue
                out.append(
                    Finding(
                        rule="trace-ctx-dropped",
                        path=mod.path,
                        line=line,
                        symbol=func.qualname,
                        key=f"fleet-fwd:{name}",
                        message=(
                            f"'{name}' is bound from trace_begin() but "
                            "never forwarded to the upstream call "
                            f"(trace_id= / {_TRACE_HEADER} header) — "
                            "the replica half of the cross-process "
                            "stitch is silently lost"
                        ),
                    )
                )
    return out


def run(index: PackageIndex) -> List[Finding]:
    findings = _ResourcePass(
        index,
        kinds=TRACE_KINDS,
        leak_rule="trace-ctx-dropped",
        double_rule="trace-ctx-double-end",
    ).run()
    findings.extend(_fleet_forward_findings(index))
    return findings
