"""Trace-context pass: a started trace span must be ended on every path.

The forensics trace store (``telemetry/traces.py``) hands out ``Trace``
handles from ``start_trace``; a handle that is never ``end()``-ed (or
``end_trace``-d by id) leaves the trace permanently unfinished — it
still renders, but the doctor grades it ``insufficient_data``-adjacent
and the ring holds a request that looks in-flight forever. The naming
contract (documented on :mod:`..telemetry.traces`) makes this a
resource-lifecycle problem:

- acquire: ``tr = <anything>.start_trace(...)`` — *binding* the handle
  takes ownership of ending it in this function;
- release: ``tr.end(...)`` or ``<store>.end_trace(tr)``.

Sites that start and end a trace in *different* functions (the gateway
starts, ``finish()`` ends) use a BARE ``start_trace(...)`` call and key
the handoff by the trace_id string — the pass tracks bound handles
only, so cross-function propagation is clean by design.

Rule: ``trace-ctx-dropped``. The engine is the parameterized
acquire/release walker from :mod:`.resources` — same escape rules
(arg-pass, attribute/subscript store, closure capture, rebind, yield,
``is None`` refinement), same implicit-exception-edge gating (only
functions that end a trace somewhere get exception-path findings).
"""

from __future__ import annotations

from typing import List, Tuple

from .callgraph import PackageIndex
from .core import Finding
from .resources import Kind, _ResourcePass

TRACE_KINDS: Tuple[Kind, ...] = (
    Kind(
        name="trace-ctx",
        acquire_suffix=(".start_trace",),
        release_method=(".end",),
        release_arg=(".end_trace",),
        release_hint="end()/end_trace()",
    ),
)


def run(index: PackageIndex) -> List[Finding]:
    return _ResourcePass(
        index,
        kinds=TRACE_KINDS,
        leak_rule="trace-ctx-dropped",
        double_rule="trace-ctx-double-end",
    ).run()
