"""Thread, exception, and retry hygiene pass.

- ``thread-unjoined``        every ``threading.Thread(...)`` must be
                             ``daemon=True`` or have a ``.join(...)``
                             with a bounded timeout reachable in its
                             module (same variable/attribute name)
- ``thread-unbounded-join``  ``.join()`` on a thread without a timeout
                             wedges teardown forever on a hung thread
- ``silent-except``          ``except Exception:`` / bare ``except:``
                             whose body neither calls anything (no
                             logging), re-raises, nor stores the error
                             — the classic swallowed-failure shape
- ``unbounded-retry``        a retry loop (except-driven re-iteration
                             that sleeps or names attempts) must carry
                             BOTH an attempt/deadline bound and a
                             growing (non-constant) backoff sleep —
                             unbounded or lockstep retries turn one
                             transient fault into a hammering loop
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set

from .callgraph import ModuleInfo, PackageIndex, dotted
from .core import Finding


def _bool_kw(call: ast.Call, name: str) -> Optional[bool]:
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return None


def _join_sites(mod: ModuleInfo) -> Dict[str, List[ast.Call]]:
    """receiver text -> ``.join`` calls anywhere in the module."""
    out: Dict[str, List[ast.Call]] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        text = dotted(node.func)
        if not text or not text.endswith(".join"):
            continue
        recv = text[: -len(".join")]
        out.setdefault(recv, []).append(node)
    return out


def _join_is_bounded(call: ast.Call) -> bool:
    if call.args:
        return True  # positional timeout
    return any(kw.arg == "timeout" for kw in call.keywords)


def _thread_findings(index: PackageIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index.modules.values():
        joins = _join_sites(mod)
        # walk every assignment / expression statement for Thread ctors
        for node in ast.walk(mod.tree):
            call: Optional[ast.Call] = None
            target_text: Optional[str] = None
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                call = node.value
                if len(node.targets) == 1:
                    target_text = dotted(node.targets[0])
            elif isinstance(node, ast.Expr) and isinstance(
                node.value, ast.Call
            ):
                # threading.Thread(...).start() — anonymous spawn
                inner = node.value
                f = inner.func
                if (
                    isinstance(f, ast.Attribute)
                    and f.attr == "start"
                    and isinstance(f.value, ast.Call)
                ):
                    call = f.value
            if call is None:
                continue
            ctor = dotted(call.func)
            if not ctor or mod.expand(ctor) != "threading.Thread":
                continue
            daemon = _bool_kw(call, "daemon")
            if daemon:
                continue
            name_hint = target_text or "<anonymous>"
            # bounded join anywhere in the module under the same name?
            join_calls = joins.get(target_text or "", [])
            bounded = [c for c in join_calls if _join_is_bounded(c)]
            unbounded = [
                c for c in join_calls if not _join_is_bounded(c)
            ]
            if bounded:
                continue
            if unbounded:
                findings.append(
                    Finding(
                        rule="thread-unbounded-join",
                        path=mod.path,
                        line=unbounded[0].lineno,
                        symbol=f"{mod.name}:{name_hint}",
                        key=name_hint,
                        message=(
                            f"thread `{name_hint}` joined without a "
                            "timeout — a hung thread wedges teardown "
                            "forever"
                        ),
                    )
                )
                continue
            findings.append(
                Finding(
                    rule="thread-unjoined",
                    path=mod.path,
                    line=call.lineno,
                    symbol=f"{mod.name}:{name_hint}",
                    key=name_hint,
                    message=(
                        f"thread `{name_hint}` is neither daemon=True "
                        "nor joined with a bounded timeout"
                    ),
                )
            )
    return findings


_BROAD = {"Exception", "BaseException"}


def _is_silent_body(body: List[ast.stmt]) -> bool:
    """True when the handler body cannot observe/record the error: only
    pass/continue/break, constant-ish returns, or constant-ish
    assignments (no call, no raise, no exception-name use)."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return):
            if stmt.value is None or _constantish(stmt.value):
                continue
            return False
        if isinstance(stmt, ast.Assign):
            if _constantish(stmt.value):
                continue
            return False
        return False
    return True


def _constantish(node: ast.AST) -> bool:
    """Literal-shaped value: no calls, no name loads that could carry
    the error (plain names and literals allowed)."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Call, ast.Await, ast.Yield)):
            return False
    return True


def _except_findings(index: PackageIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index.modules.values():
        # map handler -> enclosing function for symbols
        enclosing: Dict[int, str] = {}
        for func in mod.functions.values():
            for sub in ast.walk(func.node):
                if isinstance(sub, ast.ExceptHandler):
                    # innermost function wins (walk order: outer first,
                    # later overwrites are the nested functions)
                    enclosing[id(sub)] = func.label
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            caught = "bare"
            if node.type is not None:
                text = dotted(node.type)
                if text is None or text.split(".")[-1] not in _BROAD:
                    continue
                caught = text.split(".")[-1]
            if not _is_silent_body(node.body):
                continue
            body_kind = type(node.body[0]).__name__ if node.body else ""
            findings.append(
                Finding(
                    rule="silent-except",
                    path=mod.path,
                    line=node.lineno,
                    symbol=enclosing.get(id(node), mod.name),
                    key=f"{caught}|{body_kind}",
                    message=(
                        f"broad `except {caught}` swallows the error "
                        "(no log, no re-raise, no classification)"
                    ),
                )
            )
    return findings


# -- unbounded-retry ---------------------------------------------------

_RETRYISH = re.compile(r"attempt|retr|tries|backoff", re.I)
_BOUNDISH = re.compile(
    r"attempt|retr|tries|deadline|budget|remaining|timeout", re.I
)

_LOOP_STOPS = (
    ast.While,
    ast.For,
    ast.FunctionDef,
    ast.AsyncFunctionDef,
    ast.Lambda,
)


def _shallow(nodes: List[ast.stmt]) -> Iterator[ast.AST]:
    """Walk a loop body WITHOUT descending into nested loops or
    function definitions (those are their own retry scopes)."""
    stack: List[ast.AST] = list(nodes)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _LOOP_STOPS):
                continue
            stack.append(child)


def _names_in(node: ast.AST) -> Iterator[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr


def _handler_reiterates(handler: ast.ExceptHandler) -> bool:
    """True when the except body leads to another loop iteration: it
    contains a ``continue``, or nothing that leaves the loop (no
    raise/return/break on every path is approximated as 'none present
    at all')."""
    kinds = [type(n) for n in _shallow(handler.body)]
    if ast.Continue in kinds:
        return True
    return not any(k in kinds for k in (ast.Raise, ast.Return, ast.Break))


def _guarded_exit(loop_nodes: List[ast.AST]) -> bool:
    """A bound expressed as control flow: an ``if`` whose test compares
    something attempt/deadline-ish (or reads the clock) and whose body
    leaves the loop (raise/break/return)."""
    for node in loop_nodes:
        if not isinstance(node, ast.If):
            continue
        test_names = list(_names_in(node.test))
        timeish = any(n in ("monotonic", "time") for n in test_names)
        boundish = any(_BOUNDISH.search(n) for n in test_names)
        if not (timeish or boundish):
            continue
        if any(
            isinstance(n, (ast.Raise, ast.Break, ast.Return))
            for n in _shallow(node.body)
        ):
            return True
    return False


def _sleep_calls(loop_nodes: List[ast.AST]) -> List[ast.Call]:
    out = []
    for node in loop_nodes:
        if isinstance(node, ast.Call):
            text = dotted(node.func)
            if text and text.split(".")[-1] == "sleep":
                out.append(node)
    return out


def _retry_findings(index: PackageIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index.modules.values():
        # loop -> enclosing function label (innermost wins: outer
        # functions are walked first, nested ones overwrite)
        enclosing: Dict[int, str] = {}
        for func in mod.functions.values():
            for sub in ast.walk(func.node):
                if isinstance(sub, (ast.While, ast.For)):
                    enclosing[id(sub)] = func.label
        loop_idx: Dict[str, int] = {}
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.While, ast.For)):
                continue
            symbol = enclosing.get(id(node), mod.name)
            idx = loop_idx.get(symbol, 0)
            loop_idx[symbol] = idx + 1
            body = list(_shallow(node.body))
            handlers = [
                h
                for t in body
                if isinstance(t, ast.Try)
                for h in t.handlers
            ]
            if not any(_handler_reiterates(h) for h in handlers):
                continue
            sleeps = _sleep_calls(body)
            # a RETRY loop (vs a service/poll loop): it sleeps between
            # attempts or names its iteration state attempt/retry-ish
            header = (
                node.target if isinstance(node, ast.For) else node.test
            )
            retryish = bool(sleeps) or (
                header is not None
                and any(_RETRYISH.search(n) for n in _names_in(header))
            )
            if not retryish:
                continue
            # bound: a for loop is finite; a while needs a non-constant
            # test or an explicit attempt/deadline exit guard
            if isinstance(node, ast.For):
                bounded = True
            else:
                test_const_true = (
                    isinstance(node.test, ast.Constant)
                    and bool(node.test.value)
                )
                bounded = not test_const_true or _guarded_exit(body)
            # backoff: at least one sleep with a NON-constant argument
            # (a growing delay); constant sleeps retry in lockstep
            backoff = any(
                c.args and not isinstance(c.args[0], ast.Constant)
                for c in sleeps
            )
            if bounded and backoff:
                continue
            aspect = "bound" if not bounded else "backoff"
            findings.append(
                Finding(
                    rule="unbounded-retry",
                    path=mod.path,
                    line=node.lineno,
                    symbol=symbol,
                    key=f"loop{idx}|{aspect}",
                    message=(
                        "retry loop has no attempt/deadline bound — a "
                        "permanent fault retries forever"
                        if not bounded
                        else "retry loop has no growing backoff sleep "
                        "— attempts hammer the faulted resource in "
                        "lockstep"
                    ),
                )
            )
    return findings


def run(index: PackageIndex) -> List[Finding]:
    return (
        _thread_findings(index)
        + _except_findings(index)
        + _retry_findings(index)
    )
