"""graftlint core: findings, suppressions, baseline, reports.

Engine-aware static analysis for this codebase (ISSUE 2). Three pass
families over a shared AST index:

- ``locks``    lock-order inversions, blocking calls under a lock,
               externally-supplied callbacks invoked under a lock,
               same-lock re-acquisition (non-reentrant deadlock)
- ``jitpure``  host-sync and nondeterminism inside jit/Pallas entry
               points; wall-clock/nondeterminism in the scheduler's
               decode window
- ``hygiene``  threads that are neither daemon nor joined with a
               bounded timeout; silently swallowed exceptions
- ``resources`` path-sensitive acquire/release lifecycle over the
               engine's refcounted resources (pages, pins, channels,
               sockets, threads, slots)
- ``protocol`` dp/elastic wire-frame additivity vs the checked-in
               ``wire_schema.json``; unknown-key-tolerant parsing
- ``killswitch`` env-gated subsystems must gate side-effecting calls
               behind their flag (taint-walked from the env read)
- ``cardinality`` telemetry label values vs declared fixed-cardinality
               series budgets
- ``tracectx`` a bound forensics trace handle (``start_trace``) must be
               ended on every function exit path

Findings are fingerprinted by (rule, path, enclosing symbol, stable
detail key) — NOT by line number — so unrelated edits don't invalidate
the baseline. The committed baseline (``baseline.json``) holds a count
per fingerprint; the gate fails only on findings *exceeding* their
baselined count. Inline suppression::

    something_flagged()  # graftlint: disable=lock-blocking-call

(on the finding's line or the line above; comma-separate several rules,
or ``disable=all``.)
"""

from __future__ import annotations

import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence

from .callgraph import PackageIndex

RULES = {
    "lock-order": "inconsistent pairwise lock acquisition order "
    "(deadlock risk)",
    "lock-reentrant": "non-reentrant lock re-acquired while already "
    "held on the same call path (self-deadlock)",
    "lock-blocking-call": "blocking call (I/O, sleep, join, socket) "
    "made while holding a lock",
    "lock-callback": "externally-supplied callback invoked while "
    "holding a lock",
    "jit-host-sync": "host-synchronizing operation reachable from a "
    "jit/Pallas entry point",
    "jit-nondeterminism": "wall clock or unseeded randomness inside a "
    "jit/Pallas entry point",
    "sched-nondeterminism": "wall clock or unseeded randomness in the "
    "scheduler decode window",
    "thread-unjoined": "thread is neither daemon nor joined",
    "thread-unbounded-join": "thread joined without a bounded timeout",
    "silent-except": "broad except swallows the exception without "
    "logging or re-raising",
    "unbounded-retry": "retry loop with no attempt/deadline bound or "
    "no (growing) backoff sleep between attempts",
    "resource-leak": "acquired resource (pages/pin/channel/socket/"
    "thread/slot) escapes a function exit path without its paired "
    "release",
    "resource-double-release": "resource released twice on one path "
    "(free-list / refcount corruption)",
    "wire-key-removed": "dp/elastic wire frame or key present in "
    "wire_schema.json is no longer produced (frames are strictly "
    "additive)",
    "wire-strict-parse": "frame parser rejects unknown keys instead "
    "of ignoring them (breaks protocol additivity)",
    "killswitch-ungated": "side-effecting call into an env-gated "
    "subsystem not dominated by its kill-switch flag check",
    "telemetry-cardinality": "metric label value outside the declared "
    "fixed-cardinality budget (or identifier-shaped)",
    "trace-ctx-dropped": "bound trace handle (start_trace) escapes a "
    "function exit path without end()/end_trace() — the trace stays "
    "unfinished in the forensics ring",
    "trace-ctx-double-end": "trace handle ended twice on one path",
    "shared-state-unlocked": "field reachable from two thread roots "
    "with a write that holds no lock and no happens-before edge "
    "(queue/event handoff, pre-start publication, bounded join)",
    "lockset-inconsistent": "field reachable from two thread roots "
    "whose accesses are each locked — but never by a common lock "
    "(empty lockset intersection)",
    "check-then-act": "value read from a field under a lock is used "
    "to write the field back after the lock was released and "
    "re-acquired (lost-update window)",
    "stale-suppression": "graftlint disable pragma that no longer "
    "masks any finding",
}

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\- ]+)"
)


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    symbol: str = ""
    key: str = ""
    fp: Optional[str] = None  # explicit fingerprint override

    def fingerprint(self) -> str:
        if self.fp is not None:
            return self.fp
        return f"{self.rule}|{self.path}|{self.symbol}|{self.key}"

    def to_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "fingerprint": self.fingerprint(),
        }

    def render(self) -> str:
        where = f" [in {self.symbol}]" if self.symbol else ""
        return (
            f"{self.path}:{self.line}: {self.rule}{where} {self.message}"
        )


def pragma_map(lines: Sequence[str]) -> Dict[int, List[str]]:
    """1-based line -> rule tokens of ``# graftlint: disable=`` pragmas.

    Comments only (via ``tokenize``): a pragma *example* inside a
    docstring neither suppresses nor counts as stale. Falls back to a
    per-line regex when tokenization fails (syntactically odd input).
    """
    import io
    import tokenize

    src = "\n".join(lines)
    out: Dict[int, List[str]] = {}

    def record(lineno: int, text: str) -> None:
        m = _SUPPRESS_RE.search(text)
        if m:
            toks = [r.strip() for r in m.group(1).split(",") if r.strip()]
            if toks:
                out.setdefault(lineno, []).extend(toks)

    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                record(tok.start[0], tok.string)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        out.clear()
        for i, text in enumerate(lines, start=1):
            record(i, text)
    return out


def _index_pragmas(index: PackageIndex) -> Dict[str, Dict[int, List[str]]]:
    cache = getattr(index, "_graftlint_pragmas", None)
    if cache is None:
        cache = {
            m.path: pragma_map(m.lines) for m in index.modules.values()
        }
        index._graftlint_pragmas = cache  # type: ignore[attr-defined]
    return cache


def apply_suppressions(
    index: PackageIndex, findings: Iterable[Finding]
) -> "tuple[List[Finding], List[Finding]]":
    """Split findings into (active, suppressed) per inline pragmas (on
    the finding's line or the line above)."""
    active: List[Finding] = []
    suppressed: List[Finding] = []
    pragmas = _index_pragmas(index)
    for f in findings:
        per_path = pragmas.get(f.path, {})
        rules: set = set()
        for ln in (f.line, f.line - 1):
            rules.update(per_path.get(ln, ()))
        if "all" in rules or f.rule in rules:
            suppressed.append(f)
        else:
            active.append(f)
    return active, suppressed


def stale_suppression_findings(
    index: PackageIndex, suppressed: Sequence[Finding]
) -> List[Finding]:
    """Pragmas whose rule tokens masked nothing: each becomes a
    ``stale-suppression`` finding (suppressions must earn their keep,
    or the next real finding at that site is silently eaten)."""
    used: set = set()  # (path, pragma_line, rule_token)
    pragmas = _index_pragmas(index)
    for f in suppressed:
        per_path = pragmas.get(f.path, {})
        for ln in (f.line, f.line - 1):
            toks = per_path.get(ln, ())
            if f.rule in toks:
                used.add((f.path, ln, f.rule))
            elif "all" in toks:
                used.add((f.path, ln, "all"))
    out: List[Finding] = []
    mod_names = {m.path: m.name for m in index.modules.values()}
    for path, per_path in pragmas.items():
        for line, toks in per_path.items():
            for tok in toks:
                if (path, line, tok) in used:
                    continue
                why = (
                    "unknown rule"
                    if tok != "all" and tok not in RULES
                    else "masks no finding"
                )
                out.append(
                    Finding(
                        rule="stale-suppression",
                        path=path,
                        line=line,
                        message=f"suppression `disable={tok}` {why} — "
                        "remove it (dead pragmas silently eat the next "
                        "real finding here)",
                        symbol=mod_names.get(path, path),
                        key=tok,
                    )
                )
    return out


# -- scanning ----------------------------------------------------------


def build_index(paths: Sequence[str]) -> PackageIndex:
    index = PackageIndex()
    files: List[Path] = []
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    seen = set()
    for f in files:
        if "__pycache__" in f.parts:
            continue
        rp = f.as_posix()
        if rp in seen:
            continue
        seen.add(rp)
        index.add_file(f, rp)
    return index


def run_passes(
    index: PackageIndex, rules: Optional[Sequence[str]] = None
) -> List[Finding]:
    from . import (
        cardinality,
        hygiene,
        jitpure,
        killswitch,
        locks,
        protocol,
        races,
        resources,
        tracectx,
    )

    findings: List[Finding] = []
    findings.extend(locks.run(index))
    findings.extend(jitpure.run(index))
    findings.extend(hygiene.run(index))
    findings.extend(resources.run(index))
    findings.extend(protocol.run(index))
    findings.extend(killswitch.run(index))
    findings.extend(cardinality.run(index))
    findings.extend(tracectx.run(index))
    findings.extend(races.run(index))
    if rules:
        keep = set(rules)
        findings = [f for f in findings if f.rule in keep]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    return findings


def analyze(
    paths: Sequence[str], rules: Optional[Sequence[str]] = None
) -> "tuple[List[Finding], List[Finding], PackageIndex]":
    """Scan ``paths``; returns (active, suppressed, index)."""
    index = build_index(paths)
    findings = run_passes(index, rules)
    active, suppressed = apply_suppressions(index, findings)
    stale = stale_suppression_findings(index, suppressed)
    if rules:
        stale = [f for f in stale if f.rule in set(rules)]
    active.extend(stale)
    active.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    return active, suppressed, index


# -- baseline ----------------------------------------------------------

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def baseline_counts(findings: Iterable[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.fingerprint()] = counts.get(f.fingerprint(), 0) + 1
    return counts


def write_baseline(path: Path, findings: Sequence[Finding]) -> None:
    payload = {
        "version": 1,
        "tool": "graftlint",
        "counts": dict(sorted(baseline_counts(findings).items())),
    }
    path.write_text(json.dumps(payload, indent=2) + "\n")


def load_baseline(path: Path) -> Dict[str, int]:
    data = json.loads(path.read_text())
    counts = data.get("counts", {})
    return {str(k): int(v) for k, v in counts.items()}


def compare_baseline(
    findings: Sequence[Finding], baseline: Dict[str, int]
) -> "tuple[List[Finding], Dict[str, int]]":
    """Returns (new_findings, stale) where ``new`` are findings beyond
    their baselined count and ``stale`` maps fingerprints whose current
    count dropped below baseline (fixed findings — regenerate)."""
    remaining = dict(baseline)
    new: List[Finding] = []
    for f in findings:
        fp = f.fingerprint()
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
        else:
            new.append(f)
    stale = {fp: n for fp, n in remaining.items() if n > 0}
    return new, stale


# -- reports -----------------------------------------------------------


def render_text(
    findings: Sequence[Finding],
    new: Optional[Sequence[Finding]] = None,
    stale: Optional[Dict[str, int]] = None,
    suppressed_count: int = 0,
) -> str:
    out: List[str] = []
    if new is None:
        for f in findings:
            out.append(f.render())
        out.append(
            f"graftlint: {len(findings)} finding(s), "
            f"{suppressed_count} suppressed"
        )
        return "\n".join(out)
    for f in new:
        out.append("NEW " + f.render())
    out.append(
        f"graftlint: {len(findings)} finding(s) "
        f"({len(new)} new vs baseline, {suppressed_count} suppressed)"
    )
    if stale:
        out.append(
            f"graftlint: {sum(stale.values())} baselined finding(s) no "
            "longer present — regenerate with --write-baseline"
        )
    return "\n".join(out)


def render_json(
    findings: Sequence[Finding],
    new: Optional[Sequence[Finding]] = None,
    stale: Optional[Dict[str, int]] = None,
    suppressed_count: int = 0,
) -> str:
    payload: Dict[str, object] = {
        "tool": "graftlint",
        "findings": [f.to_dict() for f in findings],
        "suppressed": suppressed_count,
    }
    if new is not None:
        payload["new"] = [f.to_dict() for f in new]
        payload["stale_baseline"] = stale or {}
    return json.dumps(payload, indent=2)


def render_sarif(findings: Sequence[Finding]) -> str:
    """SARIF 2.1.0 document for diff-annotation tooling (one run, one
    result per finding, fingerprints carried for dedupe)."""
    rules_seen = sorted({f.rule for f in findings})
    driver = {
        "name": "graftlint",
        "informationUri": "https://github.com/sutro-sh/sutro",
        "rules": [
            {
                "id": r,
                "shortDescription": {"text": RULES.get(r, r)},
            }
            for r in rules_seen
        ],
    }
    results = [
        {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "partialFingerprints": {
                "graftlint/v1": f.fingerprint()
            },
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {"startLine": max(1, f.line)},
                    }
                }
            ],
        }
        for f in findings
    ]
    doc = {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{"tool": {"driver": driver}, "results": results}],
    }
    return json.dumps(doc, indent=2)
